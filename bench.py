"""Benchmark entry point — run by the driver on real TPU hardware.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: ResNet-50 training throughput per chip (examples/sec/chip), the
BASELINE.md headline workload.  The reference publishes no numbers
(BASELINE.json "published": {}), so vs_baseline compares against the
locally recorded first-build number in BASELINE.md once it exists
(stored in BENCH_BASELINE.json); until then vs_baseline=1.0 by
definition.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models import resnet50
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

    n_dev = len(jax.devices())
    mesh = make_mesh({"dp": n_dev})

    batch_per_chip = int(os.environ.get("BENCH_BATCH_PER_CHIP", "64"))
    global_batch = batch_per_chip * n_dev
    rng = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(
            rng.rand(global_batch, 224, 224, 3).astype(np.float32)
        ),
        "label": jnp.asarray(rng.randint(0, 1000, size=(global_batch,))),
    }
    trainer = Trainer(
        resnet50(),
        TrainerConfig(optimizer="sgd", learning_rate=0.1, momentum=0.9),
        mesh,
        batchnorm_cross_entropy_loss,
        batch,
    )
    stats = trainer.benchmark(batch, steps=20, warmup=5)
    per_chip = stats["examples_per_sec"] / n_dev

    baseline_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    vs = 1.0
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f).get("resnet50_examples_per_sec_per_chip")
        if base:
            vs = per_chip / base

    print(
        json.dumps(
            {
                "metric": "resnet50_train_examples_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "examples/sec/chip",
                "vs_baseline": round(vs, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
