"""Benchmark entry point — run by the driver on real TPU hardware.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": ...}

Metric: ResNet-50 training throughput per chip (examples/sec/chip), the
BASELINE.md headline workload.  The reference publishes no numbers
(BASELINE.json "published": {}), so vs_baseline compares against the
round-1 locally recorded number pinned in BENCH_BASELINE.json.

Robustness contract (VERDICT round 1, item 1): TPU backend init on this
box can fail transiently (UNAVAILABLE) or hang.  The measurement
therefore runs in a CHILD process — retried with backoff on failure,
killed on hang — and an unrecoverable environment failure still emits
the single JSON line (with an "error" field) instead of a traceback.

Env knobs: BENCH_BATCH_PER_CHIP (default: autotune over 256/128/64),
BENCH_STEPS, BENCH_RETRIES, BENCH_CHILD_TIMEOUT, BENCH_PLATFORM
(e.g. cpu for a smoke run), BENCH_PEAK_TFLOPS (MFU denominator
override).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

METRIC = "resnet50_train_examples_per_sec_per_chip"
UNIT = "examples/sec/chip"


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _peak_flops(device) -> float:
    """Per-chip bf16 peak for MFU; overridable via BENCH_PEAK_TFLOPS."""

    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in (
        ("v6", 918e12),
        ("trillium", 918e12),
        ("v5p", 459e12),
        ("v5 lite", 197e12),
        ("v5e", 197e12),
        ("v5lite", 197e12),
        ("v4", 275e12),
    ):
        if key in kind:
            return peak
    return 197e12  # this box: v5 lite


def _step_flops(trainer, batch) -> float:
    """XLA's own flop count for the compiled train step (fwd+bwd+opt)."""

    try:
        import flax.linen as nn

        with trainer.mesh, nn.logical_axis_rules(trainer._rules):
            compiled = trainer._step.lower(trainer.state, batch).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))
    except Exception:
        return 0.0


def run_bench() -> dict:
    import jax

    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models import resnet50
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

    devices = jax.devices()
    n_dev = len(devices)
    mesh = make_mesh({"dp": n_dev})
    steps = int(os.environ.get("BENCH_STEPS", "20"))

    env_batch = os.environ.get("BENCH_BATCH_PER_CHIP")
    candidates = [int(env_batch)] if env_batch else [256, 128, 64]

    rng = np.random.RandomState(0)
    last_err: Exception | None = None
    for batch_per_chip in candidates:
        global_batch = batch_per_chip * n_dev
        # bf16 input pipeline: halves input HBM traffic vs the round-1
        # fp32 images; the model computes in bf16 anyway
        batch = {
            "image": jnp.asarray(
                rng.rand(global_batch, 224, 224, 3).astype(np.float32),
                dtype=jnp.bfloat16,
            ),
            "label": jnp.asarray(rng.randint(0, 1000, size=(global_batch,))),
        }
        try:
            trainer = Trainer(
                resnet50(),
                TrainerConfig(optimizer="sgd", learning_rate=0.1, momentum=0.9),
                mesh,
                batchnorm_cross_entropy_loss,
                batch,
            )
            sharded = trainer.shard_batch(batch)
            flops_per_step = _step_flops(trainer, sharded)
            stats = trainer.benchmark(batch, steps=steps, warmup=5)
        except Exception as e:  # OOM at this batch size → try smaller
            last_err = e
            if "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e):
                continue
            raise
        per_chip = stats["examples_per_sec"] / n_dev
        result = {
            "metric": METRIC,
            "value": round(per_chip, 2),
            "unit": UNIT,
            "vs_baseline": 1.0,
            "batch_per_chip": batch_per_chip,
            "step_ms": round(stats["step_ms"], 2),
            "platform": devices[0].platform,
            "device_kind": getattr(devices[0], "device_kind", "?"),
            "n_devices": n_dev,
        }
        if flops_per_step:
            # XLA cost_analysis reports the post-GSPMD per-device module,
            # so flops_per_step is already per-chip (verified empirically:
            # an 8-way dp-sharded matmul reports 1/8 the 1-device flops)
            achieved = flops_per_step * stats["steps_per_sec"]
            result["achieved_tflops_per_chip"] = round(achieved / 1e12, 1)
            result["mfu"] = round(achieved / _peak_flops(devices[0]), 4)
        # ---- input pipeline live (VERDICT r2 item 3): same train step
        # fed by the grain loader from disk — loading, sharding and
        # host→device transfer inside the measured window.  uint8 on
        # the wire, normalised on device.
        if os.environ.get("BENCH_PIPELINE", "1") == "1":
            try:
                from tf_operator_tpu.data import (
                    device_prefetch,
                    ensure_imagenet_like,
                    make_loader,
                )

                data_dir = os.environ.get(
                    "BENCH_DATA_DIR",
                    os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "examples", "data", "imagenet-like",
                    ),
                )
                ensure_imagenet_like(data_dir, n=512)
                loader = make_loader(
                    data_dir, global_batch, process_id=0, process_count=1,
                    num_epochs=None,
                )
                batches = device_prefetch(
                    loader,
                    trainer.batch_sharding,
                    image_dtype=jnp.bfloat16,
                    normalize_on_device=True,
                    prefetch=3,
                )
                pstats = trainer.benchmark_stream(
                    batches, steps=steps, warmup=3
                )
                result["pipeline_examples_per_sec_per_chip"] = round(
                    pstats["examples_per_sec"] / n_dev, 2
                )
                result["pipeline_step_ms"] = round(pstats["step_ms"], 2)
                if flops_per_step:
                    p_achieved = flops_per_step * pstats["steps_per_sec"]
                    result["pipeline_mfu"] = round(
                        p_achieved / _peak_flops(devices[0]), 4
                    )
            except Exception as e:  # pipeline must never sink the bench
                result["pipeline_error"] = f"{type(e).__name__}: {e}"[:200]
        return result
    raise RuntimeError(f"all batch sizes OOMed: {last_err}")


def _vs_baseline(value: float) -> float:
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    try:
        with open(path) as f:
            base = json.load(f).get("resnet50_examples_per_sec_per_chip")
        return round(value / base, 4) if base else 1.0
    except Exception:
        return 1.0


def main() -> int:
    if os.environ.get("_BENCH_CHILD") == "1":
        result = run_bench()
        _emit(result)
        return 0

    retries = int(os.environ.get("BENCH_RETRIES", "3"))
    child_timeout = float(os.environ.get("BENCH_CHILD_TIMEOUT", "1500"))
    delay = 10.0
    last_err = "unknown"
    for attempt in range(retries):
        env = dict(os.environ)
        env["_BENCH_CHILD"] = "1"
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env,
                capture_output=True,
                text=True,
                timeout=child_timeout,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            last_err = f"bench child hung >{child_timeout:.0f}s (TPU init stall?)"
            continue
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    result = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "value" in result:
                    result["vs_baseline"] = _vs_baseline(result["value"])
                    _emit(result)
                    return 0
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        last_err = (tail[-1] if tail else f"rc={proc.returncode}")[:300]
        if attempt < retries - 1:
            time.sleep(delay)
            delay *= 3
    # unrecoverable environment failure: still ONE parseable JSON line
    _emit(
        {
            "metric": METRIC,
            "value": 0.0,
            "unit": UNIT,
            "vs_baseline": 0.0,
            "error": last_err,
        }
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
