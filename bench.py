"""Benchmark entry point — run by the driver on real TPU hardware.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu_xla": ...,
   "mfu_analytic": ..., "llama_train_tokens_per_sec_per_chip": ..., ...}

Headline metric: ResNet-50 training throughput per chip
(examples/sec/chip).  Co-headline (VERDICT r3 item 3): llama-mini
train tokens/sec/chip + steady-state decode tokens/sec, measured in a
second child so the transformer stack (flash fwd+bwd, GQA, KV-cache
decode) reaches the driver's BENCH artifact.  The reference publishes
no numbers (BASELINE.json "published": {}), so vs_baseline compares
against the round-1 locally recorded number in BENCH_BASELINE.json.

Robustness contract (VERDICT r3 weak #1): the whole run is bounded by
BENCH_TOTAL_BUDGET seconds (default 1140 ≈ 19 min) enforced across all
children and retries — against the *driver's* clock, not our own.  The
first thing that runs is a cheap probe child with a short timeout, so a
dead TPU tunnel produces the fail-fast error JSON in ~2 minutes instead
of a driver-killed rc=124.  Every child is killed at
min(its own timeout, time left in the budget); the single JSON line is
emitted before the budget expires in every path.

MFU accounting (VERDICT r3 weak #2): two fields are reported.
`mfu_xla` uses XLA cost-analysis flops for the compiled fwd+bwd+update
step (hardware-utilization flavour; over-counts strided/dilated bwd
convs — see benchmarks/FLOPS.md).  `mfu_analytic` uses the standard
model-flops convention (3 × fwd flops, fwd verified against hand
conv-arithmetic in benchmarks/flops_audit.py) and is the honest
headline MFU.

Env knobs: BENCH_TOTAL_BUDGET, BENCH_BATCH_PER_CHIP (default: autotune
256/128/64), BENCH_STEPS, BENCH_RETRIES, BENCH_CHILD_TIMEOUT,
BENCH_LLAMA_TIMEOUT, BENCH_PROBE_TIMEOUT, BENCH_PLATFORM (e.g. cpu for
a smoke run), BENCH_PEAK_TFLOPS (MFU denominator override),
BENCH_PIPELINE=0, BENCH_LLAMA=0, BENCH_QUANT=0, BENCH_WIDE_DECODE=0 to
skip sections (wide decode also self-skips past
BENCH_WIDE_DECODE_CUTOFF seconds of llama-child elapsed, default 240).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

METRIC = "resnet50_train_examples_per_sec_per_chip"
UNIT = "examples/sec/chip"

# config-level platform override: this box's sitecustomize re-pins
# JAX_PLATFORMS to the TPU tunnel after process start, so env-level
# selection is NOT sufficient — jax.config wins (same reason
# tests/conftest.py overrides via jax.config).
_PROBE_SRC = (
    "import os, jax; "
    "p = os.environ.get('BENCH_PLATFORM'); "
    "p and jax.config.update('jax_platforms', p); "
    "import jax.numpy as jnp; "
    "x = jnp.ones((512, 512), jnp.bfloat16); "
    "print('probe ok', float((x @ x).sum()))"
)


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


#: the final stdout line must stay under this many bytes: the driver
#: captures a bounded tail, and r5's artifact was truncated mid-key
#: (BENCH_r05.json "parsed": null) when the whole last_measured ledger
#: rode the final line
FINAL_LINE_LIMIT = 2048

#: fields the compact fallback keeps when the headline line would
#: overflow FINAL_LINE_LIMIT — the driver-parsed contract plus MFU
_CORE_KEYS = (
    "metric", "value", "unit", "vs_baseline", "mfu", "mfu_xla",
    "mfu_analytic", "error", "batch_per_chip", "step_ms", "platform",
    "device_kind", "n_devices", "budget_left_s", "chip_lock",
)


def emit_final(result: dict) -> None:
    """Emit the run's record with the DRIVER CONTRACT enforced
    in-process (VERDICT r5 next #3): the `last_measured` ledger prints
    on its own line BEFORE the final line, and the FINAL stdout line is
    a compact headline JSON self-checked to parse and fit
    FINAL_LINE_LIMIT.  Five rounds of artifact fumbles end here: a
    violation of the contract raises in-process instead of shipping an
    unparseable artifact."""

    result = dict(result)
    last = result.pop("last_measured", None)
    if last:
        _emit({"last_measured": last})
    line = json.dumps(result)
    if len(line) >= FINAL_LINE_LIMIT:
        slim = {k: result[k] for k in _CORE_KEYS if k in result}
        dropped = sorted(set(result) - set(slim))
        # the dropped detail still reaches the artifact's tail text —
        # just upstream of the line the driver parses
        _emit({"final_line_overflow_dropped": dropped,
               **{k: result[k] for k in dropped}})
        line = json.dumps(slim)
    parsed = json.loads(line)  # self-check: the driver must parse this
    assert "value" in parsed and "metric" in parsed, parsed
    assert len(line) < FINAL_LINE_LIMIT, (len(line), FINAL_LINE_LIMIT)
    print(line, flush=True)


def _last_measured() -> dict | None:
    """The most recent REAL numbers (benchmarks/LAST_MEASURED.json,
    written by collect_window.py after every completed measurement
    window).  Attached to error JSON so a dead-tunnel run still points
    the reader at the latest measured values and their provenance
    instead of a bare value: 0.0 (VERDICT r4 next #9)."""

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks", "LAST_MEASURED.json",
    )
    try:
        with open(path) as f:
            ledger = json.load(f)
        return ledger or None
    except Exception:
        return None


def _peak_flops(device) -> float:
    """Per-chip bf16 peak for MFU; overridable via BENCH_PEAK_TFLOPS."""

    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in (
        ("v6", 918e12),
        ("trillium", 918e12),
        ("v5p", 459e12),
        ("v5 lite", 197e12),
        ("v5e", 197e12),
        ("v5lite", 197e12),
        ("v4", 275e12),
    ):
        if key in kind:
            return peak
    return 197e12  # this box: v5 lite


def _xla_flops(compiled) -> float:
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))
    except Exception:
        return 0.0


def _step_flops(trainer, batch) -> float:
    """XLA's own flop count for the compiled train step (fwd+bwd+opt)."""

    try:
        import flax.linen as nn

        with trainer.mesh, nn.logical_axis_rules(trainer._rules):
            compiled = trainer._step.lower(trainer.state, batch).compile()
        return _xla_flops(compiled)
    except Exception:
        return 0.0


def _fwd_flops(trainer, batch) -> float:
    """XLA flop count for the forward pass alone.  For plain (non-bwd)
    convs and matmuls XLA's count equals the analytic 2·MAC arithmetic
    (verified per-layer in benchmarks/flops_audit.py), so 3× this is
    the standard analytic fwd+bwd model-flops count."""

    try:
        import jax

        def fwd(params, model_state, images):
            variables = {"params": params, **model_state}
            return trainer.model.apply(variables, images, train=False).sum()

        with trainer.mesh:
            compiled = (
                jax.jit(fwd)
                .lower(
                    trainer.state.params,
                    trainer.state.model_state,
                    batch["image"],
                )
                .compile()
            )
        return _xla_flops(compiled)
    except Exception:
        return 0.0


def run_resnet() -> dict:
    import jax

    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models import resnet50
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

    devices = jax.devices()
    n_dev = len(devices)
    mesh = make_mesh({"dp": n_dev})
    steps = int(os.environ.get("BENCH_STEPS", "20"))

    env_batch = os.environ.get("BENCH_BATCH_PER_CHIP")
    candidates = [int(env_batch)] if env_batch else [256, 128, 64]

    rng = np.random.RandomState(0)
    last_err: Exception | None = None
    for batch_per_chip in candidates:
        global_batch = batch_per_chip * n_dev
        # bf16 input pipeline: halves input HBM traffic vs the round-1
        # fp32 images; the model computes in bf16 anyway
        batch = {
            "image": jnp.asarray(
                rng.rand(global_batch, 224, 224, 3).astype(np.float32),
                dtype=jnp.bfloat16,
            ),
            "label": jnp.asarray(rng.randint(0, 1000, size=(global_batch,))),
        }
        try:
            trainer = Trainer(
                resnet50(),
                TrainerConfig(optimizer="sgd", learning_rate=0.1, momentum=0.9),
                mesh,
                batchnorm_cross_entropy_loss,
                batch,
            )
            sharded = trainer.shard_batch(batch)
            flops_xla = _step_flops(trainer, sharded)
            flops_fwd = _fwd_flops(trainer, sharded)
            stats = trainer.benchmark(batch, steps=steps, warmup=5)
        except Exception as e:  # OOM at this batch size → try smaller
            last_err = e
            if "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e):
                continue
            raise
        per_chip = stats["examples_per_sec"] / n_dev
        result = {
            "metric": METRIC,
            "value": round(per_chip, 2),
            "unit": UNIT,
            "vs_baseline": 1.0,
            "batch_per_chip": batch_per_chip,
            "step_ms": round(stats["step_ms"], 2),
            "platform": devices[0].platform,
            "device_kind": getattr(devices[0], "device_kind", "?"),
            "n_devices": n_dev,
        }
        peak = _peak_flops(devices[0])
        if flops_xla:
            # XLA cost_analysis reports the post-GSPMD per-device module,
            # so flops are already per-chip (verified empirically: an
            # 8-way dp-sharded matmul reports 1/8 the 1-device flops)
            achieved = flops_xla * stats["steps_per_sec"]
            result["achieved_tflops_per_chip_xla"] = round(achieved / 1e12, 1)
            result["mfu_xla"] = round(achieved / peak, 4)
            # round-1/2 continuity: "mfu" was XLA-counted in BENCH_r01/r02
            result["mfu"] = result["mfu_xla"]
        if flops_fwd:
            analytic = 3.0 * flops_fwd  # fwd + dL/dx + dL/dw, model-flops
            a_achieved = analytic * stats["steps_per_sec"]
            result["flops_per_step_fwd_xla"] = round(flops_fwd / 1e9, 2)
            result["achieved_tflops_per_chip_analytic"] = round(
                a_achieved / 1e12, 1
            )
            result["mfu_analytic"] = round(a_achieved / peak, 4)
        if flops_xla and flops_fwd:
            result["xla_bwd_overcount"] = round(flops_xla / (3.0 * flops_fwd), 3)
        # ---- input pipeline live (VERDICT r2 item 3): same train step
        # fed by the grain loader from disk — loading, sharding and
        # host→device transfer inside the measured window.  uint8 on
        # the wire, normalised on device.
        if os.environ.get("BENCH_PIPELINE", "1") == "1":
            try:
                from tf_operator_tpu.data import (
                    device_prefetch,
                    ensure_imagenet_like,
                    make_loader,
                )

                data_dir = os.environ.get(
                    "BENCH_DATA_DIR",
                    os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "examples", "data", "imagenet-like",
                    ),
                )
                ensure_imagenet_like(data_dir, n=512)
                loader = make_loader(
                    data_dir, global_batch, process_id=0, process_count=1,
                    num_epochs=None,
                )
                batches = device_prefetch(
                    loader,
                    trainer.batch_sharding,
                    image_dtype=jnp.bfloat16,
                    normalize_on_device=True,
                    prefetch=3,
                )
                pstats = trainer.benchmark_stream(
                    batches, steps=steps, warmup=3
                )
                result["pipeline_examples_per_sec_per_chip"] = round(
                    pstats["examples_per_sec"] / n_dev, 2
                )
                result["pipeline_step_ms"] = round(pstats["step_ms"], 2)
                # host→device bandwidth probe: on a tunneled chip (this
                # box: axon) transfers ride the NETWORK, so the live-
                # pipeline number can be wire-bound rather than
                # framework-bound.  Reporting the measured h2d rate and
                # the wire bytes/step makes the artifact self-explaining.
                # Random payload (an all-zeros buffer is the best case
                # for any compressing transport); own try/except so a
                # probe hiccup can't wipe the pipeline fields below.
                try:
                    n_bytes = 16 * 10**6
                    buf = np.random.RandomState(1).randint(
                        0, 256, size=(n_bytes,), dtype=np.uint8
                    )
                    jax.device_put(buf).block_until_ready()  # warm the path
                    t0 = time.perf_counter()
                    jax.device_put(buf).block_until_ready()
                    h2d = n_bytes / 1e6 / (time.perf_counter() - t0)
                    result["h2d_mb_per_sec"] = round(h2d, 1)
                    result["pipeline_wire_mb_per_step"] = round(
                        global_batch * 224 * 224 * 3 / 1e6, 1
                    )
                except Exception as e:
                    result["h2d_probe_error"] = f"{type(e).__name__}: {e}"[:120]
                if flops_xla:
                    result["pipeline_mfu_xla"] = round(
                        flops_xla * pstats["steps_per_sec"] / peak, 4
                    )
                if flops_fwd:
                    result["pipeline_mfu_analytic"] = round(
                        3.0 * flops_fwd * pstats["steps_per_sec"] / peak, 4
                    )
            except Exception as e:  # pipeline must never sink the bench
                result["pipeline_error"] = f"{type(e).__name__}: {e}"[:200]
        return result
    raise RuntimeError(f"all batch sizes OOMed: {last_err}")


def _llama_analytic_flops_per_token(
    cfg, n_params_matmul: int, seq: int, window: int | None = None
) -> float:
    """Standard decoder-only model-flops per trained token: 6 flops per
    matmul parameter (fwd 2 + bwd 4) plus causal attention
    3 × (2·(QKᵀ) + 2·(AV)) flops/token over the average visible
    context — S/2 unwindowed; with a sliding window w the exact
    causal-banded average is w·(1 - (w-1)/(2S)) (rows below w see
    their full prefix), so windowed runs are scored on their USEFUL
    flops, not the full quadratic."""

    if window is None:
        avg_ctx = seq / 2.0
    else:
        w = min(window, seq)
        avg_ctx = w * (1.0 - (w - 1) / (2.0 * seq))
    d_total = cfg.n_heads * cfg.head_dim
    attn_fwd_per_token = 2 * 2 * avg_ctx * d_total * cfg.n_layers
    return 6.0 * n_params_matmul + 3.0 * attn_fwd_per_token


def encoder_analytic_flops_per_token(
    cfg, n_params_matmul: int, seq: int
) -> float:
    """Standard ENCODER model-flops per trained token (BERT-style,
    bidirectional): 6 flops per matmul parameter (fwd 2 + bwd 4) plus
    full — not causal — attention, 3 × (2·(QKᵀ) + 2·(AV)) flops/token
    over all S visible positions (a causal decoder averages S/2; an
    encoder's every token attends the whole sequence).  The BERT-base
    accounting behind BASELINE.md's bert mfu_analytic —
    benchmarks/FLOPS.md "BERT"."""

    d_total = cfg.n_heads * cfg.head_dim
    attn_fwd_per_token = 2 * 2 * seq * d_total * cfg.n_layers
    return 6.0 * n_params_matmul + 3.0 * attn_fwd_per_token


def llama_mini_config(seq: int, window: int | None = None):
    """The ~120M llama-mini benchmark config (RoPE + GQA 16q:4kv +
    SwiGLU) — ONE definition shared by bench.py, measure.py and
    benchmarks/profile_llama.py so the BENCH artifact and the sweeps
    can never measure different models under the same name."""

    from tf_operator_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=32000, hidden=1024, n_heads=16, head_dim=64,
        n_layers=8, mlp_dim=2816, max_len=seq, dropout=0.0,
        rope=True, attn_bias=False, n_kv_heads=4, window=window,
    )


def llama_wide_config(seq: int, window: int | None = None):
    """The ~700M wide-llama config (d_model 2048, 12 layers, GQA
    16q:8kv heads of 128, SwiGLU 5632) — the >=0.40-MFU existence-proof
    shape (VERDICT r4 next #3): llama-mini's d_model 1024 cannot fill
    the MXU's 128x128 tiles with enough arithmetic per weight byte;
    this width can.  Sized so adam fp32 state (~8.4 GB) + bf16
    activations at seq 2048 batch 2 (remat) fit one 16 GB v5e chip."""

    from tf_operator_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=32000, hidden=2048, n_heads=16, head_dim=128,
        n_layers=12, mlp_dim=5632, max_len=seq, dropout=0.0,
        rope=True, attn_bias=False, n_kv_heads=8, window=window,
    )


def matmul_param_count(params) -> int:
    """Matmul parameters for the analytic flop count: every >=2-d
    kernel except the embedding gather (llama's untied lm_head IS a
    matmul and is in the tree under its own name)."""

    import jax
    import numpy as np

    return sum(
        int(np.prod(p.shape))
        for path, p in jax.tree_util.tree_leaves_with_path(params)
        if len(p.shape) >= 2 and "embed" not in str(path).lower()
    )


def run_llama() -> dict:
    """llama-mini (~120M: RoPE + GQA 16q:4kv + SwiGLU) train tokens/s/chip
    + steady-state KV-cache decode tokens/s — the transformer co-headline
    (VERDICT r3 item 3).  Mirrors measure.py --section train's config so
    the BASELINE.md row and the BENCH artifact agree."""

    child_t0 = time.perf_counter()

    import jax

    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models import LlamaLM, llama_loss
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    devices = jax.devices()
    n_dev = len(devices)
    r = np.random.RandomState(0)
    seq = int(os.environ.get("BENCH_LLAMA_SEQ", "1024"))
    per_chip = int(os.environ.get("BENCH_LLAMA_BATCH", "8"))
    cfg = llama_mini_config(seq)
    lm = {
        "input_ids": jnp.asarray(
            r.randint(0, 32000, size=(per_chip * n_dev, seq)), jnp.int32
        )
    }
    trainer = Trainer(
        LlamaLM(cfg),
        TrainerConfig(learning_rate=1e-3),
        make_mesh({"fsdp": n_dev}),
        llama_loss,
        lm,
        init_args=(lm["input_ids"],),
        shardings="logical",
    )
    stats = trainer.benchmark(lm, steps=10, warmup=3)
    tokens_per_step_per_chip = per_chip * seq
    tps = stats["steps_per_sec"] * tokens_per_step_per_chip
    out = {
        "llama_train_tokens_per_sec_per_chip": round(tps, 1),
        "llama_step_ms": round(stats["step_ms"], 2),
        "llama_seq": seq,
        "llama_batch_per_chip": per_chip,
    }
    n_matmul = matmul_param_count(trainer.state.params)
    flops_tok = _llama_analytic_flops_per_token(cfg, n_matmul, seq)
    peak = _peak_flops(devices[0])
    out["llama_mfu_analytic"] = round(tps * flops_tok / peak, 4)
    flops_xla = _step_flops(trainer, trainer.shard_batch(lm))
    if flops_xla:
        out["llama_mfu_xla"] = round(
            flops_xla * stats["steps_per_sec"] / peak, 4
        )
    # steady-state greedy decode.  Slope timing (two windows, shared
    # with every decode row below): the fixed dispatch/RTT cost rides
    # on every call over this tunnel, so single-call timing both
    # understates tok/s and compresses any weights-dtype ratio toward
    # 1 (the fdad200 lesson — "the old one-window numbers were
    # dispatch-bound").
    def timed_decode(gen_fn, rows: int, n_new: int) -> float:
        np.asarray(gen_fn())  # compile + settle

        def window(k):
            for _ in range(k):
                res = gen_fn()
            np.asarray(res)
            return None

        t0 = time.perf_counter()
        window(1)
        t1 = time.perf_counter()
        window(3)
        t2 = time.perf_counter()
        dt = max(1e-9, ((t2 - t1) - (t1 - t0)) / 2)
        return rows * n_new / dt

    prompt = lm["input_ids"][:8, :16]
    rows = prompt.shape[0]  # may be < 8 on small smoke batches
    n_new = 64
    out["llama_decode_tokens_per_sec"] = round(
        timed_decode(
            lambda: trainer.generate(prompt, max_new_tokens=n_new),
            rows, n_new,
        ), 1,
    )
    from tf_operator_tpu.models import generate as raw_generate

    if os.environ.get("BENCH_QUANT", "1") != "0":
        # int8 weights-only decode (ops/quant.py): same greedy program
        # with the quantized tree — decode at batch 8 is weight-
        # bandwidth-bound, so int8 weights should approach 2x
        try:
            from tf_operator_tpu.ops.quant import quantize_tree

            qparams = quantize_tree(trainer.state.params)
            jit_gen = jax.jit(
                lambda q, ids: raw_generate(
                    trainer.model, q, ids, max_new_tokens=n_new
                )
            )
            out["llama_decode_int8_tokens_per_sec"] = round(
                timed_decode(lambda: jit_gen(qparams, prompt), rows, n_new),
                1,
            )
        except Exception as exc:  # measurement is additive, never fatal
            out["llama_decode_int8_error"] = repr(exc)[:200]
    if os.environ.get("BENCH_WIDE_DECODE", "1") != "0":
        # the int8 economics only show at width: mini's batch-8 decode
        # reads weights for ~60% of its step so int8 barely moves it,
        # while the ~700M wide model is squarely weight-bandwidth-bound
        # at batch 1 (PROFILE.md "int8 decode").  Put that ratio in the
        # driver artifact: batch-1 greedy, bf16-STORED weights vs int8
        # weights-only.  Guarded by the child's own elapsed clock so a
        # slow window loses only this section, never the rows above.
        elapsed = time.perf_counter() - child_t0
        cutoff = float(os.environ.get("BENCH_WIDE_DECODE_CUTOFF", "240"))
        if elapsed > cutoff:
            out["llama_wide_decode_error"] = (
                f"skipped: llama child at {elapsed:.0f}s, "
                f"cutoff {cutoff:.0f}s"
            )
            return out
        try:
            from tf_operator_tpu.models import LlamaLM as _LM
            from tf_operator_tpu.ops.quant import quantize_tree

            wcfg = llama_wide_config(256)
            wmodel = _LM(wcfg)
            wprompt = jnp.asarray(
                np.random.RandomState(1).randint(0, 32000, size=(1, 16)),
                jnp.int32,
            )
            wparams = wmodel.init(jax.random.PRNGKey(0), wprompt)["params"]
            # flax init stores f32; the honest baseline stores bf16 —
            # fp32-stored weights would double the baseline's HBM
            # traffic and overstate the int8 ratio
            wparams = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.bfloat16), wparams
            )
            wq = quantize_tree(wparams)
            n_new_w = 64

            def wide_tps(ps):
                fn = jax.jit(
                    lambda q, ids: raw_generate(
                        wmodel, q, ids, max_new_tokens=n_new_w
                    )
                )
                return timed_decode(lambda: fn(ps, wprompt), 1, n_new_w)

            bf16_tps = wide_tps(wparams)
            int8_tps = wide_tps(wq)
            out["llama_wide_decode_tokens_per_sec"] = round(bf16_tps, 1)
            out["llama_wide_decode_int8_tokens_per_sec"] = round(int8_tps, 1)
            out["llama_wide_decode_int8_speedup"] = round(
                int8_tps / bf16_tps, 2
            )
        except Exception as exc:  # additive, never fatal
            out["llama_wide_decode_error"] = repr(exc)[:200]
    return out


def _vs_baseline(value: float) -> float:
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    try:
        with open(path) as f:
            base = json.load(f).get("resnet50_examples_per_sec_per_chip")
        return round(value / base, 4) if base else 1.0
    except Exception:
        return 1.0


class _Budget:
    """The driver-clock wall: every child timeout is clamped to what is
    left, and `exhausted` leaves enough margin to emit the JSON line."""

    def __init__(self, total: float, margin: float = 10.0):
        self.deadline = time.monotonic() + total
        self.margin = margin

    def left(self) -> float:
        return self.deadline - time.monotonic() - self.margin

    def clamp(self, timeout: float) -> float:
        return max(1.0, min(timeout, self.left()))


def _run_child(kind: str, timeout: float) -> tuple[dict | None, str]:
    """Run one bench child; returns (parsed-json, error-string)."""

    env = dict(os.environ)
    env["_BENCH_CHILD"] = kind
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"{kind} child hung >{timeout:.0f}s (TPU stall?)"
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), ""
            except json.JSONDecodeError:
                continue
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return None, (tail[-1] if tail else f"{kind} rc={proc.returncode}")[:300]


def _probe(budget: _Budget) -> str:
    """Fast tunnel-liveness gate: a 2-minute matmul child, retried at
    most BENCH_PROBE_RETRIES times (default 2) so a dead tunnel yields
    the fail-fast error JSON in ~2-4 minutes instead of burning the
    whole budget on a deterministic failure.  Returns "" when the
    device answers."""

    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
    retries = int(os.environ.get("BENCH_PROBE_RETRIES", "2"))
    cmd = [sys.executable, "-c", _PROBE_SRC]
    err = "probe never ran"
    for attempt in range(retries):
        if budget.left() < 30:
            break
        try:
            proc = subprocess.run(
                cmd, env=dict(os.environ), capture_output=True, text=True,
                timeout=budget.clamp(probe_timeout),
            )
            if proc.returncode == 0:
                return ""
            tail = (proc.stderr or "").strip().splitlines()
            err = f"probe rc={proc.returncode}: " + (tail[-1] if tail else "")[:200]
        except subprocess.TimeoutExpired:
            err = "probe hung: TPU tunnel not answering"
        if attempt < retries - 1 and budget.left() > 60:
            time.sleep(10)
    return err


def main() -> int:
    kind = os.environ.get("_BENCH_CHILD")
    if kind in ("1", "resnet"):
        _emit(run_resnet())
        return 0
    if kind == "llama":
        _emit(run_llama())
        return 0

    budget = _Budget(float(os.environ.get("BENCH_TOTAL_BUDGET", "1140")))
    retries = int(os.environ.get("BENCH_RETRIES", "2"))
    child_timeout = float(os.environ.get("BENCH_CHILD_TIMEOUT", "600"))
    llama_timeout = float(os.environ.get("BENCH_LLAMA_TIMEOUT", "420"))

    # The axon tunnel serves one claimant at a time; our own watcher /
    # measurement window coordinate through an advisory chip lock.  The
    # driver's bench run is the highest-priority consumer: evict any
    # in-repo holder so a stale window can never stall the children
    # (benchmarks/chiplock.py has the round-4 incident writeup).
    lock_note = ""
    if os.environ.get("TPU_CHIP_LOCK_INHERITED") == "1":
        lock_note = "running under parent's chip claim"
    else:
        try:
            import importlib.util

            _spec = importlib.util.spec_from_file_location(
                "tf_operator_tpu_chiplock",
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "benchmarks", "chiplock.py",
                ),
            )
            _mod = importlib.util.module_from_spec(_spec)
            _spec.loader.exec_module(_mod)
            _lock = _mod.ChipLock("bench")
            lock_note = _lock.acquire_or_preempt()
        except Exception as e:  # the lock must never be able to fail the bench
            lock_note = f"chiplock unavailable: {type(e).__name__}"

    probe_err = _probe(budget)
    if probe_err:
        last = _last_measured()
        emit_final(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": UNIT,
                "vs_baseline": 0.0,
                "error": probe_err,
                **({"last_measured": last} if last else {}),
                **({"chip_lock": lock_note} if lock_note else {}),
            }
        )
        return 0

    result: dict | None = None
    last_err = "unknown"
    for attempt in range(retries):
        if budget.left() < 90:
            last_err = f"budget exhausted before attempt {attempt + 1}: {last_err}"
            break
        child, err = _run_child("resnet", budget.clamp(child_timeout))
        if child and "value" in child:
            result = child
            break
        last_err = err or "resnet child returned no JSON"
        if attempt < retries - 1 and budget.left() > 120:
            time.sleep(10)

    if result is None:
        last = _last_measured()
        emit_final(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": UNIT,
                "vs_baseline": 0.0,
                "error": last_err,
                **({"last_measured": last} if last else {}),
                **({"chip_lock": lock_note} if lock_note else {}),
            }
        )
        return 0

    result["vs_baseline"] = _vs_baseline(result["value"])
    if os.environ.get("BENCH_LLAMA", "1") == "1" and budget.left() > 60:
        llama, err = _run_child("llama", budget.clamp(llama_timeout))
        if llama:
            result.update(llama)
        else:
            result["llama_error"] = err
    elif os.environ.get("BENCH_LLAMA", "1") == "1":
        result["llama_error"] = "skipped: total budget exhausted"
    result["budget_left_s"] = round(max(0.0, budget.left()), 1)
    if lock_note:
        result["chip_lock"] = lock_note
    # The driver artifact is the round's perf record; the live children
    # above only re-measure the headline + llama co-headline within the
    # budget.  Attach the measurement-window ledger (wide-MFU existence
    # proof, mnist/BERT, flash/window gates, batching, speculative —
    # each stamped with its window artifact + date) so BENCH_rN carries
    # the full field set even though those rows are too slow to re-run
    # inside the bench budget.  Same ledger the error paths attach.
    last = _last_measured()
    if last:
        result["last_measured"] = last
    emit_final(result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
