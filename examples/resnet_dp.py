"""ResNet data-parallel training — BASELINE.md configs 2 and 4.

Parity: the reference's ResNet-50/ImageNet examples — config 2
(MultiWorkerMirroredStrategy, 4 GPU workers) and config 4 (Horovod+NCCL
all-reduce, 8 workers, volcano gang-sched).  Both are the same
computation: synchronous data-parallel SGD with gradient all-reduce.
The TPU-native shape is one jitted SPMD train step over a global ``dp``
(optionally ``fsdp``) mesh; XLA inserts the all-reduce over ICI where
MultiWorkerMirrored/Horovod issued NCCL calls (SURVEY.md §2b/§2c).
Gang scheduling is the operator's job (enableGangScheduling in the
manifest), not this script's.

Runs single-process (the real chip) or multi-process under the
operator's local backend (CPU collectives); model size and batch are
flags so the same script is the TPU benchmark and the CPU e2e workload.
"""

from __future__ import annotations

import sys

from tf_operator_tpu.runtime import initialize
from tf_operator_tpu.runtime.harness import standard_parser, train_loop


def main() -> int:
    parser = standard_parser(__doc__.split("\n")[0])
    parser.add_argument("--model", choices=["resnet50", "resnet18"], default="resnet50")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--fsdp", type=int, default=1, help="fsdp axis size")
    args = parser.parse_args()

    initialize()

    import jax
    import numpy as np

    from tf_operator_tpu.models import resnet18, resnet50
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

    n_dev = len(jax.devices())
    assert n_dev % args.fsdp == 0, (n_dev, args.fsdp)
    mesh = make_mesh({"dp": n_dev // args.fsdp, "fsdp": args.fsdp})

    from tf_operator_tpu.runtime.harness import batch_sizes

    _, local_batch = batch_sizes(args.batch_per_device)
    rng = np.random.RandomState(jax.process_index())
    batch = {
        "image": rng.rand(local_batch, args.image_size, args.image_size, 3).astype(
            np.float32
        ),
        "label": rng.randint(0, args.num_classes, size=(local_batch,)).astype(
            np.int32
        ),
    }

    model_fn = resnet50 if args.model == "resnet50" else resnet18
    trainer = Trainer(
        model_fn(num_classes=args.num_classes),
        TrainerConfig(optimizer="sgd", learning_rate=args.learning_rate),
        mesh,
        batchnorm_cross_entropy_loss,
        batch,
    )
    sharded = trainer.shard_batch(batch)
    tag = f"{args.model} dp={mesh.shape['dp']} fsdp={mesh.shape['fsdp']}"
    train_loop(
        trainer, sharded, args.steps, tag=tag,
        steps_per_sync=args.steps_per_sync,
    )
    stats = trainer.benchmark(batch, steps=max(args.steps // 2, 5), warmup=0)
    print(f"{tag}: {stats['examples_per_sec']:.1f} ex/s global", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
