"""ResNet data-parallel training — BASELINE.md configs 2 and 4.

Parity: the reference's ResNet-50/ImageNet examples — config 2
(MultiWorkerMirroredStrategy, 4 GPU workers) and config 4 (Horovod+NCCL
all-reduce, 8 workers, volcano gang-sched).  Both are the same
computation: synchronous data-parallel SGD with gradient all-reduce.
The TPU-native shape is one jitted SPMD train step over a global ``dp``
(optionally ``fsdp``) mesh; XLA inserts the all-reduce over ICI where
MultiWorkerMirrored/Horovod issued NCCL calls (SURVEY.md §2b/§2c).
Gang scheduling is the operator's job (enableGangScheduling in the
manifest), not this script's.

Runs single-process (the real chip) or multi-process under the
operator's local backend (CPU collectives); model size and batch are
flags so the same script is the TPU benchmark and the CPU e2e workload.
"""

from __future__ import annotations

import argparse
import sys

from tf_operator_tpu.runtime import initialize


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--model", choices=["resnet50", "resnet18"], default="resnet50")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--batch-per-device", type=int, default=32)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--learning-rate", type=float, default=0.1)
    parser.add_argument("--fsdp", type=int, default=1, help="fsdp axis size")
    args = parser.parse_args()

    initialize()

    import jax
    import numpy as np

    from tf_operator_tpu.models import resnet18, resnet50
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

    n_dev = len(jax.devices())
    assert n_dev % args.fsdp == 0, (n_dev, args.fsdp)
    mesh = make_mesh({"dp": n_dev // args.fsdp, "fsdp": args.fsdp})

    global_batch = args.batch_per_device * n_dev
    local_batch = global_batch // jax.process_count()
    rng = np.random.RandomState(jax.process_index())
    batch = {
        "image": rng.rand(local_batch, args.image_size, args.image_size, 3).astype(
            np.float32
        ),
        "label": rng.randint(0, args.num_classes, size=(local_batch,)).astype(
            np.int32
        ),
    }

    model_fn = resnet50 if args.model == "resnet50" else resnet18
    trainer = Trainer(
        model_fn(num_classes=args.num_classes),
        TrainerConfig(optimizer="sgd", learning_rate=args.learning_rate),
        mesh,
        batchnorm_cross_entropy_loss,
        batch,
    )
    sharded = trainer.shard_batch(batch)

    losses = []
    for _ in range(args.steps):
        metrics = trainer.train_step(sharded)
        losses.append(float(metrics["loss"]))
    stats = trainer.benchmark(batch, steps=max(args.steps // 2, 5), warmup=0)

    print(
        f"process {jax.process_index()}/{jax.process_count()}: "
        f"{args.model} dp={mesh.shape['dp']} fsdp={mesh.shape['fsdp']} "
        f"loss {losses[0]:.4f} -> {losses[-1]:.4f} "
        f"({stats['examples_per_sec']:.1f} ex/s global)",
        flush=True,
    )
    if args.steps >= 20 and not losses[-1] < losses[0]:
        print("loss did not decrease", file=sys.stderr, flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
