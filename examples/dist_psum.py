"""Minimal distributed workload: proves collective bootstrap end-to-end.

The smallest TPU-native analogue of the reference's dist-mnist smoke
(SURVEY.md §3.3): each replica joins via the injected env, runs a global
allgather + psum across processes, asserts the result, exits 0.
"""

import sys

from tf_operator_tpu.runtime import initialize


def main() -> int:
    ctx = initialize()
    import jax
    import jax.numpy as jnp
    from jax.experimental.multihost_utils import process_allgather

    n = jax.process_count()
    pid = jax.process_index()
    if ctx is not None:
        assert pid == ctx.process_id, (pid, ctx.process_id)
        assert n == ctx.num_processes, (n, ctx.num_processes)

    gathered = process_allgather(jnp.array([float(pid)]))
    expected = [[float(i)] for i in range(n)]
    assert gathered.tolist() == expected, (gathered.tolist(), expected)
    print(f"process {pid}/{n}: allgather ok -> {gathered.ravel().tolist()}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
