"""T5 multi-host training — BASELINE.md config 5.

Parity: the reference's config 5 is "T5-base JAX/Flax multi-host via
jax.distributed on a v5e-16 slice" — the one config that was already
TPU-shaped.  Here it is first-class: the operator injects the
coordinator env, every replica joins one jax.distributed world, and the
model trains over a ``dp × tp`` mesh using the transformer family's
logical-axis shardings (megatron tensor parallelism on tp, data
parallelism on dp), with XLA collectives over ICI within a slice.

--model t5_base on real slices; t5_tiny for CPU e2e under the operator.
"""

from __future__ import annotations

import sys

from tf_operator_tpu.runtime import initialize
from tf_operator_tpu.runtime.harness import standard_parser, train_loop


def synthetic_seq2seq_batch(rng, n: int, enc_len: int, dec_len: int, vocab: int):
    import numpy as np

    r = np.random.RandomState(rng)
    return {
        "encoder_ids": r.randint(2, vocab, size=(n, enc_len)).astype(np.int32),
        "decoder_ids": r.randint(2, vocab, size=(n, dec_len)).astype(np.int32),
        "targets": r.randint(2, vocab, size=(n, dec_len)).astype(np.int32),
    }


def main() -> int:
    parser = standard_parser(
        __doc__.split("\n")[0], batch_per_device=4, learning_rate=1e-4
    )
    parser.add_argument("--model", choices=["t5_base", "t5_tiny"], default="t5_base")
    parser.add_argument("--enc-len", type=int, default=64)
    parser.add_argument("--dec-len", type=int, default=32)
    parser.add_argument("--tp", type=int, default=1, help="tensor-parallel axis size")
    args = parser.parse_args()

    initialize()

    import jax

    from tf_operator_tpu.models import seq2seq_loss, t5_base, t5_tiny
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    n_dev = len(jax.devices())
    assert n_dev % args.tp == 0, (n_dev, args.tp)
    mesh = make_mesh({"dp": n_dev // args.tp, "tp": args.tp})

    if args.model == "t5_base":
        model, vocab = t5_base(mesh=mesh), 32128
    else:
        model, vocab = t5_tiny(mesh=mesh), 1024

    # with tp in the mesh the batch axis replicates across tp devices,
    # so every process builds the IDENTICAL global batch (same seed) and
    # shard_global_batch hands each device exactly its slice — replicas
    # stay bit-identical, as XLA's collectives require
    dp_total = mesh.shape["dp"]
    global_batch = max(args.batch_per_device * dp_total, dp_total)
    batch = synthetic_seq2seq_batch(
        0, global_batch, args.enc_len, args.dec_len, vocab
    )

    trainer = Trainer(
        model,
        TrainerConfig(learning_rate=args.learning_rate, warmup_steps=10),
        mesh,
        seq2seq_loss,
        batch,
        init_args=(batch["encoder_ids"], batch["decoder_ids"]),
        shardings="logical",
    )
    sharded = trainer.shard_global_batch(batch)
    train_loop(
        trainer, sharded, args.steps,
        tag=f"{args.model} dp={mesh.shape['dp']} tp={mesh.shape['tp']}",
        steps_per_sync=args.steps_per_sync,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
