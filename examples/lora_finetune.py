"""LoRA fine-tuning: adapt a pretrained artifact with rank-r adapters,
train ONLY the adapters, export a merged serving artifact.

The classic deployment story the reference (SURVEY.md §0) never had:
the base checkpoint is shared and frozen; each task trains a few
hundred KB of adapters (models/lora.py merges them into the dense
kernels INSIDE the jitted step, so the hot matmuls stay pure MXU ops);
`--export-dir` bakes the adapters back in and writes a standard
artifact that every serving path accepts (serve_lm, int8 quantization,
continuous batching, speculative decode).

    # 1) pretrain a base artifact
    python examples/llama_pretrain.py --steps 60 --export-dir /tmp/base
    # 2) LoRA-finetune it on a different corpus slice
    python examples/lora_finetune.py --base /tmp/base --steps 40 \
        --rank 8 --export-dir /tmp/tuned
    # 3) serve the tuned artifact
    python examples/serve_lm.py --artifact /tmp/tuned --port 8600
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--base", required=True, help="export_params artifact dir")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--alpha", type=float, default=16.0)
    ap.add_argument("--learning-rate", type=float, default=1e-3)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--data-dir", default="examples/data/text-lora")
    ap.add_argument("--data-seed", type=int, default=7,
                    help="corpus seed != pretraining's so the adapters "
                         "have something new to learn")
    ap.add_argument("--export-dir", default="")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.data.text import decode_bytes, ensure_text, make_text_loader
    from tf_operator_tpu.models import generate, llama_loss
    from tf_operator_tpu.models.lora import LoraModel
    from tf_operator_tpu.models.registry import model_from_description
    from tf_operator_tpu.parallel import (
        Trainer,
        TrainerConfig,
        load_model_description,
        load_params,
        make_mesh,
    )

    desc = load_model_description(args.base)
    if desc is None:
        raise SystemExit(
            f"{args.base} has no model.json — re-export the base with a "
            "current export_params"
        )
    model = model_from_description(desc)
    base_params = load_params(args.base)
    print(f"base: family={desc['family']} from {args.base}", flush=True)

    n_dev = len(jax.devices())
    mesh = make_mesh({"dp": n_dev})
    ensure_text(args.data_dir, seq_len=args.seq_len, seed=args.data_seed)
    loader = make_text_loader(
        args.data_dir, args.batch * n_dev, process_id=0, process_count=1,
        num_epochs=None,
    )
    it = iter(loader)

    def next_batch():
        ids = np.asarray(next(it)["input_ids"], np.int32)
        return {"input_ids": jnp.asarray(ids[:, : args.seq_len])}

    example = next_batch()
    lora = LoraModel(
        model, base_params, rank=args.rank, alpha=args.alpha
    )
    trainer = Trainer(
        lora,
        TrainerConfig(learning_rate=args.learning_rate),
        mesh,
        llama_loss,
        example,
        init_args=(example["input_ids"],),
        shardings="fsdp",
    )
    n_adapter = sum(
        x.size for x in jax.tree_util.tree_leaves(trainer.state.params)
    )
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(base_params))
    print(
        f"training {n_adapter:,} adapter params over a frozen "
        f"{n_base:,}-param base ({n_adapter / n_base:.2%})",
        flush=True,
    )
    for step in range(args.steps):
        m = trainer.train_step(trainer.shard_batch(next_batch()))
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:4d} loss {float(m['loss']):.4f}", flush=True)

    merged = lora.merged_params(trainer.state.params)
    prompt = jnp.asarray(
        np.frombuffer(b"the operator ", np.uint8).astype(np.int32)[None]
    )
    out = generate(model, merged, prompt, max_new_tokens=32)
    print("sample:", repr(decode_bytes(np.asarray(out[0, prompt.shape[1]:]))))

    if args.export_dir:
        # export the MERGED tree as a standard self-describing artifact
        from tf_operator_tpu.parallel.checkpoint import export_merged_params

        export_merged_params(model, merged, args.export_dir)
        print(f"exported merged artifact to {args.export_dir}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
