"""Minimal LM serving: load an exported artifact, answer /generate.

The last leg of the train → export → serve journey
(examples/llama_pretrain.py trains; parallel/checkpoint.py's
export_params writes the artifact this loads).  Deliberately tiny —
stdlib HTTP in front of the jitted KV-cache decoder — because the
framework's serving primitives (models/decode.py, GQA-width cache, one
XLA program per shape) do the actual work.

    python examples/serve_lm.py --artifact /path/to/export --port 8600
    curl -s localhost:8600/generate -d '{"prompt": "the sharded ", "max_new_tokens": 32}'
    curl -s localhost:8600/metrics   # Prometheus text: requests by
                                     # status, latency histogram,
                                     # tokens generated, mode gauges
    curl -s localhost:8600/slo       # JSON SLO quantile summaries
    curl -s localhost:8600/alerts    # burn-rate/threshold alert state
                                     # (utils/alerts.py, firing first)

Serving modes: `--batching SLOTS` multiplexes concurrent requests
through the continuous-batching pool (models/batching.py — one decode
loop, step-granular joins; PAGED by default since r11: block-granular
KV admission + shared prefix cache, `--kv-blocks`/`--kv-block-size`
size the arena); `--replicas N` runs N pool replicas behind one
admission queue (models/pool_router.py — per-replica gauges on
/metrics, merged quantiles on /slo); `--roles prefill=1,decode=2`
phase-splits the fleet (r15, ISSUE 13): prefill replicas chunk-prefill
and publish finished prompt blocks into the shared prefix-cache
fabric, decode replicas map the published chain (pulling only the
missing tail — migrate_in) and run the unchanged 1-dispatch/step
loop, and the two replica classes scale independently off
kv_blocks_pressure{role=}; `--quantize int8` halves HBM
weight traffic per decoded token (ops/quant.py); `--speculative`
(r18, ISSUE 18) speculates ON THE PAGED POOL: an int8 self-draft
pages its KV through the same block arena, K draft tokens verify in
one fused multi-query dispatch, accept/rollback happen in-graph, and
speculation is gated per SLO tier (interactive by default — see
--spec-tiers).  `--quantize` composes with either; `--speculative`
composes with `--batching` (and defaults to 4 slots when given
alone).

The jit-compile cache is bounded BY DESIGN (VERDICT r3 weak #5/next #9):
prompts prefill through the KV cache in power-of-2 chunks (binary
decomposition — exact semantics, no padding) and token budgets round up
to powers of two, so arbitrary request lengths share at most
~2·log2(max_len) prefill/decode programs
(models/decode.ChunkedServingDecoder).  Temperature is quantized to a
0.05 grid and top_k is validated/int-cast unconditionally, so no request
field can force unbounded fresh compiles.  Byte-level vocab (256) to
match the llama_pretrain artifact.

Observability (r6): every /generate request runs inside a server trace
span (adopting an incoming ``x-trace-id`` and echoing it on the
response — the PR-2 propagation contract), every decoder device
dispatch is counted and timed through a shared
``utils/metrics.DispatchLedger`` (``serving_dispatch_*`` on /metrics;
request-thread dispatches appear as ``dispatch.<phase>`` child spans in
the request waterfall), and ``/traces`` + ``/traces/<id>`` expose the
trace store like the operator API does.

Request autopsy (r13, ISSUE 11): every pool request carries a
first-class id (the trace id — adopted from ``x-trace-id`` when sent,
returned as ``request_id`` in the /generate body) and a complete
lifecycle: the pool emits ``queue.wait`` / ``admission`` /
``decode.window`` / ``retire`` spans on the request's trace (the
router adds ``route``), and ``GET /requests/<id>`` serves the
assembled record — timings, blocks reserved, prefix-hit depth,
per-request dispatch counts — from the bounded per-replica RequestLog
(``GET /requests`` lists recent ones, merged across replicas).
``GET /debug/arena`` serves the per-replica KV-arena occupancy
timeline (the time-series twin of kv_blocks_pressure), and
``GET /debug/profile?seconds=N`` wraps ``jax.profiler`` around the
live decode loop and returns the trace-artifact path (host-side only;
one profile at a time).

SLO tiers (r14, ISSUE 12): /generate accepts ``"tier":
"interactive"|"batch"`` (or the ``x-slo-tier`` header; default batch).
The paged pool admits by PRIORITY, not FIFO — interactive first, with
a bounded age boost so batch never starves — reserves KV budget
on-demand (admission commits prompt blocks + 1; decode blocks
allocate lazily at block boundaries), and under arena pressure
preempts batch seats mid-decode, swapping their KV to a host-side
arena and resuming them later token-identically.  Every TTFT /
time-per-output-token / queue-wait observation carries the {tier}
label, so ``/slo`` reports per-tier quantiles.

Honest speculation (r6, VERDICT r5 next #2): ``--speculative`` consults
the measured ledger (benchmarks/LAST_MEASURED.json).  If every measured
speculative configuration on this box is a slowdown (<1x), the server
REFUSES to start with the measured number and its artifact, instead of
silently serving 10x slower; ``--speculative-force`` overrides for
real-RTT deployments where the dispatch economics differ.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# the serving binary is launched standalone (`python examples/serve_lm.py`)
# more often than under the operator's PYTHONPATH injection
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def speculative_slowdown(ledger_path: "str | None" = None):
    """The measured speculative verdict from the last-measured ledger:
    ``(best_speedup, row)``, or ``(None, None)`` when nothing has been
    measured.  Since ISSUE 18 this reads the PAGED-PLANE row
    (``spec_paged_speedup`` — int8 self-draft in the shared block
    arena vs the non-speculative paged pool at the same arena, the
    configuration ``--speculative`` actually serves), NOT the dead
    pre-paged ``speculative_speedup``/``speculative_wide_speedup``
    rows: those measured the orphaned batch-1 SpeculativeDecoder and
    must not unfence (or fence) the pool path.  main() refuses
    --speculative when the best measured row is a slowdown — the 0.1x
    era must not be the feature's silent default face."""

    if ledger_path is None:
        ledger_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks", "LAST_MEASURED.json",
        )
    try:
        with open(ledger_path) as f:
            ledger = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None, None
    rows = {
        key: ledger[key]
        for key in ("spec_paged_speedup",)
        if isinstance(ledger.get(key), dict) and "value" in ledger[key]
    }
    if not rows:
        return None, None
    best_key = max(rows, key=lambda key: rows[key]["value"])
    row = dict(rows[best_key])
    row["metric"] = best_key
    return row["value"], row


def parse_roles(spec: str) -> "list[str]":
    """``--roles prefill=1,decode=2`` → ["prefill", "decode",
    "decode"] (ISSUE 13).  Roles come from
    models/batching.REPLICA_ROLES; a disaggregated spec (any prefill)
    must also declare at least one decode/unified replica."""

    roles: "list[str]" = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"--roles entries are role=count, got {part!r}"
            )
        role, _, count = part.partition("=")
        role = role.strip()
        if role not in ("prefill", "decode", "unified"):
            raise ValueError(
                f"unknown role {role!r} (prefill|decode|unified)"
            )
        try:
            n = int(count)
        except ValueError:
            raise ValueError(f"--roles count must be an int, got {count!r}")
        if n < 0:
            raise ValueError(f"--roles count must be >= 0, got {n}")
        roles.extend([role] * n)
    if not roles:
        raise ValueError("--roles declared no replicas")
    if "prefill" in roles and not any(
        r in ("decode", "unified") for r in roles
    ):
        raise ValueError(
            "--roles with prefill replicas needs at least one "
            "decode/unified replica (prefill replicas never decode)"
        )
    if "decode" in roles and "prefill" not in roles:
        # a decode-only fleet would behave like a uniform pool while
        # wearing role="decode" labels — the disaggregated policy
        # slices and /metrics would misrepresent it as phase-split
        raise ValueError(
            "--roles with decode replicas needs at least one prefill "
            "replica (use unified=N for a non-split fleet)"
        )
    return roles


def build_handler(
    model, params, max_len: int, batching_slots: int = 0,
    speculative: bool = False, prompt_cache: int = 0, tracer=None,
    model_label: str = "", metrics=None, replicas: int = 1,
    kv_blocks: "int | None" = None, kv_block_size: int = 16,
    paged_kernel: str = "auto", kv_swap_blocks: "int | None" = None,
    roles: "list[str] | None" = None,
    fabric_peers: "list[str] | None" = None,
    spec_k: int = 4, spec_tiers: "tuple[str, ...] | None" = None,
):
    """batching_slots > 0 serves through the continuous-batching pool
    (models/batching.py): concurrent requests share one decode loop,
    joining at step granularity, driven by a single background thread;
    per-slot temperature and top_k (<= batching.TOP_K_MAX — the pool's
    static top-k width; larger values get a 400 rather than silently
    differing).  speculative=True (ISSUE 18) serves through the SAME
    paged pool with an int8 self-draft speculating in the shared block
    arena: K draft tokens verified in one fused dispatch, in-graph
    accept/rollback, exact for greedy (verification) and temperature
    (rejection rule).  Speculation is gated per SLO tier (default
    interactive only — batch throughput doesn't want the draft FLOPs);
    batching_slots defaults to 4 when --speculative is given alone.
    """

    import threading
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.data.text import decode_bytes
    from tf_operator_tpu.models.batching import ContinuousBatchingDecoder
    from tf_operator_tpu.models.decode import ChunkedServingDecoder
    from tf_operator_tpu.utils import flight
    from tf_operator_tpu.utils.metrics import (
        SLO_BUCKETS,
        DispatchLedger,
        Metrics,
        finite_summary,
    )
    from tf_operator_tpu.utils.trace import (
        TRACE_HEADER,
        Tracer,
        extract_headers,
    )

    # the same observability surface the operator exposes: counters +
    # latency histogram in Prometheus text format on GET /metrics,
    # plus the PR-2 trace store on /traces.  One DispatchLedger is
    # shared by every decoder in the process: serving_dispatch_*
    # counters land in /metrics and request-thread dispatches become
    # dispatch.<phase> child spans of the request span.
    # main() passes ITS registry so every sink in the process — the
    # handler's /metrics, the watchdog's stall counter, the flight
    # recorder's deltas — reads and writes the same exposition
    metrics = metrics if metrics is not None else Metrics()
    if tracer is None:
        tracer = Tracer()
    ledger = DispatchLedger(metrics=metrics, tracer=tracer)
    model_label = model_label or "unknown"
    #: the serving-SLO families (TTFT / time-per-output-token / queue
    #: wait / end-to-end), labeled by model+mode (route on the e2e
    #: family), get the long-tail SLO buckets — a 256-token generate
    #: on a tunneled chip is tens of seconds
    for fam in (
        "serve_ttft_seconds",
        "serve_time_per_output_token_seconds",
        "serve_queue_wait_seconds",
        "serve_request_seconds",
    ):
        metrics.set_buckets(fam, SLO_BUCKETS)
    #: process flight recorder: spans + logs + metric deltas survive to
    #: the moment of failure; served on /debug/flightrecorder
    recorder = flight.default_recorder
    recorder.attach_tracer(tracer)
    recorder.attach_metrics(metrics)
    #: SLO alert engine over THIS registry (utils/alerts.py): GET
    #: /alerts serves the lifecycle state, and a pending→firing
    #: transition dumps the flight recorder once per episode.  NOT
    #: started here — tests build handlers by the dozen and an
    #: evaluator thread per handler would leak; main() starts the one
    #: that serves real traffic (exposed as ``Handler.alert_engine``).
    from tf_operator_tpu.utils.alerts import AlertEngine, default_rules

    alert_engine = AlertEngine(
        default_rules(), metrics=metrics, recorder=recorder
    )
    #: device cost plane (ISSUE 20): ONE CompileLedger + HBM accountant
    #: + step-time sentinel shared by every pool replica in the process,
    #: on THIS registry — compile_total/hbm_*/step_time_* land in
    #: /metrics where the compile-storm and step-time-regression rules
    #: (started by main()) bind, and GET /debug/compiles +
    #: /debug/memory serve the ledgers below
    from tf_operator_tpu.utils.costplane import CostPlane, default_costplane

    costplane = CostPlane(metrics=metrics)
    # the weights are device bytes regardless of serving mode; pools
    # add their KV arenas (and swap staging) as they construct
    costplane.hbm.register_tree("weights", params)

    def observe_slo(mode: str, queue_wait: float, ttft: float,
                    tpot: float, exemplar: "str | None" = None) -> None:
        """The single-dispatch chunked mode produces its whole output
        in one program: the first token is host-visible
        only when every token is, so TTFT is honestly the full
        generate wall and time-per-output-token is wall/n (docs/
        SERVING.md "SLO definitions").  The pool observes its own
        precise per-request values instead.  ``exemplar`` is the
        request's trace id — the "p99 is bad → which request?" link
        (ISSUE 11)."""

        metrics.observe_histogram(
            "serve_queue_wait_seconds", queue_wait,
            exemplar=exemplar, model=model_label, mode=mode,
        )
        metrics.observe_histogram(
            "serve_ttft_seconds", ttft, exemplar=exemplar,
            model=model_label, mode=mode,
        )
        metrics.observe_histogram(
            "serve_time_per_output_token_seconds", tpot,
            exemplar=exemplar, model=model_label, mode=mode,
        )

    spec_pool_kw = {}
    if speculative:
        from tf_operator_tpu.ops.quant import is_quantized, quantize_tree

        # ISSUE 18: speculation IS a paged-pool mode now — the draft's
        # KV pages through the same block arena, verify is one fused
        # multi-query dispatch, accept/rollback happen in-graph.  The
        # draft is the SAME weights int8-quantized (half the HBM bytes
        # per draft step, near-total agreement).  If serving already
        # quantized (--quantize int8), target and draft share the int8
        # tree — still exact, just less speedup.
        if batching_slots <= 0:
            batching_slots = 4  # spec serving rides the pool
        dparams = params if is_quantized(params) else quantize_tree(params)
        spec_pool_kw = dict(
            draft_model=model, draft_params=dparams, spec_k=spec_k,
        )
        if spec_tiers is not None:
            # passed through UNVALIDATED on purpose: the pool's
            # constructor raises on a typo'd tier, so a bad
            # --spec-tiers fails startup instead of silently serving
            # non-speculatively (the PR 10 honesty rule)
            spec_pool_kw["spec_tiers"] = tuple(spec_tiers)
    if batching_slots > 0:
        if prompt_cache:
            raise ValueError(
                "--prompt-cache applies to the chunked decoder; the "
                "batching pool consumes the shared PREFIX cache "
                "(models/prefix_cache.py) instead — drop one of the "
                "flags"
            )
        from tf_operator_tpu.models.batching import (
            PagedContinuousBatchingDecoder,
        )
        from tf_operator_tpu.models.kv_blocks import NotPageableError
        from tf_operator_tpu.models.pool_router import PoolRouter
        from tf_operator_tpu.models.prefix_cache import PrefixFabric

        n_replicas = max(1, int(replicas))
        role_list = list(roles) if roles else ["unified"] * n_replicas
        if len(role_list) != n_replicas:
            raise ValueError(
                f"--roles declares {len(role_list)} replicas but "
                f"--replicas says {n_replicas}"
            )
        # ISSUE 13: the prefix-cache FABRIC is the migration transport
        # of a disaggregated fleet — one shared host-side store every
        # replica publishes into / pulls from
        fabric = (
            PrefixFabric(metrics=metrics, model_label=model_label)
            if ("prefill" in role_list or fabric_peers is not None)
            else None
        )
        if fabric is not None and fabric_peers is not None:
            # ISSUE 17: fleet mode — wrap the local store with the
            # cross-pod client tier: local misses pull from peers that
            # advertise the chain key, local publishes announce to
            # them, and __contains__ answers fleet-wide so a prompt
            # another pod already published is never recomputed here
            from tf_operator_tpu.models.fabric_service import FleetFabric

            fabric = FleetFabric(
                fabric, peers=fabric_peers, metrics=metrics,
                model_label=model_label,
            )
        pool_replicas = []
        for i in range(n_replicas):
            # replica labels only under the router: single-replica
            # serving keeps the legacy unlabeled series
            rep = str(i) if n_replicas > 1 else ""
            try:
                # PAGED is the default pool (ISSUE 8): admission gated
                # on blocks free, shared prefix cache; kv_blocks=None
                # sizes the arena at the slot pool's HBM budget.
                # --paged-kernel (ISSUE 10) selects the steady-state
                # step: "auto" fuses the Pallas paged-attention read
                # on TPU / emulates elsewhere; an explicit "on" FAILS
                # here (ValueError, not NotPageableError) when the
                # kernel cannot serve — never a silent downgrade
                p = PagedContinuousBatchingDecoder(
                    model, params, slots=batching_slots,
                    kv_blocks=kv_blocks, kv_block_size=kv_block_size,
                    ledger=ledger, metrics=metrics,
                    model_label=model_label, replica_label=rep,
                    paged_kernel=paged_kernel,
                    swap_blocks=kv_swap_blocks,
                    role=role_list[i], fabric=fabric,
                    costplane=costplane,
                    **spec_pool_kw,
                )
                if i == 0:
                    print(
                        "paged decode step: "
                        + (p._kernel_impl or "gather emulation"),
                        flush=True,
                    )
            except NotPageableError as exc:
                if spec_pool_kw:
                    # speculation exists ONLY on the paged plane (the
                    # draft's KV lives in the block arena) — a model
                    # the paged pool refuses must fail --speculative
                    # startup, never silently serve non-speculatively
                    raise ValueError(
                        f"--speculative requires the paged pool: {exc}"
                    ) from exc
                if fabric is not None:
                    # the fabric transport is block-granular: a model
                    # the paged pool refuses cannot be disaggregated —
                    # fail startup rather than silently serve a
                    # unified contiguous fleet under --roles
                    raise ValueError(
                        f"--roles requires the paged pool: {exc}"
                    ) from exc
                # MODEL-shape fallback only (rolling-window caches):
                # operator config errors (bad --kv-blocks /
                # --kv-block-size) must fail startup, not silently
                # downgrade away the paged capacity they asked for
                print(f"paged pool unavailable ({exc}); serving the "
                      "contiguous slot pool", flush=True)
                p = ContinuousBatchingDecoder(
                    model, params, slots=batching_slots, ledger=ledger,
                    metrics=metrics, model_label=model_label,
                    replica_label=rep, costplane=costplane,
                )
            pool_replicas.append(p)
        pool = (
            PoolRouter(pool_replicas, tracer=tracer) if n_replicas > 1
            else pool_replicas[0]
        )
        # autopsies + arena history ride every flight-recorder dump:
        # an alert/watchdog post-mortem names the requests in flight
        # and the pressure ramp that preceded the episode (ISSUE 11)
        for p in pool_replicas:
            recorder.attach_request_log(p.request_log)
            if getattr(p, "timeline", None) is not None:
                recorder.attach_arena_timeline(p.timeline)
        pool_fatal = []  # driver-thread death must surface as 500s

        def _drive(p, hb_name):
            # the pool driver is THE liveness-critical thread: a wedge
            # here hangs every queued client, so it heartbeats the
            # process watchdog (which dumps stacks + flight recorder
            # past the deadline — utils/watchdog.py)
            from tf_operator_tpu.utils.watchdog import default_watchdog

            hb = default_watchdog.register(hb_name)
            while True:
                try:
                    hb.beat()
                    if p.step() == 0:
                        _time.sleep(0.005)
                except Exception as exc:  # a dead driver = hung clients
                    pool_fatal.append(repr(exc))
                    default_watchdog.unregister(hb.name)
                    return

        for i, p in enumerate(pool_replicas):
            name = "serving.pool" if n_replicas == 1 else f"serving.pool{i}"
            threading.Thread(
                target=_drive, args=(p, name), daemon=True
            ).start()
        pool_fabric = fabric
    else:
        pool = None
        pool_replicas = []
        pool_fatal = []
        pool_fabric = None
        decoder = ChunkedServingDecoder(
            model, params, prompt_cache=prompt_cache, ledger=ledger,
        )

    #: one live device profile at a time (GET /debug/profile):
    #: jax.profiler has process-global start/stop state, so a second
    #: concurrent request must 409, not corrupt the first
    profile_lock = threading.Lock()

    # /requests + /debug/arena reads: the multi-replica merge lives on
    # PoolRouter (request_autopsy/recent_requests/arena_snapshots —
    # duck-typed below so the single-pool and no-pool modes answer the
    # same shape without duplicating the merge logic here)
    def recent_requests(limit: int = 50):
        if hasattr(pool, "recent_requests"):
            return pool.recent_requests(limit)
        if pool_replicas:
            return pool_replicas[0].request_log.recent(limit)
        return []

    def request_autopsy(req_id: str):
        if hasattr(pool, "request_autopsy"):
            return pool.request_autopsy(req_id)
        if pool_replicas:
            return pool_replicas[0].request_log.get(req_id)
        return None

    # the dashboard strip renders at most ~160 samples per replica —
    # shipping the full 512-sample ring on every 2 s poll would be
    # pure serialization waste on the process serving decode traffic
    ARENA_SAMPLE_LIMIT = 160

    def arena_snapshots():
        if hasattr(pool, "arena_snapshots"):
            return pool.arena_snapshots(ARENA_SAMPLE_LIMIT)
        return [
            p.timeline.snapshot(ARENA_SAMPLE_LIMIT)
            for p in pool_replicas
            if getattr(p, "timeline", None) is not None
        ]

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _reply(self, code: int, payload: dict) -> None:
            t0 = getattr(self, "_t0", None)
            if t0 is not None:  # a /generate request being answered
                self._t0 = None
                metrics.observe_histogram(
                    "serve_request_seconds", _time.perf_counter() - t0,
                    exemplar=getattr(self, "_trace_id", None),
                    route="/generate", model=model_label,
                )
                metrics.inc("serve_requests_total", status=str(code))
                if code == 200 and isinstance(payload.get("sample"), str):
                    metrics.inc(
                        "serve_tokens_generated_total",
                        float(len(payload["sample"])),
                    )
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            tid = getattr(self, "_trace_id", None)
            if tid:  # the PR-2 propagation contract: echo on EVERY reply
                self.send_header(TRACE_HEADER, tid)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            # keep-alive reuses the handler instance across requests: a
            # stale span id from a previous POST on this connection must
            # not stamp an untraced response (same guard as server/api)
            self._trace_id = None
            if self.path == "/healthz":
                return self._reply(200, {"ok": True})
            if self.path == "/metrics":
                # live gauges appended to the counter exposition
                # compile-count gauges in EVERY mode: bounded compile
                # cardinality is this module's headline invariant, and
                # a fragmenting workload should be visible on /metrics
                extra = []
                if pool is not None:
                    extra.append(f"serve_pool_compiles {pool.compile_count}")
                if pool is not None and getattr(pool, "spec_enabled", False):
                    # paged-plane speculation gauges (ISSUE 18): the
                    # counter families (serve_spec_*_total{model,tier})
                    # ride the registry; acceptance and the CPU-honest
                    # dispatches-per-token ratio are derived here
                    snap = pool.spec_snapshot()
                    extra.append(
                        "serve_spec_acceptance_rate "
                        f"{snap['acceptance_rate']:.4f}"
                    )
                    dpt = snap["dispatches_per_token"]
                    if dpt != float("inf"):
                        extra.append(
                            f"serve_spec_dispatches_per_token {dpt:.4f}"
                        )
                if pool is None:  # chunked decoder serves (or backstops)
                    extra.append(
                        f"serve_prompt_cache_hits {decoder.prompt_cache_hits}"
                    )
                    extra.append(
                        f"serve_decoder_compiles {decoder.compile_count}"
                    )
                body = (metrics.exposition() + "\n".join(extra) + "\n").encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path == "/traces":
                return self._reply(200, {"traces": tracer.store.summaries(50)})
            if self.path.startswith("/traces/"):
                t = tracer.store.trace(self.path[len("/traces/"):])
                if t is None:
                    return self._reply(404, {"error": "unknown trace id"})
                return self._reply(200, t)
            if self.path == "/requests":
                # recent autopsies newest-first, merged across every
                # replica's RequestLog (the /slo merged-family
                # pattern applied to request records)
                return self._reply(200, {"requests": recent_requests(50)})
            if self.path.startswith("/requests/"):
                entry = request_autopsy(self.path[len("/requests/"):])
                if entry is not None:
                    return self._reply(200, entry)
                return self._reply(404, {
                    "error": "unknown request id (pool modes only; ids "
                             "are trace ids — the /generate response's "
                             "request_id / x-trace-id header)"})
            if self.path == "/debug/arena":
                # the KV-arena occupancy timeline per paged replica
                # (each snapshot carries the replica's phase role) —
                # the time-series twin of kv_blocks_pressure — plus
                # the fabric's publish/pull accounting when the fleet
                # is disaggregated (ISSUE 13)
                return self._reply(200, {
                    "replicas": arena_snapshots(),
                    "fabric": pool_fabric.snapshot()
                    if pool_fabric is not None else None,
                })
            if self.path == "/debug/fabric":
                # the fleet-fabric panel/CLI read (ISSUE 17): peer
                # liveness + hit/pull/failure counts + bytes by
                # transport, merged out of the fabric snapshot
                if pool_fabric is None:
                    return self._reply(404, {
                        "error": "no prefix fabric (start with --roles "
                                 "prefill=... or --fabric-peers)"})
                return self._reply(200, {
                    "model": model_label,
                    "fabric": pool_fabric.snapshot(),
                })
            if self.path == "/debug/compiles":
                # the compile ledger (ISSUE 20): every jit/pallas entry
                # point in the serving hot paths registers its compiles
                # with program, trigger class, wall and owning trace —
                # the "why is the fleet recompiling" read behind the
                # compile-storm rule.  The chunked decoder registers on
                # the process-default ledger (it has no registry of its
                # own) — serve that one when no pool is running.
                src = (
                    costplane.compiles if pool is not None
                    else default_costplane.compiles
                )
                return self._reply(200, {
                    "model": model_label,
                    **src.snapshot(),
                })
            if self.path == "/debug/memory":
                # the HBM accountant (ISSUE 20): per-device bytes by
                # component (weights / kv_arena / swap staging /
                # program temp peak), headroom-worst-first, with the
                # accounted-vs-live coverage ratio so a leak shows as
                # falling coverage, not silence
                return self._reply(200, {
                    "model": model_label,
                    **costplane.hbm.snapshot(),
                })
            if self.path == "/debug/profile" or \
                    self.path.startswith("/debug/profile?"):
                # exact-or-query match only: a typo'd /debug/profileX
                # must 404, not trigger a real device profile
                return self._profile()
            if self.path == "/slo":
                # the operator's one-look answer to "what latency are
                # users seeing right now": per-{model,mode} quantiles
                # of every SLO family plus the live load gauges.
                # MERGED across {replica=} (histogram_family_merged):
                # multi-replica serving reports ONE user-facing p99
                # TTFT, not N disjoint per-replica summaries; /metrics
                # keeps the per-replica series for capacity eyes.
                fams = {}
                for fam in (
                    "serve_ttft_seconds",
                    "serve_time_per_output_token_seconds",
                    "serve_queue_wait_seconds",
                    "serve_request_seconds",
                ):
                    fams[fam] = [
                        {**dict(labels), **finite_summary(summary)}
                        for labels, summary in sorted(
                            metrics.histogram_family_merged(fam).items()
                        )
                    ]

                def gauge_sum(name: str) -> float:
                    # per-replica gauge series sum to the fleet view
                    return sum(
                        v
                        for labels, v in metrics.gauge_series(name).items()
                        if dict(labels).get("model", model_label)
                        == model_label
                    )

                return self._reply(200, {
                    "model": model_label,
                    "replicas": max(1, int(replicas)),
                    "histograms": fams,
                    "gauges": {
                        "serve_admission_queue_depth": gauge_sum(
                            "serve_admission_queue_depth"
                        ),
                        "serve_tokens_in_flight": gauge_sum(
                            "serve_tokens_in_flight"
                        ),
                        "kv_blocks_free": gauge_sum("kv_blocks_free"),
                        "kv_blocks_in_use": gauge_sum("kv_blocks_in_use"),
                    },
                    "requests_ok": metrics.counter(
                        "serve_requests_total", status="200"
                    ),
                })
            if self.path == "/alerts":
                # the serving plane's alert state: same read contract
                # as the operator API's GET /alerts
                return self._reply(200, alert_engine.snapshot())
            if self.path == "/debug/flightrecorder":
                body = recorder.dump_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            return self._reply(404, {"error": "try POST /generate"})

        def _profile(self):
            """GET /debug/profile?seconds=N — wrap jax.profiler around
            the LIVE decode loop (the driver threads keep stepping;
            this request thread only sleeps) and return the trace
            artifact directory.  Host-side only: profiling observes
            the device stream, it never fetches from it, so the
            no-hot-sync gate over the step loop is untouched."""

            seconds = 1.0
            query = self.path.split("?", 1)[1] if "?" in self.path else ""
            for part in query.split("&"):
                if part.startswith("seconds="):
                    try:
                        seconds = float(part.split("=", 1)[1])
                    except ValueError:
                        return self._reply(
                            400, {"error": "seconds must be a number"}
                        )
            if not (0.0 < seconds <= 30.0):
                return self._reply(
                    400, {"error": "seconds must be in (0, 30]"}
                )
            if pool is not None:
                # an idle decode loop produces an empty trace after a
                # full `seconds` of wall — refuse up front instead of
                # making the operator wait for a useless artifact
                # (ISSUE 20 satellite).  Host-side queue/seat counts
                # only; no device fetch.
                load = sum(
                    sum(p.load_components().values())
                    for p in pool_replicas
                )
                if load == 0:
                    return self._reply(503, {
                        "error": "decode loop idle: no active seats or "
                                 "queued requests to profile — send "
                                 "traffic first, then re-request",
                    })
            if not profile_lock.acquire(blocking=False):
                return self._reply(
                    409, {"error": "a profile is already running "
                                   "(jax.profiler is process-global)"}
                )
            try:
                import tempfile

                base = os.environ.get("TPUJOB_PROFILE_DIR")
                if base:
                    os.makedirs(base, exist_ok=True)
                # the artifact name carries the compile-ledger count at
                # capture: two profiles of the same job disambiguate
                # "before/after the recompile storm" from the filename
                cost_compiles = (
                    costplane.compiles if pool is not None
                    else default_costplane.compiles
                )
                compiles0 = cost_compiles.total()
                out_dir = tempfile.mkdtemp(
                    prefix=f"serve-profile-c{compiles0}-",
                    dir=base or None,
                )
                t0 = _time.perf_counter()
                jax.profiler.start_trace(out_dir)
                try:
                    _time.sleep(seconds)
                finally:
                    jax.profiler.stop_trace()
                # cost-plane autopsy rides the artifact (COSTPLANE.json
                # next to the trace) AND the response: what compiled
                # during the window and what the step-time sentinel saw
                context = {
                    "compiles_at_start": compiles0,
                    "compiles_during_window":
                        cost_compiles.total() - compiles0,
                    "compile_programs": cost_compiles.snapshot(
                        limit=8
                    )["byProgram"],
                    "step_time": costplane.sentinel.snapshot(),
                }
                try:
                    with open(
                        os.path.join(out_dir, "COSTPLANE.json"), "w"
                    ) as f:
                        json.dump(context, f, indent=2, sort_keys=True)
                except OSError:
                    pass  # the trace is the artifact; context is extra
                return self._reply(200, {
                    "artifact": out_dir,
                    "seconds": seconds,
                    "wall_seconds": round(_time.perf_counter() - t0, 3),
                    "costplane": context,
                })
            except Exception as exc:  # profiler quirks must not 500 loop
                return self._reply(500, {"error": repr(exc)})
            finally:
                profile_lock.release()

        def do_POST(self):
            if self.path != "/generate":
                return self._reply(404, {"error": "unknown path"})
            # every request is a server span: adopt an incoming trace
            # id (x-trace-id/x-parent-span-id) or root a fresh one;
            # request-thread decoder dispatches (chunked path) nest
            # under it as dispatch.<phase> children.  Pool
            # dispatches run on the driver thread — they link by the
            # rid attribute instead (docs/ARCHITECTURE.md "serving
            # dispatch accounting").
            tid, parent = extract_headers(self.headers)
            with tracer.start_span(
                "serve.generate", kind="server", trace_id=tid,
                parent_id=parent,
            ) as span:
                self._trace_id = span.trace_id
                self._generate(span)

        def _generate(self, span):
            self._t0 = _time.perf_counter()
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                text = req.get("prompt", "")
                n_new = int(req.get("max_new_tokens", 32))
                # quantize: bounds the jit-cache cardinality under
                # arbitrary client temperature values
                temperature = round(float(req.get("temperature", 0.0)) * 20) / 20
                if temperature < 0.0:
                    return self._reply(400, {"error": "temperature must be >= 0"})
                # int-cast/validate UNCONDITIONALLY: a raw string here
                # would fragment the compile cache (and greedy requests
                # carrying top_k used to skip the cast entirely)
                top_k = req.get("top_k")
                if top_k is not None:
                    top_k = int(top_k)
                    if top_k < 1:
                        return self._reply(400, {"error": "top_k must be >= 1"})
                seed = req.get("seed")
                if seed is None:
                    # fresh entropy per request — a fixed default would
                    # return identical "samples" every time
                    seed = int.from_bytes(os.urandom(4), "little")
                seed = int(seed)
                # stop sequence: generation still runs its fixed-shape
                # budget (XLA has no data-dependent early exit worth
                # its recompiles here); the SAMPLE is truncated at the
                # first occurrence, which is the API contract users
                # expect.  Host-side, exact, compile-cache-neutral.
                stop = req.get("stop")
                if stop is not None and not isinstance(stop, str):
                    return self._reply(400, {"error": "stop must be a string"})
                # SLO tier (ISSUE 12): body field wins over the
                # x-slo-tier header; default batch — callers opt INTO
                # interactive priority explicitly.  Validated here so
                # a typo'd tier is a 400, not a silent batch demotion
                # — including falsy body values ("" / null-as-False):
                # an explicit `is None` check, not `or`-chaining
                tier = req.get("tier")
                if tier is None:
                    tier = self.headers.get("x-slo-tier") or "batch"
                if tier not in ("interactive", "batch"):
                    return self._reply(400, {
                        "error": "tier must be 'interactive' or 'batch'"})

                def finish(sample: str) -> str:
                    if stop:
                        cut = sample.find(stop)
                        if cut >= 0:
                            return sample[:cut]
                    return sample
                if not text:
                    return self._reply(400, {"error": "empty prompt"})
                if n_new < 1:
                    return self._reply(400, {"error": "max_new_tokens must be >= 1"})
                ids = np.frombuffer(text.encode("ascii", "replace"), np.uint8)
                if len(ids) + n_new > max_len:
                    return self._reply(400, {
                        "error": f"prompt({len(ids)}) + max_new_tokens({n_new}) "
                                 f"> max_len({max_len})"})
                span.set_attribute("prompt_tokens", int(len(ids)))
                span.set_attribute("max_new_tokens", n_new)
                if pool is not None:
                    span.set_attribute("mode", "pool")
                    from tf_operator_tpu.models.batching import TOP_K_MAX

                    # full client-error range pre-validated here: the
                    # pool's own ValueError would surface as a 500
                    if top_k is not None and not (1 <= top_k <= TOP_K_MAX):
                        return self._reply(400, {
                            "error": f"top_k must be in [1, {TOP_K_MAX}] "
                                     "in --batching mode (static top-k "
                                     "width)"})
                    # the request's first-class id IS this span's trace
                    # id (adopted x-trace-id or freshly minted): every
                    # pool lifecycle span — route, queue.wait,
                    # admission, decode.window, retire — and the
                    # /requests/<id> autopsy key on it (ISSUE 11)
                    span.set_attribute("tier", tier)
                    if pool_fabric is not None and hasattr(
                        pool, "publish_to_fabric"
                    ):
                        # fleet mode, unified single replica (ISSUE
                        # 17): make this prompt's full blocks
                        # fleet-visible BEFORE admission.  First pod to
                        # see a prefix pays the prefill and publishes;
                        # every other pod's publish early-returns (the
                        # fleet-wide contains check) and its admission
                        # pulls the chain from the publisher instead of
                        # recomputing.  A failed publish never fails
                        # the request — admission just recomputes.
                        try:
                            pub = pool.publish_to_fabric(
                                ids.astype(np.int32), tier=tier,
                                trace_id=span.trace_id, timeout=120.0,
                            )
                            span.set_attribute(
                                "fabric_published", pub["published"]
                            )
                        except Exception as exc:
                            metrics.inc(
                                "serve_fabric_publish_failures_total",
                                model=model_label,
                            )
                            span.set_attribute(
                                "fabric_publish_error", repr(exc)
                            )
                    rid = pool.submit(
                        ids.astype(np.int32), n_new,
                        temperature=temperature, top_k=top_k,
                        rng=jax.random.PRNGKey(seed)
                        if temperature > 0.0 else None,
                        trace_id=span.trace_id,
                        tier=tier,
                    )
                    span.set_attribute("rid", rid)
                    # condition wait (no lock-churning poll); the
                    # periodic timeout is only to notice driver death
                    while True:
                        out_row = pool.result_wait(rid, timeout=0.5)
                        if out_row is not None:
                            break
                        if pool_fatal:
                            return self._reply(500, {
                                "error": "decode driver died: "
                                         f"{pool_fatal[0]}"})
                    sample = finish(decode_bytes(out_row[len(ids):]))
                    return self._reply(
                        200, {"prompt": text, "sample": sample,
                              "seed": seed,
                              "request_id": span.trace_id}
                    )
                prompt = jnp.asarray(ids, jnp.int32)[None]
                span.set_attribute("mode", "chunked")
                t_gen = _time.perf_counter()
                out = decoder.generate(
                    prompt, n_new, temperature=temperature, top_k=top_k,
                    rng=jax.random.PRNGKey(seed),
                )
                # generate returns an UN-fetched device array; without
                # this host fetch inside the timed window, wall would
                # record async-dispatch latency (~ms), not generation
                new_ids = np.asarray(out[0, prompt.shape[1]:])
                wall = _time.perf_counter() - t_gen
                observe_slo("chunked", 0.0, wall, wall / n_new,
                            exemplar=span.trace_id)
                sample = finish(decode_bytes(new_ids))
                return self._reply(
                    200, {"prompt": text, "sample": sample, "seed": seed}
                )
            except (ValueError, TypeError, KeyError, json.JSONDecodeError) as exc:
                return self._reply(400, {"error": repr(exc)})  # client's fault
            except Exception as exc:  # serving must not die on bad input
                span.set_error(repr(exc))  # tail sampling protects it
                return self._reply(500, {"error": repr(exc)})

    #: the engine this handler's /alerts serves — main() starts/stops
    #: its evaluator; tests can drive evaluate_once() synthetically
    Handler.alert_engine = alert_engine
    #: the pool's prefix fabric (None outside pool modes) — main()
    #: boots the FabricServer over it and stamps the advertise addr
    Handler.pool_fabric = pool_fabric
    #: the serving pool (None in chunked mode) — tests assert the
    #: speculative config actually landed on it (ISSUE 18)
    Handler.pool = pool
    #: the process cost plane this handler's /debug/compiles +
    #: /debug/memory serve (ISSUE 20) — tests read the ledgers directly
    Handler.costplane = costplane
    return Handler


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--artifact", required=True, help="export_params directory")
    ap.add_argument("--port", type=int, default=8600)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument(
        "--platform", default=None,
        help="force a jax platform (e.g. cpu) — goes through jax.config, "
             "which beats env-level pins like this box's sitecustomize",
    )
    ap.add_argument(
        "--prompt-cache", type=int, default=0, metavar="N",
        help="LRU of N prompt-KV snapshots: an exact repeat prompt "
             "(same system+context, fresh budget/sampling) skips "
             "prefill entirely; each entry holds one full KV cache",
    )
    ap.add_argument(
        "--speculative", action="store_true",
        help="speculate on the paged pool (ISSUE 18): an int8 "
             "self-draft pages its KV through the same block arena, K "
             "draft tokens verify in one fused dispatch, accept/"
             "rollback happen in-graph.  Composes with --batching "
             "(defaults to 4 slots when given alone); gated per SLO "
             "tier (interactive by default — see --spec-tiers).  "
             "REFUSES to start when the measured spec_paged_speedup "
             "row in benchmarks/LAST_MEASURED.json is a slowdown on "
             "this box",
    )
    ap.add_argument(
        "--speculative-force", action="store_true",
        help="serve --speculative even though the measured ledger says "
             "it is a slowdown here (for deployments whose dispatch "
             "economics differ from the measured box)",
    )
    ap.add_argument(
        "--spec-k", type=int, default=4, metavar="K",
        help="draft tokens proposed per speculative window (validated "
             "by the pool: K < 1 fails startup)",
    )
    ap.add_argument(
        "--spec-tiers", default=None, metavar="T1[,T2]",
        help="comma-separated SLO tiers that speculate (default: "
             "interactive only — batch throughput doesn't want the "
             "draft FLOPs).  A typo'd tier FAILS STARTUP (the pool "
             "validates against its SLO tier set) — never a silent "
             "non-speculative downgrade",
    )
    ap.add_argument(
        "--batching", type=int, default=0, metavar="SLOTS",
        help="serve through the continuous-batching pool with this many "
             "slots (concurrent requests share one decode loop); 0 = "
             "one-request-at-a-time ChunkedServingDecoder.  The pool is "
             "PAGED by default (block-granular KV admission + shared "
             "prefix cache — models/batching.py); rolling-window "
             "models fall back to the contiguous slot pool",
    )
    ap.add_argument(
        "--replicas", type=int, default=1, metavar="N",
        help="run N pool replicas behind one admission queue "
             "(models/pool_router.py — least-blocks-in-use routing; "
             "per-replica gauges on /metrics, merged quantiles on "
             "/slo).  Requires --batching",
    )
    ap.add_argument(
        "--roles", default=None, metavar="ROLE=N,...",
        help="phase-split the replica fleet (ISSUE 13 disaggregated "
             "serving): e.g. 'prefill=1,decode=2' runs one prefill "
             "replica (chunk-prefills prompts and publishes finished "
             "blocks into the prefix-cache fabric) and two decode "
             "replicas (admit by mapping the published chain, pulling "
             "only the missing tail — migrate_in — then run the "
             "unchanged 1-dispatch/step loop).  Implies --replicas = "
             "the declared total; requires --batching and a pageable "
             "model.  Default: every replica 'unified' (both phases)",
    )
    ap.add_argument(
        "--fabric-port", type=int, default=None, metavar="PORT",
        help="export this pod's prefix-fabric store on "
             "127.0.0.1:PORT (GET /fabric/index, /fabric/blocks/<key>, "
             "POST /fabric/publish — models/fabric_service.py).  "
             "Default: the reconciler-injected TPUJOB_FABRIC_PORT when "
             "set (the tpujob.dist/fabric-port discovery contract), "
             "else no fabric server.  Requires --batching",
    )
    ap.add_argument(
        "--fabric-peers", default=None, metavar="HOST:PORT,...",
        help="static peer list for the cross-pod KV fabric (ISSUE 17): "
             "local prefix-cache misses pull published blocks from "
             "these peers over HTTP (one migrate_in dispatch, "
             "content-hash verified, recompute on any failure), and "
             "local publishes announce to them.  May be empty ('') to "
             "enter fleet mode with announcement-only discovery.  "
             "Requires --batching",
    )
    ap.add_argument(
        "--kv-blocks", type=int, default=None, metavar="N",
        help="paged pool arena size in KV blocks per replica (default: "
             "slots x max_len / block-size — the same HBM the slot "
             "pool would pin, now admitting by blocks free)",
    )
    ap.add_argument(
        "--kv-block-size", type=int, default=16, metavar="TOKENS",
        help="tokens per KV block (must divide max_len)",
    )
    ap.add_argument(
        "--kv-swap-blocks", type=int, default=None, metavar="N",
        help="cap the host-side KV swap arena at N blocks per replica "
             "(ISSUE 12 preemption spill space; default: unbounded). "
             "When BOTH the device arena and the swap cap are "
             "exhausted, requests queue/park — the pool never crashes "
             "mid-decode (docs/SERVING.md oversubscription honesty "
             "rule)",
    )
    ap.add_argument(
        "--paged-kernel", choices=["auto", "on", "off", "interpret"],
        default="auto", metavar="MODE",
        help="paged-attention decode step (ISSUE 10): 'auto' reads KV "
             "straight off the block arena with the Pallas kernel on "
             "the TPU backend and falls back to the gather emulation "
             "elsewhere; 'on' REFUSES to start where the kernel cannot "
             "serve (no silent downgrade); 'off' pins the emulation; "
             "'interpret' runs the kernel through the Pallas "
             "interpreter (test/debug only — slow)",
    )
    ap.add_argument(
        "--quantize", choices=["int8"], default=None,
        help="weights-only int8 for the projection kernels "
             "(ops/quant.py): ~2x less HBM weight traffic per decoded "
             "token; embedding/logits head stays bf16",
    )
    args = ap.parse_args()

    if args.speculative and not args.speculative_force:
        best, row = speculative_slowdown()
        if best is not None and best < 1.0:
            cfg = row.get(
                "config", "int8 self-draft on the paged pool"
            )
            raise SystemExit(
                f"--speculative refused: the best MEASURED speculative "
                f"config on this box is {best}x of the non-speculative "
                f"paged pool at the same arena ({cfg}; {row['metric']}, "
                f"{row['artifact']}, {row['date']}) — serving it would "
                "be a measured slowdown, not a feature.  Re-measure "
                "with `python benchmarks/measure.py --section "
                "speculative-paged`, or pass --speculative-force on a "
                "deployment whose dispatch economics differ from the "
                "measured box."
            )

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from tf_operator_tpu.models import llama_tiny
    from tf_operator_tpu.parallel import load_model_description, load_params
    from tf_operator_tpu.utils import flight
    from tf_operator_tpu.utils.metrics import Metrics
    from tf_operator_tpu.utils.watchdog import maybe_start_from_env

    # ONE registry for the whole serving process: the handler's
    # /metrics+/slo, the watchdog's stall counter, and the flight
    # recorder's metric deltas all share it — a stall must be visible
    # on the endpoint the operator actually scrapes
    serve_metrics = Metrics()
    # black-box recorder: SIGTERM / a fatal exception dumps the recent
    # spans+logs+metric deltas; TPUJOB_WATCHDOG=1 adds the stall monitor
    flight.install(metrics=serve_metrics)
    maybe_start_from_env(metrics=serve_metrics)

    # validate against the tiny model.json FIRST — rejecting an
    # incompatible artifact must not cost a full orbax restore
    desc = load_model_description(args.artifact)
    max_len = args.max_len
    model_label = "llama-tiny"
    if desc is not None:
        if desc["config"]["vocab_size"] != 256:
            raise SystemExit(
                f"this server is byte-level (vocab 256); the artifact "
                f"was trained with vocab {desc['config']['vocab_size']}"
            )
        # cap the serving cache at the trained length: learned position
        # tables are undefined past it (registry raises), and rope
        # extension beyond training length degrades silently
        if max_len > desc["config"]["max_len"]:
            max_len = desc["config"]["max_len"]
            print(f"capping --max-len to trained length {max_len}", flush=True)
        from tf_operator_tpu.models.registry import model_from_description

        model = model_from_description(desc, max_len=max_len)
        model_label = desc["family"]
        print(f"serving family={desc['family']} from model.json", flush=True)
    else:
        # legacy artifact without a description: the historical default
        model = llama_tiny(vocab_size=256, max_len=max_len)
    params = load_params(args.artifact)
    if args.quantize == "int8":
        from tf_operator_tpu.ops.quant import quantize_tree, tree_bytes

        before = tree_bytes(params)
        params = quantize_tree(params)
        print(
            f"int8 weights-only quantization: params "
            f"{before / 1e6:.1f} MB -> {tree_bytes(params) / 1e6:.1f} MB",
            flush=True,
        )
    if args.replicas > 1 and not args.batching:
        raise SystemExit("--replicas requires --batching SLOTS")
    role_list = None
    if args.roles:
        if not args.batching:
            raise SystemExit("--roles requires --batching SLOTS")
        try:
            role_list = parse_roles(args.roles)
        except ValueError as exc:
            raise SystemExit(f"bad --roles: {exc}")
        if args.replicas > 1 and args.replicas != len(role_list):
            raise SystemExit(
                f"--roles declares {len(role_list)} replicas but "
                f"--replicas says {args.replicas} — drop one of the flags"
            )
        args.replicas = len(role_list)
        print(f"disaggregated roles: {','.join(role_list)}", flush=True)
    # fleet fabric (ISSUE 17): explicit flags are hard requirements;
    # the reconciler-injected env port is soft (every pod gets one —
    # a non-fleet invocation must not die on it)
    fabric_port = args.fabric_port
    if fabric_port is None:
        from tf_operator_tpu.bootstrap.tpu_env import ENV_FABRIC_PORT

        try:
            env_port = int(os.environ.get(ENV_FABRIC_PORT, "0") or "0")
        except ValueError:
            env_port = 0
        if env_port > 0 and args.batching:
            fabric_port = env_port
    fabric_peers = None
    if args.fabric_peers is not None:
        if not args.batching:
            raise SystemExit("--fabric-peers requires --batching SLOTS")
        fabric_peers = [
            p.strip() for p in args.fabric_peers.split(",") if p.strip()
        ]
    if args.fabric_port is not None and not args.batching:
        raise SystemExit("--fabric-port requires --batching SLOTS")
    if fabric_port is not None and fabric_peers is None:
        fabric_peers = []  # fleet mode: discovery by announcement
    handler = build_handler(
        model, params, max_len,
        batching_slots=args.batching, speculative=args.speculative,
        prompt_cache=args.prompt_cache, model_label=model_label,
        metrics=serve_metrics, replicas=args.replicas,
        kv_blocks=args.kv_blocks, kv_block_size=args.kv_block_size,
        paged_kernel=args.paged_kernel, kv_swap_blocks=args.kv_swap_blocks,
        roles=role_list, fabric_peers=fabric_peers,
        spec_k=args.spec_k,
        spec_tiers=(
            tuple(
                t.strip() for t in args.spec_tiers.split(",") if t.strip()
            )
            if args.spec_tiers is not None else None
        ),
    )
    server = ThreadingHTTPServer(("127.0.0.1", args.port), handler)
    fabric_server = None
    if handler.pool_fabric is not None and fabric_peers is not None:
        from tf_operator_tpu.models.fabric_service import FabricServer

        fabric_server = FabricServer(
            handler.pool_fabric, port=fabric_port or 0
        ).start()
        handler.pool_fabric.set_advertise(fabric_server.addr)
        print(f"fabric server on {fabric_server.addr} "
              f"(peers: {','.join(fabric_peers) or 'announce-only'})",
              flush=True)
    # the serving binary boots the SLO evaluator (build_handler only
    # constructs it — see the leak note there)
    handler.alert_engine.start()
    print(f"serving on 127.0.0.1:{args.port} (artifact: {args.artifact})", flush=True)
    try:
        server.serve_forever()
    finally:
        handler.alert_engine.stop()
        if fabric_server is not None:
            fabric_server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
