"""dist-mnist — the canonical e2e training workload (BASELINE.md config 1).

Parity: the reference's ``examples/v1/dist-mnist/dist_mnist.py`` (TF1
between-graph replication: parse TF_CONFIG, tf.train.Server, PS/worker
roles, MonitoredTrainingSession; SURVEY.md §3.3).  The TPU-native shape
is SPMD instead of PS/worker: every replica joins one jax.distributed
world (bootstrapped from the operator-injected env), a global ``dp``
mesh shards the batch across all devices of all processes, and jit
inserts the gradient all-reduce that PS round-trips used to do.

Checkpoint/resume (SURVEY.md §5 "Checkpoint / resume"): with
``--checkpoint-dir``, training resumes from the latest orbax step —
restart-with-same-env then continues rather than starting over, which
is the operator's restart contract.

Runs anywhere: single process (CPU or the real TPU chip) or
multi-process under the operator's local backend (CPU collectives).
"""

from __future__ import annotations

import argparse
import sys
from functools import partial

from tf_operator_tpu.runtime import initialize


def synthetic_mnist(rng, n: int):
    """Deterministic fake MNIST (same on every process)."""

    import numpy as np

    r = np.random.RandomState(rng)
    images = r.rand(n, 28, 28, 1).astype("float32")
    labels = r.randint(0, 10, size=(n,)).astype("int32")
    return images, labels


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--batch-size", type=int, default=64, help="global")
    parser.add_argument("--learning-rate", type=float, default=0.05)
    parser.add_argument(
        "--data-dir",
        default="",
        help="on-disk dataset read through the grain input pipeline "
        "(generated once by the coordinator if missing); default: "
        "in-memory synthetic tensors",
    )
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--checkpoint-every", type=int, default=10)
    parser.add_argument(
        "--profile-dir",
        default="",
        help="write a JAX profiler trace here (the mnist_with_summaries"
        " observability analogue; view with tensorboard/xprof)",
    )
    args = parser.parse_args()

    ctx = initialize()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tf_operator_tpu.models import MnistCNN
    from tf_operator_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": len(jax.devices())})
    repl = NamedSharding(mesh, P())
    data_sharding = NamedSharding(mesh, P("dp", None, None, None))
    label_sharding = NamedSharding(mesh, P("dp"))

    model = MnistCNN()
    tx = optax.sgd(args.learning_rate, momentum=0.9)

    dummy = jnp.zeros((1, 28, 28, 1), jnp.float32)
    params = jax.jit(
        lambda rng: model.init(rng, dummy, train=False)["params"],
        out_shardings=repl,
    )(jax.random.PRNGKey(0))
    opt_state = jax.jit(tx.init, out_shardings=repl)(params)
    start_step = 0

    ckpt = None
    if args.checkpoint_dir:
        import orbax.checkpoint as ocp

        ckpt = ocp.CheckpointManager(
            args.checkpoint_dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=2),
        )
        latest = ckpt.latest_step()
        if latest is not None:
            restored = ckpt.restore(
                latest,
                args=ocp.args.StandardRestore({"params": params, "opt": opt_state}),
            )
            params, opt_state = restored["params"], restored["opt"]
            start_step = latest + 1
            print(f"resumed from checkpoint step {latest}", flush=True)

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, images, labels):
        def loss_fn(p):
            logits = model.apply({"params": p}, images, train=False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    import contextlib

    @contextlib.contextmanager
    def maybe_trace():
        if args.profile_dir and jax.process_index() == 0:
            jax.profiler.start_trace(args.profile_dir)
            try:
                yield
            finally:
                # flush the trace even when a step raises — that's the
                # run you most want the profile of
                jax.profiler.stop_trace()
                print(f"profiler trace written to {args.profile_dir}", flush=True)
        else:
            yield

    n_proc = jax.process_count()
    per_proc = max(args.batch_size // n_proc, 1)

    batches = None
    if args.data_dir:
        # the real data path (SURVEY.md §7 step 8): on-disk dataset,
        # grain loader with a disjoint per-process shard, host→device
        # transfer overlapped with compute
        from tf_operator_tpu.data import (
            device_prefetch,
            ensure_mnist,
            make_loader,
            wait_for_dataset,
        )
        from tf_operator_tpu.data.synthetic import mnist_meta

        if jax.process_index() == 0:
            ensure_mnist(args.data_dir)
        else:
            # wait for THESE parameters: a stale dataset mid-rewrite by
            # the coordinator must not satisfy the wait
            wait_for_dataset(args.data_dir, meta=mnist_meta())
        loader = make_loader(args.data_dir, per_proc, num_epochs=None)
        batches = device_prefetch(
            loader,
            {"image": data_sharding, "label": label_sharding},
            image_dtype="float32",
        )

    losses = []
    with maybe_trace():
        for step in range(start_step, args.steps):
            if batches is not None:
                b = next(batches)
                x, y = b["image"], b["label"]
            else:
                images, labels = synthetic_mnist(step % 7, per_proc * n_proc)
                lo = jax.process_index() * per_proc
                x = jax.make_array_from_process_local_data(
                    data_sharding, images[lo : lo + per_proc]
                )
                y = jax.make_array_from_process_local_data(
                    label_sharding, labels[lo : lo + per_proc]
                )
            params, opt_state, loss = train_step(params, opt_state, x, y)
            losses.append(float(loss))
            if ckpt and (
                step % args.checkpoint_every == 0 or step == args.steps - 1
            ):
                import orbax.checkpoint as ocp

                ckpt.save(
                    step,
                    args=ocp.args.StandardSave(
                        {"params": params, "opt": opt_state}
                    ),
                )
    if ckpt:
        ckpt.wait_until_finished()
        ckpt.close()

    if losses:
        first, last = losses[0], float(np.mean(losses[-5:]))
        print(
            f"process {jax.process_index()}/{n_proc}: "
            f"steps {start_step}..{args.steps} loss {first:.4f} -> {last:.4f}",
            flush=True,
        )
        if start_step == 0 and args.steps >= 20 and not last < first:
            print("loss did not decrease", file=sys.stderr, flush=True)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
