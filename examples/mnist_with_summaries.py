"""mnist_with_summaries — step-series metrics a user can plot.

Parity: the reference's ``examples/v1/mnist_with_summaries`` writes
TensorBoard summaries for a TF mnist run (SURVEY.md §2 row).  The
TPU-native analogue: the Trainer writes a JSON-lines scalar series
(loss / accuracy / steps-per-sec) through utils/summaries.SummaryWriter,
and the operator surfaces it — annotate the job with
``tpujob.dist/summary-dir`` and the series shows in
``tpujob describe`` and the dashboard's detail pane.

Run standalone or under the operator:
    python examples/mnist_with_summaries.py --summary-dir /tmp/mnist-sum
"""

from __future__ import annotations

import argparse
import sys

from tf_operator_tpu.runtime import initialize


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument("--batch-size", type=int, default=64, help="global")
    parser.add_argument("--learning-rate", type=float, default=0.05)
    parser.add_argument("--summary-dir", required=True)
    parser.add_argument("--summary-every", type=int, default=5)
    parser.add_argument(
        "--checkpoint-dir",
        default="",
        help="resume via the framework TrainerCheckpointer (restart "
        "contract: same env ⇒ training continues)",
    )
    args = parser.parse_args()

    ctx = initialize()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models import MnistCNN
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.parallel.trainer import cross_entropy_loss
    from tf_operator_tpu.utils.summaries import SummaryWriter

    mesh = make_mesh({"dp": len(jax.devices())})
    n_proc = jax.process_count()
    per_proc = max(args.batch_size // n_proc, 1)

    r = np.random.RandomState(0)
    local = {
        "image": jnp.asarray(r.rand(per_proc, 28, 28, 1), jnp.float32),
        "label": jnp.asarray(r.randint(0, 10, size=(per_proc,))),
    }

    writer = SummaryWriter(args.summary_dir, process_id=jax.process_index())
    trainer = Trainer(
        MnistCNN(),
        TrainerConfig(
            optimizer="sgd",
            learning_rate=args.learning_rate,
            summary_every=args.summary_every,
        ),
        mesh,
        cross_entropy_loss,
        local,
        summary_writer=writer,
    )
    ck = None
    start = 0
    if args.checkpoint_dir:
        from tf_operator_tpu.parallel import TrainerCheckpointer

        ck = TrainerCheckpointer(args.checkpoint_dir)
        restored = ck.restore_latest(trainer)
        if restored is not None:
            start = restored
            print(f"resumed from checkpoint step {restored}", flush=True)

    batch = trainer.shard_batch(local)
    last = None
    for _ in range(start, args.steps):
        last = trainer.train_step(batch)
    if ck is not None:
        if last is not None:  # trained this run: persist the new step
            ck.save(trainer, wait=True)
        ck.close()
    writer.close()
    final = f"final loss {float(last['loss']):.4f}" if last is not None else (
        f"already complete at step {start}"
    )
    print(
        f"process {jax.process_index()}/{n_proc}: {final}, "
        f"series in {args.summary_dir}",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
