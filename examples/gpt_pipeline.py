"""Pipeline-parallel GPT training — the pp axis end to end.

Parity note: the reference has no pipeline parallelism (SURVEY.md §2b
marks PP absent/optional); this example goes beyond parity: a causal LM
whose decoder stack is split into pp stages (models/pipelined_lm.py),
parameters stage-sharded over the pp mesh axis, activations flowing
stage-to-stage by ppermute under the GPipe schedule, composed with data
parallelism on the remaining devices.

Runs anywhere with >= pp devices: virtual CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8), a TPU slice, or
multi-process under the operator.
"""

from __future__ import annotations

import sys

from tf_operator_tpu.runtime import initialize
from tf_operator_tpu.runtime.harness import standard_parser


def main() -> int:
    parser = standard_parser(__doc__.split("\n")[0], learning_rate=1e-3)
    parser.add_argument("--pp", type=int, default=2, help="pipeline stages")
    parser.add_argument("--microbatches", type=int, default=4)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--n-layers", type=int, default=4)
    parser.add_argument("--hidden", type=int, default=128)
    parser.add_argument(
        "--family", choices=["gpt", "llama"], default="gpt",
        help="block family for the stages: gpt (learned pos, relu) or "
             "llama (RoPE + GQA + SwiGLU, no biases)",
    )
    args = parser.parse_args()

    initialize()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tf_operator_tpu.models import PipelinedLM
    from tf_operator_tpu.models.transformer import TransformerConfig
    from tf_operator_tpu.parallel import make_mesh

    n_dev = len(jax.devices())
    if n_dev % args.pp:
        print(f"{n_dev} devices not divisible by pp={args.pp}", file=sys.stderr)
        return 2
    mesh = make_mesh({"pp": args.pp, "dp": n_dev // args.pp})

    llama = args.family == "llama"
    cfg = TransformerConfig(
        vocab_size=512,
        hidden=args.hidden,
        n_heads=4,
        head_dim=args.hidden // 4,
        n_layers=args.n_layers,
        mlp_dim=(11 * args.hidden // 4) if llama else 4 * args.hidden,
        max_len=args.seq_len,
        rope=llama,
        attn_bias=not llama,
        n_kv_heads=2 if llama else None,
    )
    model = PipelinedLM(
        cfg, mesh, microbatches=args.microbatches,
        activation="swiglu" if llama else "relu",
    )
    # every process inits identically (same seed); shard_params lays the
    # stages onto the pp axis — across processes when the mesh spans them
    params = model.shard_params(model.init(jax.random.PRNGKey(0)))

    dp = mesh.shape["dp"]
    # batch-per-device keeps its usual meaning (rows per dp shard); it
    # is rounded UP to a multiple of microbatches so each microbatch's
    # rows still shard evenly over dp
    m = args.microbatches
    bpd = -(-max(args.batch_per_device, 1) // m) * m
    batch = bpd * dp
    from jax.sharding import NamedSharding, PartitionSpec as P

    r = np.random.RandomState(0)
    ids_np = r.randint(0, cfg.vocab_size, size=(batch, args.seq_len)).astype(np.int32)
    if jax.process_count() == 1:
        ids = jnp.asarray(ids_np)
    else:
        # identical global batch on every process, laid out replicated
        ids = jax.make_array_from_callback(
            ids_np.shape, NamedSharding(mesh, P()), lambda idx: ids_np[idx]
        )

    tx = optax.adamw(args.learning_rate)
    with mesh:
        opt = jax.jit(tx.init)(params)

    @jax.jit
    def step(p, o, b):
        loss, grads = jax.value_and_grad(model.loss)(p, b)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    class _Loop:
        """Adapts the functional (params, opt) step to the harness's
        trainer protocol, so the loop/summary/exit contract stays in
        ONE place (runtime/harness.py)."""

        def __init__(self, params, opt):
            self.params, self.opt = params, opt

        def train_step(self, batch):
            self.params, self.opt, loss = step(self.params, self.opt, batch)
            return {"loss": loss}

    from tf_operator_tpu.runtime.harness import train_loop

    loop = _Loop(params, opt)
    with mesh:
        train_loop(
            loop,
            ids,
            args.steps,
            tag=f"{args.family} pp={args.pp} dp={dp} mb={args.microbatches}",
            # _Loop has no train_steps: K>1 still windows the metric
            # resolution (no per-step sync), dispatch stays per-step
            steps_per_sync=args.steps_per_sync,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
