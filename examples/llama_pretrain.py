"""Modern-decoder byte-level pretraining + generation.

The reference's examples stop at 2019-era TF families; this one shows
the framework's current-generation path end to end:

- llama architecture (RoPE + RMSNorm + SwiGLU + GQA, models/llama.py),
  or ``--family moe`` for the routed-expert LM (models/moe.py) trained
  over an expert-parallel mesh and decoded droplessly
- byte-level REAL data from disk through the grain pipeline
  (data/text.py — per-process disjoint shards, no synthetic tensors)
- logical sharding over whatever mesh fits the world (fsdp when
  multi-device for llama, dp×ep for moe; sp=ring/ulysses work for
  llama too — see tests/test_llama.py)
- after training: KV-cache generation (models/decode.py) prints an
  actual sampled continuation, decoded back to text.

Single process:   python examples/llama_pretrain.py --steps 60
MoE:              python examples/llama_pretrain.py --family moe --steps 60
Under the operator: examples/manifests/llama_pretrain.yaml
"""

from __future__ import annotations

import functools
import sys

from tf_operator_tpu.runtime import initialize
from tf_operator_tpu.runtime.harness import batch_sizes, standard_parser, train_loop


def main() -> int:
    parser = standard_parser(
        __doc__.split("\n")[0], steps=60, batch_per_device=8, learning_rate=3e-3
    )
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--data-dir", default="examples/data/text")
    parser.add_argument(
        "--family", choices=["llama", "moe"], default="llama",
        help="llama (RoPE+GQA+SwiGLU over fsdp[/sp]) or moe "
             "(top-2 routed experts over dp x ep; ignores --sp)",
    )
    parser.add_argument(
        "--experts", type=int, default=4, help="moe family: expert count"
    )
    parser.add_argument("--sp", type=int, default=1, help="sequence-parallel axis size")
    parser.add_argument("--sp-impl", choices=["ring", "ulysses"], default="ring")
    parser.add_argument("--generate", type=int, default=48, help="tokens to sample after training")
    parser.add_argument(
        "--chunked-loss", type=int, default=0, metavar="N",
        help="stream the vocab projection + cross-entropy over N "
        "sequence chunks (llama_loss_chunked) — the memory knob for "
        "big-batch/long-seq runs; 0 = full-logits loss",
    )
    parser.add_argument(
        "--export-dir", default="",
        help="write a params-only serving artifact here after training "
             "(consume with examples/serve_lm.py)",
    )
    args = parser.parse_args()

    initialize()

    import jax
    import numpy as np

    from tf_operator_tpu.data import as_lm_batches, decode_bytes, ensure_text, make_text_loader
    from tf_operator_tpu.data.synthetic import wait_for_dataset
    from tf_operator_tpu.data.text import text_meta
    from tf_operator_tpu.models import (
        generate,
        llama_loss,
        llama_loss_chunked,
        llama_tiny,
        moe_lm_loss,
        moe_tiny,
    )
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    n_dev = len(jax.devices())
    if args.family == "moe":
        import math

        # ep must divide BOTH the expert count (the expert axis shards
        # over it) AND the per-process device count: the batch rides
        # (dp, fsdp), so dp = n_dev/ep has to keep one distinct batch
        # shard per process — ep spanning a whole process would leave
        # the batch "replicated" across hosts that actually hold
        # DISJOINT data shards (silently wrong gradients)
        local = max(n_dev // jax.process_count(), 1)
        ep = math.gcd(args.experts, local)
        shape = {"ep": ep, "dp": max(n_dev // ep, 1)}
    else:
        shape = {"sp": args.sp, "fsdp": max(n_dev // max(args.sp, 1), 1)}
    mesh = make_mesh(shape)

    meta = text_meta(seq_len=args.seq_len)
    if jax.process_index() == 0:
        ensure_text(args.data_dir, seq_len=args.seq_len)
    else:
        wait_for_dataset(args.data_dir, meta=meta)

    _, local_batch = batch_sizes(args.batch_per_device)
    loader = make_text_loader(args.data_dir, local_batch, num_epochs=None)
    batches = as_lm_batches(loader)
    first = next(batches)

    if args.family == "moe":
        model = moe_tiny(
            vocab_size=256, max_len=args.seq_len,
            num_experts=args.experts, mesh=mesh,
        )
        loss_fn = moe_lm_loss
        tag = f"moe bytes dp={shape['dp']} ep={shape['ep']} E={args.experts}"
    else:
        model = llama_tiny(
            vocab_size=256, max_len=args.seq_len, mesh=mesh, sp_impl=args.sp_impl
        )
        loss_fn = (
            functools.partial(llama_loss_chunked, n_chunks=args.chunked_loss)
            if args.chunked_loss else llama_loss
        )
        tag = f"llama bytes fsdp={shape['fsdp']} sp={args.sp}({args.sp_impl})"
    trainer = Trainer(
        model,
        TrainerConfig(learning_rate=args.learning_rate, warmup_steps=10),
        mesh,
        loss_fn,
        first,
        init_args=(first["input_ids"],),
        shardings="logical",
    )
    sharded = (trainer.shard_batch(b) for b in batches)
    train_loop(
        trainer, sharded, args.steps, tag=tag,
        steps_per_sync=args.steps_per_sync,
    )

    if args.export_dir:
        # collective: every process writes its shards directly
        import os

        from tf_operator_tpu.parallel import export_params

        export_params(trainer, os.path.abspath(args.export_dir))
        if jax.process_index() == 0:
            print(f"exported serving artifact to {args.export_dir}", flush=True)

    if args.generate:
        # params are globally sharded; the gather is COLLECTIVE — every
        # process participates, process 0 prints
        from tf_operator_tpu.runtime.harness import gather_params

        params = gather_params(trainer)
        if jax.process_index() == 0:
            if args.family == "moe":
                gen_model = moe_tiny(
                    vocab_size=256, max_len=args.seq_len, num_experts=args.experts
                )
            else:
                gen_model = llama_tiny(vocab_size=256, max_len=args.seq_len)
            prompt_txt = "the sharded "
            prompt = np.frombuffer(prompt_txt.encode(), np.uint8)[None].astype(np.int32)
            # the KV cache is max_len slots: cap the ask so a short
            # --seq-len can't fail the job after training succeeded
            n_new = min(args.generate, args.seq_len - prompt.shape[1])
            if n_new < 1:
                print(f"seq-len {args.seq_len} leaves no room after the "
                      f"{prompt.shape[1]}-byte prompt; skipping generation")
            else:
                out = generate(gen_model, params, prompt, max_new_tokens=n_new)
                print(f"prompt: {prompt_txt!r}")
                print(f"sample: {decode_bytes(out[0, prompt.shape[1]:])!r}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
