"""BERT pretraining — BASELINE.md config 3.

Parity: the reference's config 3 is "BERT-base pretrain,
ParameterServerStrategy, 2 PS + 4 workers".  Parameter servers have no
TPU analogue (SURVEY.md §2b): the PS role — parameters living off the
workers, updated centrally — translates to **fully-sharded (FSDP)
params+optimizer over the mesh**, where every device holds a shard and
XLA's reduce-scatter/all-gather replace the PS push/pull RPCs.  This is
a deliberate semantic translation, documented here per the survey.

Synthetic MLM batches (15% masked); --model bert_base on the chip,
bert_tiny for CPU e2e runs under the operator.
"""

from __future__ import annotations

import argparse
import sys

from tf_operator_tpu.runtime import initialize


def synthetic_mlm_batch(rng, n: int, seq: int, vocab: int, mask_id: int = 4):
    import numpy as np

    r = np.random.RandomState(rng)
    ids = r.randint(5, vocab, size=(n, seq)).astype(np.int32)
    labels = np.full((n, seq), -100, dtype=np.int32)
    mask = r.rand(n, seq) < 0.15
    labels[mask] = ids[mask]
    ids = np.where(mask, mask_id, ids)
    return {"input_ids": ids, "labels": labels}


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--model", choices=["bert_base", "bert_tiny"], default="bert_base")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--batch-per-device", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--learning-rate", type=float, default=1e-4)
    args = parser.parse_args()

    initialize()

    import jax

    from tf_operator_tpu.models import bert_base, bert_tiny, mlm_loss
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    n_dev = len(jax.devices())
    # the PS-analogue: every device a parameter shard (fsdp = whole mesh)
    mesh = make_mesh({"fsdp": n_dev})

    if args.model == "bert_base":
        model, vocab, seq = bert_base(max_len=args.seq_len), 30522, args.seq_len
    else:
        model, vocab, seq = bert_tiny(max_len=args.seq_len), 1024, args.seq_len

    local_batch = args.batch_per_device * n_dev // jax.process_count()
    batch = synthetic_mlm_batch(jax.process_index(), local_batch, seq, vocab)

    trainer = Trainer(
        model,
        TrainerConfig(learning_rate=args.learning_rate, warmup_steps=10),
        mesh,
        mlm_loss,
        batch,
        init_args=(batch["input_ids"],),
        shardings="logical",
    )
    sharded = trainer.shard_batch(batch)
    losses = []
    for _ in range(args.steps):
        metrics = trainer.train_step(sharded)
        losses.append(float(metrics["loss"]))

    print(
        f"process {jax.process_index()}/{jax.process_count()}: "
        f"{args.model} fsdp={mesh.shape['fsdp']} "
        f"mlm loss {losses[0]:.4f} -> {losses[-1]:.4f}",
        flush=True,
    )
    if args.steps >= 20 and not losses[-1] < losses[0]:
        print("loss did not decrease", file=sys.stderr, flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
