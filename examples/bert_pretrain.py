"""BERT pretraining — BASELINE.md config 3.

Parity: the reference's config 3 is "BERT-base pretrain,
ParameterServerStrategy, 2 PS + 4 workers".  Parameter servers have no
TPU analogue (SURVEY.md §2b): the PS role — parameters living off the
workers, updated centrally — translates to **fully-sharded (FSDP)
params+optimizer over the mesh**, where every device holds a shard and
XLA's reduce-scatter/all-gather replace the PS push/pull RPCs.  This is
a deliberate semantic translation, documented here per the survey.

Synthetic MLM batches (15% masked); --model bert_base on the chip,
bert_tiny for CPU e2e runs under the operator.
"""

from __future__ import annotations

import sys

from tf_operator_tpu.runtime import initialize
from tf_operator_tpu.runtime.harness import standard_parser, train_loop


def synthetic_mlm_batch(rng, n: int, seq: int, vocab: int, mask_id: int = 4):
    import numpy as np

    r = np.random.RandomState(rng)
    ids = r.randint(5, vocab, size=(n, seq)).astype(np.int32)
    labels = np.full((n, seq), -100, dtype=np.int32)
    mask = r.rand(n, seq) < 0.15
    labels[mask] = ids[mask]
    ids = np.where(mask, mask_id, ids)
    return {"input_ids": ids, "labels": labels}


def main() -> int:
    parser = standard_parser(
        __doc__.split("\n")[0], batch_per_device=8, learning_rate=1e-4
    )
    parser.add_argument("--model", choices=["bert_base", "bert_tiny"], default="bert_base")
    parser.add_argument("--seq-len", type=int, default=128)
    args = parser.parse_args()

    initialize()

    import jax

    from tf_operator_tpu.models import bert_base, bert_tiny, mlm_loss
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    n_dev = len(jax.devices())
    # the PS-analogue: every device a parameter shard (fsdp = whole mesh)
    mesh = make_mesh({"fsdp": n_dev})

    if args.model == "bert_base":
        model, vocab, seq = bert_base(max_len=args.seq_len), 30522, args.seq_len
    else:
        model, vocab, seq = bert_tiny(max_len=args.seq_len), 1024, args.seq_len

    from tf_operator_tpu.runtime.harness import batch_sizes

    _, local_batch = batch_sizes(args.batch_per_device)
    batch = synthetic_mlm_batch(jax.process_index(), local_batch, seq, vocab)

    trainer = Trainer(
        model,
        TrainerConfig(learning_rate=args.learning_rate, warmup_steps=10),
        mesh,
        mlm_loss,
        batch,
        init_args=(batch["input_ids"],),
        shardings="logical",
    )
    sharded = trainer.shard_batch(batch)
    train_loop(
        trainer, sharded, args.steps,
        tag=f"{args.model} fsdp={mesh.shape['fsdp']}",
        steps_per_sync=args.steps_per_sync,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
