"""Multi-slice workload: proves the DCN/megascale wiring end-to-end.

Parity: SURVEY.md §2c — multi-slice TPU jobs ride DCN with megascale
env describing the slice topology, while jax.distributed forms ONE
world across every host of every slice.  Each process asserts the
operator-injected MEGASCALE_* / TPU_WORKER_* env is consistent with its
position in the world, then allgathers across all slices.

On CPU (tier-3 e2e) the megascale vars are inert to JAX but the
injection contract is identical to the real-TPU path — that contract is
what this workload pins from INSIDE the worker process (the golden-file
tests pin it from outside).
"""

import os
import sys

from tf_operator_tpu.runtime import initialize


def main() -> int:
    ctx = initialize()
    import jax
    import jax.numpy as jnp
    from jax.experimental.multihost_utils import process_allgather

    n = jax.process_count()
    pid = jax.process_index()

    num_slices = int(os.environ["MEGASCALE_NUM_SLICES"])
    slice_id = int(os.environ["MEGASCALE_SLICE_ID"])
    worker_id = int(os.environ["TPU_WORKER_ID"])
    hostnames = os.environ["TPU_WORKER_HOSTNAMES"].split(",")
    hosts_per_slice = len(hostnames)

    # one world spanning every host of every slice
    assert n == num_slices * hosts_per_slice, (n, num_slices, hosts_per_slice)
    # this process's position in the world matches its slice coordinates
    assert slice_id == pid // hosts_per_slice, (slice_id, pid, hosts_per_slice)
    assert worker_id == pid % hosts_per_slice, (worker_id, pid, hosts_per_slice)
    # hostnames list the *own* slice's hosts, one per host VM.  (Their
    # content is backend-dependent — DNS names on a cluster backend,
    # loopback on the local backend — and is pinned by the golden-file
    # tests; here we pin the structure.)
    assert hosts_per_slice >= 1 and all(hostnames), hostnames

    gathered = process_allgather(jnp.array([float(pid)]))
    assert gathered.tolist() == [[float(i)] for i in range(n)]
    print(
        f"process {pid}/{n}: slice {slice_id}/{num_slices} worker {worker_id} "
        f"megascale ok, allgather -> {gathered.ravel().tolist()}",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
