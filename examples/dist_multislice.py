"""Multi-slice workload: the DCN/megascale wiring AND the slice-aware
training stack, end-to-end (ISSUE 14 — promoted from env-assert +
allgather to a real workload).

Parity: SURVEY.md §2c — multi-slice TPU jobs ride DCN with megascale
env describing the slice topology, while jax.distributed forms ONE
world across every host of every slice.  Each process:

1. asserts the operator-injected MEGASCALE_* / TPU_WORKER_* env is
   consistent with its position in the world, then allgathers across
   all slices (the PR 5 dryrun contract, kept verbatim);
2. builds the SLICE-AWARE mesh — ``make_mesh`` auto-detects the slice
   count from the injected env, puts ``dp`` across slices (DCN) and
   ``fsdp`` within a slice (ICI) — and runs a few fused train steps
   whose gradient sync rides the hierarchical two-stage psum
   (parallel/collectives.py: only 1/intra_slice_size of the gradient
   bytes cross DCN);
3. process 0 prints the grad-sync ledger as the stdout tail —
   ``MULTISLICE_LEDGER {...}`` — so the MULTICHIP artifact records the
   byte accounting the bench section measures.

On CPU (tier-3 e2e) the megascale vars are inert to JAX but the
injection contract and the program structure (mesh layout, collective
decomposition) are identical to the real-TPU path.  Run with a single
slice (no MEGASCALE env) the same workload degenerates to the flat
1-slice mesh — the contract tests pin that equivalence.
"""

import json
import os
import sys

from tf_operator_tpu.runtime import initialize


def main() -> int:
    ctx = initialize()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.multihost_utils import process_allgather

    n = jax.process_count()
    pid = jax.process_index()

    from tf_operator_tpu.bootstrap.tpu_env import detected_slice_topology

    num_slices, slice_id = detected_slice_topology()
    if num_slices > 1:
        # -- the PR 5 env contract, asserted from INSIDE the worker ----
        assert slice_id == int(os.environ["MEGASCALE_SLICE_ID"])
        worker_id = int(os.environ["TPU_WORKER_ID"])
        hostnames = os.environ["TPU_WORKER_HOSTNAMES"].split(",")
        hosts_per_slice = len(hostnames)
        # one world spanning every host of every slice
        assert n == num_slices * hosts_per_slice, (n, num_slices, hosts_per_slice)
        # this process's position in the world matches its slice coords
        assert slice_id == pid // hosts_per_slice, (slice_id, pid, hosts_per_slice)
        assert worker_id == pid % hosts_per_slice, (worker_id, pid, hosts_per_slice)
        # hostnames list the *own* slice's hosts, one per host VM (their
        # content is backend-dependent and pinned by the golden tests)
        assert hosts_per_slice >= 1 and all(hostnames), hostnames
    else:
        worker_id, hosts_per_slice = pid, n

    gathered = process_allgather(jnp.array([float(pid)]))
    assert gathered.tolist() == [[float(i)] for i in range(n)]
    print(
        f"process {pid}/{n}: slice {slice_id if slice_id is not None else 0}"
        f"/{num_slices} worker {worker_id} "
        f"megascale ok, allgather -> {gathered.ravel().tolist()}",
        flush=True,
    )

    # -- the real workload: fused train steps on the slice-aware mesh --
    import optax

    from tf_operator_tpu.models import MnistCNN
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.parallel.mesh import mesh_axis_links
    from tf_operator_tpu.runtime.harness import train_loop

    # dp across slices (auto-detected from the injected env), fsdp
    # over each slice's hosts/chips
    mesh = make_mesh({"dp": num_slices, "fsdp": -1})
    links = mesh_axis_links(mesh)
    n_dev = len(jax.devices())

    def loss_fn(params, state, batch, rng):
        logits = state.apply_fn({"params": params}, batch["image"], train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        return loss, {}

    per_dev = 8
    local_rows = per_dev * len(jax.local_devices())
    r = np.random.RandomState(pid)
    local = {
        "image": jnp.asarray(r.rand(local_rows, 28, 28, 1), jnp.float32),
        "label": jnp.asarray(r.randint(0, 10, size=(local_rows,))),
    }
    example = {
        "image": jnp.zeros((per_dev * n_dev, 28, 28, 1), jnp.float32),
        "label": jnp.zeros((per_dev * n_dev,), jnp.int32),
    }
    trainer = Trainer(
        MnistCNN(),
        TrainerConfig(optimizer="sgd", learning_rate=0.05),
        mesh,
        loss_fn,
        example,
    )
    sharded = trainer.shard_batch(local)
    losses = train_loop(
        trainer, sharded, 6, steps_per_sync=3, assert_decreasing=False,
        tag="multislice",
    )
    assert all(np.isfinite(losses)), losses

    if pid == 0:
        ledger = {
            "grad_sync": trainer.grad_sync,
            "mesh": {ax: int(s) for ax, s in mesh.shape.items() if s > 1},
            "axis_fabric": {ax: links[ax] for ax in ("dp", "fsdp")},
            "steps": 6,
            "final_loss": round(float(losses[-1]), 4),
        }
        if trainer.grad_sync_plan is not None:
            ledger.update(trainer.grad_sync_plan.ledger())
        # the MULTICHIP tail: one parseable line with the grad-sync
        # byte accounting
        print("MULTISLICE_LEDGER " + json.dumps(ledger), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
