"""ViT image classification — transformer member of the image family.

Beyond-reference example (the reference's image workloads are ResNet
CNNs; SURVEY.md §6 configs 2/4): same synchronous data-parallel shape
as resnet_dp.py, with the encoder stack, logical sharding rules, and
attention dispatcher shared with the text families.  ``--tp`` shards
heads/MLP over a tensor axis to demonstrate image models on a dp×tp
mesh — the reference had no analogue.

Runs single-process (the real chip) or multi-process under the
operator's local backend (CPU collectives), like every example.
"""

from __future__ import annotations

import sys

from tf_operator_tpu.runtime import initialize
from tf_operator_tpu.runtime.harness import batch_sizes, standard_parser, train_loop


def main() -> int:
    parser = standard_parser(__doc__.split("\n")[0], learning_rate=3e-3)
    parser.add_argument("--model", choices=["vit_b16", "vit_tiny"], default="vit_tiny")
    parser.add_argument("--image-size", type=int, default=32)
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--tp", type=int, default=1, help="tensor axis size")
    args = parser.parse_args()

    initialize()

    import jax
    import numpy as np

    from tf_operator_tpu.models import vit_b16, vit_loss, vit_tiny
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    n_dev = len(jax.devices())
    assert n_dev % args.tp == 0, (n_dev, args.tp)
    mesh = make_mesh({"dp": n_dev // args.tp, "tp": args.tp})

    _, local_batch = batch_sizes(args.batch_per_device)
    rng = np.random.RandomState(jax.process_index())
    batch = {
        "image": rng.rand(local_batch, args.image_size, args.image_size, 3).astype(
            np.float32
        ),
        "label": rng.randint(0, args.num_classes, size=(local_batch,)).astype(
            np.int32
        ),
    }

    model_fn = vit_b16 if args.model == "vit_b16" else vit_tiny
    trainer = Trainer(
        model_fn(image_size=args.image_size, n_classes=args.num_classes, mesh=mesh),
        TrainerConfig(optimizer="adamw", learning_rate=args.learning_rate),
        mesh,
        vit_loss,
        batch,
        shardings="logical",
    )
    sharded = trainer.shard_batch(batch)
    tag = f"{args.model} dp={mesh.shape['dp']} tp={mesh.shape['tp']}"
    train_loop(
        trainer, sharded, args.steps, tag=tag,
        steps_per_sync=args.steps_per_sync,
    )
    stats = trainer.benchmark(batch, steps=max(args.steps // 2, 5), warmup=0)
    print(f"{tag}: {stats['examples_per_sec']:.1f} ex/s global", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
