"""Profile-driven ResNet-50 step-time experiments (VERDICT r2 item 1).

Ad-hoc runner for the single-chip MFU push.  Measures step time / MFU
for bench variants and can capture a perfetto trace of the hot step and
aggregate the top device ops (tensorboard_plugin_profile is not in the
image, so we parse the perfetto JSON ourselves).

Usage (on the TPU box):
  python benchmarks/profile_resnet.py --variant baseline --batch 256
  python benchmarks/profile_resnet.py --variant s2d --batch 512
  python benchmarks/profile_resnet.py --variant s2d --batch 256 --trace /tmp/rn50-trace

Findings are written up in benchmarks/PROFILE.md.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# every runnable variant; argparse choices and build_trainer validate
# against this single tuple so an unknown variant fails the same way
# from the CLI and from a programmatic caller (measure.py, tpu_window)
VARIANTS = ("baseline", "s2d", "noclip", "bnbf16", "pbf16", "bnfold", "fusedbn")


def build_trainer(variant: str, batch_per_chip: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models import resnet50
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

    if variant not in VARIANTS:
        raise ValueError(
            f"unknown variant {variant!r}: expected one of {VARIANTS}"
        )
    n_dev = len(jax.devices())
    mesh = make_mesh({"dp": n_dev})
    rng = np.random.RandomState(0)
    global_batch = batch_per_chip * n_dev
    batch = {
        "image": jnp.asarray(
            rng.rand(global_batch, 224, 224, 3).astype(np.float32), dtype=jnp.bfloat16
        ),
        "label": jnp.asarray(rng.randint(0, 1000, size=(global_batch,))),
    }
    kw = {}
    if variant == "s2d":
        kw["stem"] = "space_to_depth"
    if variant == "bnbf16":
        # PROFILE.md: stem and batch scaling are exhausted; the rest is
        # bwd convs + BN chains — this probes the BN half
        kw["bn_param_dtype"] = jnp.bfloat16
    if variant == "fusedbn":
        # ISSUE 19 tentpole: train-mode BN+ReLU(+residual) as one fused
        # custom_vjp op ("auto" picks the pallas kernel on a single
        # TPU chip, the xla composition elsewhere — never silently)
        kw["norm"] = "fused"
    model = resnet50(**kw)
    cfg = TrainerConfig(optimizer="sgd", learning_rate=0.1, momentum=0.9)
    if variant == "noclip":
        cfg.grad_clip = 0.0
    if variant == "pbf16":
        # bf16 param+momentum storage: probes the trace-shown ceiling —
        # the f32 master-weight cast/copy swarm (PROFILE.md r5) — by
        # removing it entirely; accuracy note in TrainerConfig
        cfg.param_dtype = jnp.bfloat16
    trainer = Trainer(model, cfg, mesh, batchnorm_cross_entropy_loss, batch)
    return trainer, batch


def step_flops(trainer, batch) -> float:
    import flax.linen as nn

    with trainer.mesh, nn.logical_axis_rules(trainer._rules):
        compiled = trainer._step.lower(trainer.state, batch).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return float(ca.get("flops", 0.0))


def run_variant(variant: str, batch_per_chip: int, steps: int, trace_dir: str | None):
    import jax

    trainer, batch = build_trainer(variant, batch_per_chip)
    sharded = trainer.shard_batch(batch)
    flops = step_flops(trainer, sharded)
    stats = trainer.benchmark(batch, steps=steps, warmup=5)
    peak = 197e12  # v5e bf16
    achieved = flops * stats["steps_per_sec"]
    out = {
        "variant": variant,
        "batch_per_chip": batch_per_chip,
        "step_ms": round(stats["step_ms"], 2),
        "examples_per_sec": round(stats["examples_per_sec"], 1),
        "tflops": round(achieved / 1e12, 1),
        "mfu": round(achieved / peak, 4),
    }
    print(json.dumps(out), flush=True)
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            for _ in range(3):
                trainer.train_step(batch)
            jax.effects_barrier()
        summarize_xplane(trace_dir)
        # the category half (VERDICT r5 next #4, wired): every traced
        # run also prints the per-family share table AND its committed
        # markdown shape, so the window artifact carries the FLOPS.md
        # "trace category table" rows without a second invocation
        import trace_categories

        tables = trace_categories.category_tables(trace_dir)
        if tables:
            print(trace_categories.format_text(tables))
            print("\n--- markdown (FLOPS.md 'trace category table') ---")
            print(trace_categories.format_markdown(tables))
    return out


def summarize_xplane(trace_dir: str, top: int = 30):
    """Aggregate device-op durations from the .xplane.pb the profiler
    always writes (no tensorboard plugin needed — TF ships the proto)."""

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        print("no xplane found under", trace_dir)
        return
    path = max(paths, key=os.path.getmtime)
    space = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        space.ParseFromString(f.read())
    for plane in space.planes:
        # device planes: "/device:TPU:0" (tpu) / "/host:CPU" XLA client
        # lines (cpu smoke).  Skip the pure-python host plane lines.
        interesting = (
            "TPU" in plane.name
            or "/device:" in plane.name
            or plane.name == "/host:CPU"
        )
        if not interesting:
            continue
        dur_by_name = defaultdict(float)
        cnt_by_name = defaultdict(int)
        total = 0.0
        for line in plane.lines:
            # skip host-side python callstack / step-marker lines; keep
            # XLA op/module lines (TPU planes) and XLA client lines
            # (/host:CPU smoke)
            if line.name in ("python", "Steps"):
                continue
            for ev in line.events:
                meta = plane.event_metadata.get(ev.metadata_id)
                name = meta.name if meta else "?"
                dur = ev.duration_ps / 1e12
                dur_by_name[(line.name, name)] += dur
                cnt_by_name[(line.name, name)] += 1
                total += dur
        if not dur_by_name:
            continue
        print(f"\n== plane {plane.name}: total event time {total*1e3:.1f} ms ==")
        for (lname, name), dur in sorted(dur_by_name.items(), key=lambda kv: -kv[1])[:top]:
            print(f"{dur*1e3:10.2f} ms  x{cnt_by_name[(lname, name)]:<5d} [{lname[:16]}] {name[:100]}")


def summarize_trace(trace_dir: str, top: int = 30):
    """Aggregate device-op durations from the perfetto trace JSON."""

    paths = glob.glob(os.path.join(trace_dir, "**", "perfetto_trace.json.gz"), recursive=True)
    if not paths:
        print("no perfetto trace found under", trace_dir)
        return
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data["traceEvents"] if isinstance(data, dict) else data
    # find TPU device-op track pids (names like "/device:TPU:0" or "TPU core")
    tid_names = {}
    pid_names = {}
    for ev in events:
        if ev.get("ph") == "M":
            if ev.get("name") == "process_name":
                pid_names[ev["pid"]] = ev["args"].get("name", "")
            if ev.get("name") == "thread_name":
                tid_names[(ev["pid"], ev["tid"])] = ev["args"].get("name", "")
    dur_by_name = defaultdict(float)
    cnt_by_name = defaultdict(int)
    total = 0.0
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        pname = pid_names.get(ev.get("pid"), "")
        tname = tid_names.get((ev.get("pid"), ev.get("tid")), "")
        if "TPU" not in pname and "TPU" not in tname and "tpu" not in pname.lower():
            continue
        # XLA op tracks: skip steps/trace frames
        name = ev.get("name", "?")
        dur_by_name[name] += ev["dur"]
        cnt_by_name[name] += 1
        total += ev["dur"]
    print(f"\n== trace {os.path.basename(path)}: total device-op time {total/1e3:.1f} ms ==")
    for name, dur in sorted(dur_by_name.items(), key=lambda kv: -kv[1])[:top]:
        print(f"{dur/1e3:10.2f} ms  x{cnt_by_name[name]:<4d} {name[:110]}")


def run_bnfold(batch_per_chip: int, steps: int, trace_dir: "str | None"):
    """Eval-mode BN-fold A/B (ISSUE 14 satellite / ROADMAP item 2): the
    inference forward pass with every BatchNorm folded into its conv
    (models/resnet.fold_batchnorm) vs the stock eval pass — same
    params, numerics-pinned, slope-timed.  Training CANNOT fold (live
    batch statistics), so this measures the inference share of the
    FLOPS.md elementwise/BN ceiling; the train-side note lives in
    FLOPS.md "BN-fold"."""

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _peak_flops
    from tf_operator_tpu.models import fold_batchnorm, resnet50
    from tf_operator_tpu.parallel.trainer import hard_sync

    n_dev = len(jax.devices())
    rng = np.random.RandomState(0)
    x = jnp.asarray(
        rng.rand(batch_per_chip * n_dev, 224, 224, 3).astype(np.float32),
        dtype=jnp.bfloat16,
    )
    model = resnet50()
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=False)
    folded_model = resnet50(bn_fold=True)
    folded_vars = fold_batchnorm(variables)

    ref_fn = jax.jit(lambda v, a: model.apply(v, a, train=False))
    fold_fn = jax.jit(lambda v, a: folded_model.apply(v, a, train=False))
    ref = ref_fn(variables, x)
    out = fold_fn(folded_vars, x)
    max_err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))

    def slope_ms(fn, v) -> float:
        # two-window slope (Trainer._slope_time protocol): fixed costs
        # cancel, honest per-call device time on any platform
        def window(n):
            t0 = time.perf_counter()
            for _ in range(n):
                r = fn(v, x)
            hard_sync(r)
            return time.perf_counter() - t0

        window(1)  # warm
        n1 = max(1, steps // 6)
        n2 = max(n1 + 1, steps - n1)
        t1, t2 = window(n1), window(n2)
        dt = (t2 - t1) / (n2 - n1)
        return 1e3 * (dt if dt > 0 else t2 / n2)

    def fwd_flops(fn, v):
        ca = fn.lower(v, x).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))

    ms_ref = slope_ms(ref_fn, variables)
    ms_fold = slope_ms(fold_fn, folded_vars)
    peak = _peak_flops(jax.devices()[0])
    out_row = {
        "variant": "bnfold",
        "batch_per_chip": batch_per_chip,
        "eval_ms_unfolded": round(ms_ref, 2),
        "eval_ms_folded": round(ms_fold, 2),
        "bnfold_eval_speedup": round(ms_ref / ms_fold, 3) if ms_fold else None,
        "max_abs_err": max_err,
        "fwd_mfu_unfolded": round(
            fwd_flops(ref_fn, variables) / (ms_ref / 1e3) / peak, 4
        ),
        "fwd_mfu_folded": round(
            fwd_flops(fold_fn, folded_vars) / (ms_fold / 1e3) / peak, 4
        ),
    }
    print(json.dumps(out_row), flush=True)
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            for _ in range(3):
                fold_fn(folded_vars, x)
            jax.effects_barrier()
        summarize_xplane(trace_dir)
        import trace_categories

        tables = trace_categories.category_tables(trace_dir)
        if tables:
            print(trace_categories.format_text(tables))
            print("\n--- markdown (FLOPS.md 'trace category table') ---")
            print(trace_categories.format_markdown(tables))
    return out_row


def run_fusedbn(batch_per_chip: int, steps: int, trace_dir: "str | None"):
    """Train-mode fused-BN A/B (ISSUE 19 tentpole measurement): the
    same ResNet-50 train step with ``norm="fused"`` vs stock
    ``nn.BatchNorm`` — identical init (scope/path parity), identical
    batch, numerics-probed, slope-timed.  The trace leg captures BOTH
    variants and diffs the reduce+elementwise+convert chain share, the
    category-level proof that the fusion killed the chains FLOPS.md
    blames for the ~0.32 train-MFU ceiling."""

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _peak_flops
    from tf_operator_tpu.models import resnet50
    from tf_operator_tpu.ops import fused_batchnorm

    out_row = {
        "variant": "fusedbn",
        "batch_per_chip": batch_per_chip,
        "resnet_fusedbn_backend": jax.default_backend(),
        # what "auto" resolves to here — chip: pallas, CPU smoke: xla
        "resnet_fusedbn_impl": resnet50(norm="fused")._resolve_norm(),
    }

    # interpret-numerics probe: the REAL kernel body via the pallas
    # interpreter on a small tensor, fwd+grad vs the xla reference —
    # committed even from a CPU smoke so the window artifact always
    # carries kernel-body evidence, not just composition timings
    xk = jnp.asarray(np.random.RandomState(1).rand(4, 9, 9, 24), jnp.float32)
    g = jnp.ones((24,), jnp.float32) * 1.3
    b = jnp.ones((24,), jnp.float32) * 0.2

    def probe(impl):
        def f(x):
            y, _, _ = fused_batchnorm(x, g, b, relu=True, impl=impl)
            return jnp.sum(y * y)

        y, _, _ = fused_batchnorm(xk, g, b, relu=True, impl=impl)
        return y, jax.grad(f)(xk)

    y_ref, dx_ref = probe("xla")
    y_int, dx_int = probe("pallas-interpret")
    out_row["resnet_fusedbn_interpret_fwd_err"] = float(
        jnp.max(jnp.abs(y_int - y_ref))
    )
    out_row["resnet_fusedbn_interpret_grad_err"] = float(
        jnp.max(jnp.abs(dx_int - dx_ref))
    )

    stock, batch = build_trainer("baseline", batch_per_chip)
    fused, _ = build_trainer("fusedbn", batch_per_chip)

    # loss probe BEFORE timing: 3 real train steps per variant from the
    # path-parity-identical init, max relative loss divergence
    loss_s = [float(stock.train_step(batch)["loss"]) for _ in range(3)]
    loss_f = [float(fused.train_step(batch)["loss"]) for _ in range(3)]
    out_row["resnet_fusedbn_loss_max_rel_err"] = float(
        np.max(np.abs(np.array(loss_s) - np.array(loss_f))
               / np.maximum(np.abs(np.array(loss_s)), 1e-12))
    )

    peak = _peak_flops(jax.devices()[0])
    sharded = stock.shard_batch(batch)
    rows = {}
    for tag, tr in (("stock", stock), ("fused", fused)):
        flops = step_flops(tr, sharded)
        stats = tr.benchmark(batch, steps=steps, warmup=5)
        rows[tag] = stats["step_ms"]
        out_row[f"resnet_fusedbn_step_ms_{tag}"] = round(stats["step_ms"], 2)
        out_row[f"resnet_fusedbn_mfu_{tag}"] = round(
            flops * stats["steps_per_sec"] / peak, 4
        )
    out_row["resnet_fusedbn_step_wall_ratio"] = (
        round(rows["stock"] / rows["fused"], 3) if rows["fused"] else None
    )

    if trace_dir:
        import trace_categories

        shares = {}
        for tag, tr in (("stock", stock), ("fused", fused)):
            tdir = f"{trace_dir}-{tag}"
            with jax.profiler.trace(tdir):
                for _ in range(3):
                    tr.train_step(batch)
                jax.effects_barrier()
            tables = trace_categories.category_tables(tdir)
            if not tables:
                print("no xplane found under", tdir)
                continue
            print(f"\n#### {tag} ({tdir})")
            print(trace_categories.format_text(tables))
            print("\n--- markdown (FLOPS.md 'trace category table') ---")
            print(trace_categories.format_markdown(tables))
            shares[tag] = trace_categories.chain_share(tables)
        if "stock" in shares and "fused" in shares:
            out_row["fusedbn_trace_chain_share_stock"] = round(
                shares["stock"], 4
            )
            out_row["fusedbn_trace_chain_share_fused"] = round(
                shares["fused"], 4
            )
            out_row["fusedbn_trace_chain_share_drop"] = round(
                shares["stock"] - shares["fused"], 4
            )
    print(json.dumps(out_row), flush=True)
    return out_row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--variant",
        default="baseline",
        choices=list(VARIANTS),
    )
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--trace", default=None)
    ap.add_argument("--summarize-only", default=None, help="just parse an existing trace dir")
    ap.add_argument(
        "--platform", default=None,
        help="force a jax platform (e.g. cpu for a smoke run) via "
             "jax.config — env-level JAX_PLATFORMS is re-pinned by this "
             "box's sitecustomize",
    )
    args = ap.parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.summarize_only:
        summarize_xplane(args.summarize_only)
        summarize_trace(args.summarize_only)
        return
    if args.variant == "bnfold":
        run_bnfold(args.batch, args.steps, args.trace)
        return
    if args.variant == "fusedbn":
        run_fusedbn(args.batch, args.steps, args.trace)
        return
    run_variant(args.variant, args.batch, args.steps, args.trace)


if __name__ == "__main__":
    main()
