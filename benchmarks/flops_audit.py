"""Settle the XLA-vs-analytic flop accounting (VERDICT r3 weak #2).

Round-3's open question (benchmarks/PROFILE.md): XLA cost-analysis said
~23.9 GFLOP/example for the ResNet-50 train step while "the analytic
estimate" said ~12.3 — a suspected 2× bwd-conv over-count.  This script
computes the analytic count from first principles (per-layer conv/dense
MAC arithmetic derived from kernel shapes × output shapes, no compiler
involved) and compares it against XLA's count for (a) the forward pass
alone and (b) the full fwd+bwd+update step.

Usage: python benchmarks/flops_audit.py [--batch 8] [--platform cpu]
Prints one JSON object; findings written up in benchmarks/FLOPS.md.

HLO flop counting is backend-independent arithmetic over instruction
shapes, so the CPU lowering settles the question without the chip; the
TPU lowering (run when the tunnel answers) only differs through
fusion-level rounding.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def analytic_fwd_macs(model, example, init_args=None) -> dict:
    """Per-example forward MACs from kernel shapes × output shapes.

    Walks the param tree; every conv kernel (kh, kw, cin, cout)
    contributes out_h·out_w·cout·kh·kw·cin MACs per example, every
    dense kernel (din, dout) contributes din·dout.  Output shapes come
    from flax capture_intermediates under eval_shape — pure shape
    arithmetic, nothing executes.
    """

    import jax
    import numpy as np

    def init_and_capture():
        variables = model.init(jax.random.PRNGKey(0), *(init_args or (example,)), train=False)
        _, inter = model.apply(
            variables, example, train=False, capture_intermediates=True
        )
        return variables, inter

    variables, intermediates = jax.eval_shape(init_and_capture)
    params = variables["params"]

    def leaf_outputs(tree):
        """module-path → output ShapeDtypeStruct for every captured call."""
        flat = {}

        def walk(node, path):
            if isinstance(node, dict):
                for k, v in node.items():
                    walk(v, path + (k,))
            elif isinstance(node, (list, tuple)):
                for v in node:
                    walk(v, path)
            else:
                flat[path] = node

        walk(tree, ())
        return flat

    outs = leaf_outputs(intermediates["intermediates"])

    def out_shape_for(module_path):
        # capture_intermediates stores outputs under <path>/__call__
        key = tuple(module_path) + ("__call__",)
        if key in outs:
            return outs[key].shape
        return None

    per_layer = []
    total_macs = 0.0
    flat_params = jax.tree_util.tree_leaves_with_path(params)
    for keypath, leaf in flat_params:
        names = [getattr(k, "key", getattr(k, "name", str(k))) for k in keypath]
        if names[-1] != "kernel":
            continue
        module_path = names[:-1]
        shape = leaf.shape
        out = out_shape_for(module_path)
        if len(shape) == 4:  # conv kernel (kh, kw, cin, cout)
            kh, kw, cin, cout = shape
            if out is None:
                raise RuntimeError(f"no captured output for conv {module_path}")
            _, oh, ow, oc = out
            assert oc == cout, (module_path, out, shape)
            macs = float(oh * ow * cout * kh * kw * cin)
        elif len(shape) == 2:  # dense (din, dout)
            macs = float(shape[0] * shape[1])
        else:
            continue
        total_macs += macs
        per_layer.append(("/".join(module_path), macs))
    per_layer.sort(key=lambda kv: -kv[1])
    return {"total_macs": total_macs, "per_layer": per_layer}


def xla_counts(model, loss_fn, example_batch, cfg) -> dict:
    import jax

    from tf_operator_tpu.parallel import Trainer, make_mesh

    # ONE-device mesh: cost_analysis reports the post-GSPMD per-device
    # module, so a multi-device mesh would report 1/n of the global
    # flops while main() divides by the GLOBAL batch — per-example
    # counts would be understated n× on a multi-chip box
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(model, cfg, mesh, loss_fn, example_batch)
    sharded = trainer.shard_batch(example_batch)

    def flops_of(compiled):
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))

    import flax.linen as nn

    with trainer.mesh, nn.logical_axis_rules(trainer._rules):
        train_flops = flops_of(
            trainer._step.lower(trainer.state, sharded).compile()
        )

    def fwd(params, model_state, images):
        return model.apply(
            {"params": params, **model_state}, images, train=False
        ).sum()

    with trainer.mesh:
        fwd_flops = flops_of(
            jax.jit(fwd)
            .lower(trainer.state.params, trainer.state.model_state, sharded["image"])
            .compile()
        )
    return {"fwd_flops": fwd_flops, "train_flops": train_flops}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models import resnet50
    from tf_operator_tpu.parallel import TrainerConfig
    from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

    model = resnet50()
    rng = np.random.RandomState(0)
    example = jnp.asarray(
        rng.rand(args.batch, 224, 224, 3).astype(np.float32), jnp.bfloat16
    )
    batch = {
        "image": example,
        "label": jnp.asarray(rng.randint(0, 1000, size=(args.batch,))),
    }

    analytic = analytic_fwd_macs(model, example)
    # total_macs is already per-example: the batch dim is stripped from
    # every captured output shape before the MAC product
    macs_per_example = analytic["total_macs"]

    counts = xla_counts(
        model,
        batchnorm_cross_entropy_loss,
        batch,
        TrainerConfig(optimizer="sgd", learning_rate=0.1, momentum=0.9),
    )
    fwd_per_example = counts["fwd_flops"] / args.batch
    train_per_example = counts["train_flops"] / args.batch

    out = {
        "platform": jax.devices()[0].platform,
        "batch": args.batch,
        "analytic_fwd_gmacs_per_example": round(macs_per_example / 1e9, 3),
        "analytic_fwd_gflops_per_example": round(2 * macs_per_example / 1e9, 3),
        "analytic_train_gflops_per_example": round(6 * macs_per_example / 1e9, 3),
        "xla_fwd_gflops_per_example": round(fwd_per_example / 1e9, 3),
        "xla_train_gflops_per_example": round(train_per_example / 1e9, 3),
        "xla_fwd_vs_analytic": round(fwd_per_example / (2 * macs_per_example), 4),
        "xla_train_vs_analytic": round(train_per_example / (6 * macs_per_example), 4),
        "xla_bwd_overcount_vs_3x_fwd": round(
            train_per_example / (3 * fwd_per_example), 4
        ),
        "top5_layers_gmacs_per_example": [
            (name, round(m / 1e9, 3)) for name, m in analytic["per_layer"][:5]
        ],
    }
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
