"""One-shot TPU measurement window: run everything the round needs the
chip for, in priority order, each step in a child process with a
timeout so one hang can't burn the window.

    python benchmarks/tpu_window.py [--log benchmarks/tpu_window.log]

Steps (recovery order — the tunnel has died mid-window twice, so
never-landed numbers run before the long sweeps; later steps only run
if earlier ones prove the chip is answering):
  1. probe        — 512x512 matmul (is the tunnel back at all?)
  2. bench        — bench.py headline (incl. live pipeline, llama, int8)
  3. flops        — on-TPU lowering check of the FLOPS.md accounting
  4. train        — measure.py --section train (mnist/BERT rows)
  5. flash        — the fwd+bwd flash-vs-XLA perf gates (record ratios)
  6. batching     — continuous-batching pool vs sequential serving
  6b. paged       — paged-KV pool vs slot pool at equal arena (CPU smoke)
  7. speculative  — int8 self-draft speculation vs plain greedy
  7b. speculative-paged — spec decoding on the paged plane (chip + CPU
      smoke): draft KV in the shared block arena, fused K-token
      verify, vs the non-speculative pool at the same arena
  7c. resnet-fused-chip — fused train-mode BN A/B (stock vs
      norm="fused" pallas kernel) + the traced chain-share drop
  8. trace        — xplane trace of the hot step + top-op summary
  9. sweep        — the ResNet MFU variant x flag matrix
 10. llama-sweep  — the transformer variant/autotune matrix
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

PROBE = (
    "import jax, jax.numpy as jnp; "
    "x = jnp.ones((512,512), jnp.bfloat16); "
    "print('probe ok', float((x@x).sum()))"
)

# Priority order is RECOVERY order: the tunnel has died mid-window
# twice (rounds 3 and 4), so the steps whose numbers have never landed
# run before the long sweeps — a window that dies early still
# contributes fresh rows.  bench stays first (the driver's headline).
# Budget note (round 5): this box has ONE CPU core (nproc=1), and XLA
# *TPU* compiles run on the host — every step budget below carries
# headroom over its round-4 value, and the bench step carries env
# defaults so its internal 19-minute default budget can't starve a
# slow-compile run (explicit env in the operator's shell still wins).
#
# STEPS rows: (name, cmd, timeout_s[, env_defaults])
STEPS = [
    ("probe", [sys.executable, "-c", PROBE], 120),
    (
        "bench",
        [sys.executable, os.path.join(REPO, "bench.py")],
        3600,
        {
            "BENCH_TOTAL_BUDGET": "3300",
            "BENCH_CHILD_TIMEOUT": "1500",
            "BENCH_LLAMA_TIMEOUT": "900",
        },
    ),
    # TPU-lowering confirmation of the FLOPS.md accounting table
    # (compile-only, cheap — see benchmarks/FLOPS.md)
    ("flops", [sys.executable, os.path.join(HERE, "flops_audit.py")], 600),
    # r7: the section now also runs the steps_per_sync K sweep (one
    # lax.scan compile per K on this 1-core host) and the prefetch
    # depth sweep — budget raised from 1800 accordingly.  ISSUE 19:
    # now ALSO carries the fused-BN A/B leg (two resnet50 train-step
    # compiles + 3 probe steps each) — raised again from 2700.
    (
        "train",
        [sys.executable, os.path.join(HERE, "measure.py"), "--section", "train"],
        3600,
    ),
    # ISSUE 14: flat vs hierarchical grad sync on the slice-aware mesh.
    # This box has ONE chip, so the window runs the same 2-slice CPU
    # sim as the committed smoke (byte ledger + program structure —
    # platform-independent) to keep the row fresh; a real multi-slice
    # world would run with MEASURE_PLATFORM=tpu and measure the
    # DCN-vs-ICI walls this section exists for.
    (
        "multislice",
        [
            sys.executable, os.path.join(HERE, "measure.py"),
            "--section", "multislice",
        ],
        1800,
        {"MEASURE_MULTISLICE_BATCH": "16", "MEASURE_MULTISLICE_STEPS": "12"},
    ),
    (
        "flash",
        [
            sys.executable, "-m", "pytest",
            "tests/test_tpu_chip.py::TestFlashKernelOnChip::test_flash_beats_xla_at_long_seq",
            "tests/test_tpu_chip.py::TestWindowAttentionOnChip",
            "-q", "-s",
        ],
        1500,
    ),
    # serving under concurrency: continuous-batching pool vs sequential
    # (models/batching.py); parsed into BASELINE.md by collect_window.
    # r6: sweeps steps_per_sync K (one step-program compile per K on
    # this 1-core host) and embeds the dispatch ledger — budget raised
    # accordingly
    (
        "batching",
        [sys.executable, os.path.join(HERE, "measure.py"), "--section", "batching"],
        2400,
    ),
    # paged KV serving ON CHIP (ISSUE 10): the pending BASELINE rows —
    # pool >= 1x prediction, paged at-capacity tok/s — become measured,
    # plus leg D's gather-emulation vs FUSED Pallas paged-attention
    # decode-bandwidth comparison (paged_kernel_* keys; the kernel
    # only exists here) and leg E's two-tier oversubscription run
    # (ISSUE 12: paged_lazy_capacity_* / paged_tier_* / preemption +
    # swap counts — 2 more pool builds, decode volume is small).
    # Runs right after batching so a dying tunnel
    # can't lose the serving rows again.  Budget: ~13 pool builds
    # (3 legs + 2 ctx x 2 seat-mix x 2 mode bandwidth legs + 2 tier
    # legs + leg F's 4 disaggregation fleet pools, ISSUE 13) x
    # width-class compiles on the 1-core host.  WINDOWS=4 keeps the
    # leg-D decode budget ((4+2) x K = 192) low enough that BOTH ctx
    # classes (64 and 256) fit under max_len=512 — the long-context
    # cell is the most bandwidth-bound mix, the one the fused kernel
    # exists for.
    (
        "paged-chip",
        [sys.executable, os.path.join(HERE, "measure.py"),
         "--section", "paged"],
        3300,
        {
            "MEASURE_PAGED_MAXLEN": "512",
            "MEASURE_PAGED_REQUESTS": "24",
            "MEASURE_PAGED_K": "32",
            "MEASURE_PAGED_WINDOWS": "4",
        },
    ),
    # paged KV serving CPU smoke: the capacity/hit-rate/TTFT
    # accounting is platform-independent (admission is host-side
    # arithmetic), so the window also exercises it every round on the
    # host — including the interpret-mode kernel numerics probe —
    # even when the chip half dies mid-window
    (
        "paged",
        [sys.executable, os.path.join(HERE, "measure.py"),
         "--section", "paged"],
        2100,
        {
            "MEASURE_PLATFORM": "cpu",
            "MEASURE_PAGED_TINY": "1",
            "MEASURE_PAGED_MAXLEN": "128",
            "MEASURE_PAGED_REQUESTS": "16",
            "MEASURE_PAGED_K": "8",
        },
    ),
    # speculative decode vs plain greedy, batch 1: int8 self-draft
    # mini AND the draft!=target wide-700M config (the row serve_lm's
    # --speculative guard reads); the ~700M init + two extra generate
    # compiles on the 1-core host earn the bigger budget
    (
        "speculative",
        [sys.executable, os.path.join(HERE, "measure.py"),
         "--section", "speculative"],
        2700,
    ),
    # speculative decoding ON THE PAGED PLANE (ISSUE 18): int8
    # self-draft in the shared block arena, one fused K-token verify
    # dispatch per window, vs the non-speculative paged pool at the
    # same arena — the spec_paged_* row serve_lm's --speculative
    # guard reads.  Run ON CHIP when the window has one...
    (
        "speculative-paged-chip",
        [sys.executable, os.path.join(HERE, "measure.py"),
         "--section", "speculative-paged"],
        2700,
        {
            "MEASURE_SPEC_PAGED_MAXLEN": "512",
            "MEASURE_SPEC_PAGED_NEW": "128",
        },
    ),
    # ...and as a CPU smoke every round (acceptance + the ledger-pinned
    # dispatches-per-token arithmetic are platform-independent; the
    # walls come back backend-tagged so they never displace chip rows)
    (
        "speculative-paged",
        [sys.executable, os.path.join(HERE, "measure.py"),
         "--section", "speculative-paged"],
        1500,
        {
            "MEASURE_PLATFORM": "cpu",
            "MEASURE_SPEC_TINY": "1",
        },
    ),
    # ISSUE 19 tentpole measurement: the fused train-mode BatchNorm
    # A/B on chip — stock nn.BatchNorm vs norm="fused" (auto → the
    # pallas kernel here), slope-timed + MFU + loss probe, tracing
    # BOTH variants so the reduce/elementwise/convert chain-share drop
    # lands as evidence (fusedbn_trace_* keys).  Budget: two resnet50
    # fwd+bwd+opt compiles on the 1-core host (~the bench step's
    # dominant cost) plus 2x traced steps.
    (
        "resnet-fused-chip",
        [
            sys.executable, os.path.join(HERE, "profile_resnet.py"),
            "--variant", "fusedbn", "--batch", "256", "--steps", "10",
            "--trace", "/tmp/rn50-fusedbn",
        ],
        3300,
    ),
    # the A/B pair of category tables, standalone (same rationale as
    # trace-categories below: survive a truncated chip-step stdout) —
    # multi-dir mode prints the per-variant tables AND the chain-share
    # drop line
    (
        "resnet-fused-trace",
        [sys.executable, os.path.join(HERE, "trace_categories.py"),
         "/tmp/rn50-fusedbn-stock", "/tmp/rn50-fusedbn-fused", "--md"],
        300,
    ),
    # the >=0.40-MFU existence proof at serious width (~700M d_model
    # 2048, VERDICT r4 next #3) — before the long sweeps so a dying
    # tunnel can't lose it again.  5 variants x 700s child timeout =
    # 3500s < 3800s step budget (700M compiles on the 1-core host).
    (
        "wide",
        [sys.executable, os.path.join(HERE, "llama_sweep.py"),
         "--set", "wide", "--timeout", "700"],
        3800,
    ),
    (
        "trace",
        [
            sys.executable, os.path.join(HERE, "profile_resnet.py"),
            "--variant", "baseline", "--batch", "256", "--steps", "5",
            "--trace", "/tmp/rn50-xplane",
        ],
        900,
    ),
    # r8: the category table, standalone (profile_resnet already prints
    # it inline post-trace; this re-reads the saved xplane so the
    # committed FLOPS.md "trace category table" rows land in their own
    # window_out file for collect_window even if the trace step's
    # stdout is truncated)
    (
        "trace-categories",
        [sys.executable, os.path.join(HERE, "trace_categories.py"),
         "/tmp/rn50-xplane", "--md"],
        300,
    ),
    (
        "sweep",
        [sys.executable, os.path.join(HERE, "mfu_sweep.py"), "--timeout", "700"],
        6000,
    ),
    # the transformer co-headline's variant matrix (flash-vs-XLA at
    # train shapes, remat, banded windows at long seq, and the flash
    # block-size autotune candidates).  Step budget must exceed
    # worst-case inner time: 12 variants x 600s child timeout = 7200s
    # < 7500s, so a contended chip can't kill the sweep mid-matrix
    (
        "llama-sweep",
        [sys.executable, os.path.join(HERE, "llama_sweep.py"), "--timeout", "600"],
        7500,
    ),
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--log", default=os.path.join(HERE, "tpu_window.log"))
    ap.add_argument(
        "--out-dir", default=os.path.join(HERE, "window_out"),
        help="full per-step stdout/stderr land here for "
        "collect_window.py to turn into BASELINE.md rows",
    )
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    def save_step(name: str, stdout, stderr) -> None:
        for suffix, text in (("out", stdout), ("err", stderr)):
            if isinstance(text, bytes):
                text = text.decode(errors="replace")
            with open(os.path.join(args.out_dir, f"{name}.{suffix}"), "w") as f:
                f.write(text or "")

    env = dict(os.environ)
    env["RUN_TPU_TESTS"] = "1"

    # Hold the advisory chip lock for the whole window so our own
    # watcher/probes back off; the driver's bench.py run preempts us
    # by design (see benchmarks/chiplock.py).
    sys.path.insert(0, HERE)
    from chiplock import ChipLock

    lock = ChipLock("window")
    if not lock.try_acquire():
        holder = lock.holder() or {}
        print(f"chip lock held by {holder}; refusing to start window",
              flush=True)
        # EX_TEMPFAIL, NOT 2: argparse usage errors exit(2), and the
        # watcher must be able to tell "lost the lock race, retry"
        # from "broken invocation"
        return 75
    # children (incl. bench.py) run under our claim — they must not
    # try to preempt their own parent
    env["TPU_CHIP_LOCK_INHERITED"] = "1"

    with open(args.log, "a") as log:
        def emit(msg):
            line = f"[{time.strftime('%H:%M:%S')}] {msg}"
            print(line, flush=True)
            log.write(line + "\n")
            log.flush()

        def tail_lines(text, n, prefix):
            for line in (text or "").strip().splitlines()[-n:]:
                emit(f"   {prefix}{line}")

        def reprobe() -> bool:
            try:
                p = subprocess.run(
                    [sys.executable, "-c", PROBE], env=env, cwd=REPO,
                    capture_output=True, text=True, timeout=120,
                )
                return p.returncode == 0
            except subprocess.TimeoutExpired:
                return False

        emit("== tpu window start ==")
        for name, cmd, timeout, *rest in STEPS:
            emit(f"-- {name}: {' '.join(os.path.basename(c) for c in cmd[:3])} ...")
            t0 = time.time()
            step_env = dict(env)
            for k, v in (rest[0] if rest else {}).items():
                step_env.setdefault(k, v)
            try:
                proc = subprocess.run(
                    cmd, env=step_env, cwd=REPO, capture_output=True,
                    text=True, timeout=timeout,
                )
            except subprocess.TimeoutExpired as exc:
                emit(f"   {name}: TIMEOUT >{timeout}s")
                # postmortem: keep whatever the step printed before dying
                out = exc.stdout
                save_step(name, out, exc.stderr)
                tail_lines(
                    out.decode(errors="replace") if isinstance(out, bytes) else out,
                    20, "",
                )
                if name == "probe" or not reprobe():
                    emit("   chip not answering; aborting window")
                    return 1
                continue
            dt = time.time() - t0
            save_step(name, proc.stdout, proc.stderr)
            tail_lines(proc.stdout, 12, "")
            if proc.returncode != 0:
                tail_lines(proc.stderr, 12, "stderr: ")
            emit(f"   {name}: rc={proc.returncode} in {dt:.0f}s")
            if name == "probe" and proc.returncode != 0:
                emit("   chip not answering; aborting window")
                return 1
        emit("== tpu window complete ==")
    return 0


if __name__ == "__main__":
    sys.exit(main())
