"""Advisory single-claimant lock for the local TPU chip.

The axon tunnel serves ONE client process at a time: a second process
that initialises the backend while a claim is live does not fail — it
*blocks* until the claim frees.  Round 4's first measurement window
lost its bench slot exactly this way (a concurrent dryrun held the
claim for 900s; the bench child inside its 510s timeout never got the
chip and was reported as "TPU stall").  The fix is coordination, not
timeouts: every long-lived chip consumer in this repo takes this
advisory flock first.

Roles and priority:
  - `bench` (the driver's end-of-round run) has absolute priority: on
    contention it PREEMPTS the current holder (kills the recorded pid
    and its children) — a stale watcher or an in-flight measurement
    window must never cost the round its BENCH artifact.
  - `window` / `watch` (our own measurement machinery) acquire
    non-blocking and back off if someone else holds the chip.

This is deliberately advisory-only: processes outside this repo (the
driver's own compile checks) don't know about it, and the lock file
lives in /tmp so a reboot clears it.  flock(2) gives crash-safety —
a dead holder's lock vanishes with its fd, so `acquire` never sees a
stale lock, and `preempt` only ever kills a live holder.
"""

from __future__ import annotations

import fcntl
import json
import os
import signal
import subprocess
import time

LOCK_PATH = os.environ.get("TPU_CHIP_LOCK", "/tmp/tpu_chip.lock")


class ChipLock:
    def __init__(self, role: str, path: str = LOCK_PATH):
        self.role = role
        self.path = path
        self._fd: int | None = None
        #: why the last try_acquire() failed: "flock" = a live holder
        #: has the lock (preemptable); "open" = we couldn't even open
        #: the lock file (permissions — NOT evidence anyone holds it)
        self.last_fail: str | None = None

    def try_acquire(self) -> bool:
        """Non-blocking acquire; records pid+role for a preemptor.
        Returns False on ANY OS-level failure (lock held, or e.g. an
        unwritable lock file another user created) — callers treat
        False as "back off", never as a crash."""
        try:
            fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        except OSError:
            self.last_fail = "open"
            return False
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            self.last_fail = "flock"
            return False
        self.last_fail = None
        os.ftruncate(fd, 0)
        os.write(fd, json.dumps({"pid": os.getpid(), "role": self.role,
                                 "t": time.time()}).encode())
        os.fsync(fd)
        self._fd = fd
        return True

    def holder(self) -> dict | None:
        """Who holds the lock right now (None if free/unreadable)."""
        try:
            with open(self.path) as f:
                return json.loads(f.read() or "null")
        except (OSError, json.JSONDecodeError):
            return None

    def acquire_or_preempt(self, grace_s: float = 10.0) -> str:
        """Bench-priority acquire: take the lock, evicting any holder.

        Returns a short note for the caller's log/JSON ("" if the lock
        was free).  Never raises; never blocks longer than ~2*grace_s.
        """
        if self.try_acquire():
            return ""
        if self.last_fail == "open":
            # lock file unreadable, NOT held: the recorded pid (if any)
            # is stale json from a dead run — killing it could hit a
            # reused pid belonging to an unrelated process
            return "chip lock file inaccessible; proceeding unlocked"
        info = self.holder() or {}
        pid, role = info.get("pid"), info.get("role", "?")
        note = f"preempted chip holder role={role} pid={pid}"
        if (
            isinstance(pid, int) and pid > 1 and pid != os.getpid()
            and _looks_like_ours(pid)
        ):
            _kill_tree(pid, grace_s)
        deadline = time.time() + grace_s
        while time.time() < deadline:
            if self.try_acquire():
                return note
            time.sleep(0.5)
        return note + " (lock still held; proceeding unlocked)"

    def release(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)  # closes fd -> drops flock
            finally:
                self._fd = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


def _children_of(pid: int) -> list[int]:
    try:
        out = subprocess.run(
            ["ps", "-o", "pid=", "--ppid", str(pid)],
            capture_output=True, text=True, timeout=10,
        ).stdout
        return [int(p) for p in out.split()]
    except Exception:
        return []


def _descendants(pid: int, depth: int = 4) -> list[int]:
    out, frontier = [], [pid]
    for _ in range(depth):
        nxt: list[int] = []
        for p in frontier:
            nxt.extend(_children_of(p))
        if not nxt:
            break
        out.extend(nxt)
        frontier = nxt
    return out


def _looks_like_ours(pid: int) -> bool:
    """Pre-kill sanity check against pid reuse: the recorded holder
    must still be a python/bash process (everything that takes this
    lock is one).  A recycled pid running something else is spared."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmd = f.read().split(b"\0")[0].decode(errors="replace")
    except OSError:
        return False
    base = os.path.basename(cmd)
    return base.startswith(("python", "bash", "sh", "timeout"))


def _kill_tree(pid: int, grace_s: float) -> None:
    """TERM then KILL pid and its descendants.  The victim set is
    re-enumerated on every pass AND accumulated across passes: a
    holder mid-fanout can spawn a child after a one-shot snapshot, and
    a grandchild that outlives its parent is reparented to init — a
    fresh ppid-walk from the dead root would miss it, leaving the axon
    chip claim alive behind the released flock."""
    seen: set[int] = {pid}
    for sig in (signal.SIGTERM, signal.SIGKILL):
        deadline = time.time() + grace_s
        while time.time() < deadline:
            for p in list(seen):
                seen.update(_descendants(p))
            victims = [p for p in seen if _alive(p)]
            if not victims:
                return
            for p in victims:
                try:
                    os.kill(p, sig)
                except (ProcessLookupError, PermissionError):
                    pass
            time.sleep(0.25)


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _probe_main() -> int:
    """`python benchmarks/chiplock.py probe` — the watcher's one probe
    entrypoint.  Exit codes: 0 = lock taken AND the chip answered;
    2 = lock held by another consumer (NOT a tunnel problem — the
    watch log must not misread contention as an outage); 1 = chip not
    answering.  The caller wraps this in `timeout` for the hang case."""
    lock = ChipLock("watch")
    if not lock.try_acquire():
        print(f"chip lock held: {lock.holder()}", flush=True)
        return 2
    try:
        import runpy

        here = os.path.dirname(os.path.abspath(__file__))
        probe_src = runpy.run_path(os.path.join(here, "tpu_window.py"))["PROBE"]
        exec(probe_src)  # noqa: S102 — our own constant
        return 0
    except Exception as e:
        print(f"probe failed: {type(e).__name__}: {e}", flush=True)
        return 1
    finally:
        lock.release()


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "probe":
        sys.exit(_probe_main())
    sys.exit(f"usage: {sys.argv[0]} probe")
