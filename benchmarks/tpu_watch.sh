#!/bin/bash
# Probe the TPU tunnel every 10 min; the moment it answers, run the
# one-shot measurement window (benchmarks/tpu_window.py) and exit.
# Launch detached:  nohup bash benchmarks/tpu_watch.sh &> benchmarks/tpu_watch.log &
#
# Coordination (benchmarks/chiplock.py): the probe takes the advisory
# chip lock first; if another consumer holds it (e.g. the driver's
# bench.py) the probe reports rc=2 and we back off — a probe process
# queued on the axon claim would stall the holder's children (the
# round-4 incident).  A window that loses the lock race (rc=2) is
# retried, not abandoned: the watcher only exits after a window RAN.
cd "$(dirname "$0")/.." || exit 1
while true; do
  echo "[$(date +%H:%M:%S)] probing tpu..."
  timeout 120 python benchmarks/chiplock.py probe
  rc=$?
  if [ "$rc" -eq 0 ]; then
    echo "[$(date +%H:%M:%S)] TPU IS BACK — starting measurement window"
    python benchmarks/tpu_window.py
    wrc=$?
    echo "[$(date +%H:%M:%S)] window done rc=$wrc"
    # Exit ONLY on a fully completed window (rc=0).  rc=75 = lost the
    # lock race; rc=1 = chip stopped answering mid-window; rc=143/137 =
    # preempted by the driver's bench.py.  All of those mean the round
    # still needs window data — keep watching.
    if [ "$wrc" -eq 0 ]; then
      # land the numbers: regenerate BASELINE.md's training table from
      # the window artifacts and commit the round's measured results.
      # Adds are per-path (git add is all-or-nothing across a pathspec
      # list: one missing file would stage NOTHING) and the commit is
      # pathspec-scoped so operator-staged unrelated work is untouched.
      if ! python benchmarks/collect_window.py; then
        echo "[$(date +%H:%M:%S)] COLLECTOR FAILED — window artifacts left in benchmarks/window_out, NOT committed"
      fi
      for f in BASELINE.md benchmarks/RESULTS.md benchmarks/LAST_MEASURED.json benchmarks/window_out; do
        git add "$f" 2>/dev/null || echo "[$(date +%H:%M:%S)] could not stage $f"
      done
      git commit -q -m "Record measured TPU numbers from the completed measurement window" \
        -- BASELINE.md benchmarks/RESULTS.md benchmarks/LAST_MEASURED.json benchmarks/window_out \
        || echo "[$(date +%H:%M:%S)] nothing to commit from collector"
      exit 0
    fi
    echo "[$(date +%H:%M:%S)] window incomplete (rc=$wrc); retrying in 600s"
  elif [ "$rc" -eq 2 ]; then
    echo "[$(date +%H:%M:%S)] chip lock held by another consumer; sleeping 600s"
  else
    echo "[$(date +%H:%M:%S)] tunnel still down (rc=$rc); sleeping 600s"
  fi
  sleep 600
done
