#!/bin/bash
# Probe the TPU tunnel every 10 min; the moment it answers, run the
# one-shot measurement window (benchmarks/tpu_window.py) and exit.
# Launch detached:  nohup bash benchmarks/tpu_watch.sh &> benchmarks/tpu_watch.log &
cd "$(dirname "$0")/.." || exit 1
while true; do
  echo "[$(date +%H:%M:%S)] probing tpu..."
  # PROBE is shared with tpu_window.py so the two can't drift
  if timeout 120 python -c "import runpy; exec(runpy.run_path('benchmarks/tpu_window.py')['PROBE'])"; then
    echo "[$(date +%H:%M:%S)] TPU IS BACK — starting measurement window"
    python benchmarks/tpu_window.py
    rc=$?
    echo "[$(date +%H:%M:%S)] window done rc=$rc"
    exit 0
  fi
  echo "[$(date +%H:%M:%S)] tunnel still down; sleeping 600s"
  sleep 600
done
