#!/bin/bash
# Probe the TPU tunnel every 10 min; the moment it answers, run the
# one-shot measurement window (benchmarks/tpu_window.py) and exit.
# Launch detached:  nohup bash benchmarks/tpu_watch.sh &> benchmarks/tpu_watch.log &
#
# Coordination (benchmarks/chiplock.py): the probe takes the advisory
# chip lock first; if another consumer holds it (e.g. the driver's
# bench.py) the probe reports rc=2 and we back off — a probe process
# queued on the axon claim would stall the holder's children (the
# round-4 incident).  A window that loses the lock race (rc=2) is
# retried, not abandoned: the watcher only exits after a window RAN.
cd "$(dirname "$0")/.." || exit 1
while true; do
  echo "[$(date +%H:%M:%S)] probing tpu..."
  timeout 120 python benchmarks/chiplock.py probe
  rc=$?
  if [ "$rc" -eq 0 ]; then
    echo "[$(date +%H:%M:%S)] TPU IS BACK — starting measurement window"
    python benchmarks/tpu_window.py
    wrc=$?
    echo "[$(date +%H:%M:%S)] window done rc=$wrc"
    # Exit ONLY on a fully completed window (rc=0).  rc=75 = lost the
    # lock race; rc=1 = chip stopped answering mid-window; rc=143/137 =
    # preempted by the driver's bench.py.  All of those mean the round
    # still needs window data — keep watching.
    if [ "$wrc" -eq 0 ]; then
      exit 0
    fi
    echo "[$(date +%H:%M:%S)] window incomplete (rc=$wrc); retrying in 600s"
  elif [ "$rc" -eq 2 ]; then
    echo "[$(date +%H:%M:%S)] chip lock held by another consumer; sleeping 600s"
  else
    echo "[$(date +%H:%M:%S)] tunnel still down (rc=$rc); sleeping 600s"
  fi
  sleep 600
done
