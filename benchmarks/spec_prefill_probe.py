"""Why does SpeculativeDecoder's prefill cost ~1.8 s for a 32-token
prompt when plain decode's whole 128-token generate is ~0.1 s?  Times
target-prefill and draft-prefill separately (each with a blocking
fetch), plus plain generate for reference."""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    p = os.environ.get("BENCH_PLATFORM")
    if p:
        jax.config.update("jax_platforms", p)

    from bench import llama_mini_config
    from tf_operator_tpu.models import LlamaLM, SpeculativeDecoder, generate
    from tf_operator_tpu.ops.quant import quantize_tree

    seq = 512
    model = LlamaLM(llama_mini_config(seq))
    vocab = model.cfg.vocab_size
    r = np.random.RandomState(0)
    prompt = jnp.asarray(r.randint(0, vocab, size=(1, 32)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    qparams = quantize_tree(params)
    dec = SpeculativeDecoder(model, params, model, qparams, k=4)
    b = 1
    out = {}

    def timed(fn, reps=3):
        fn()
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return round((time.perf_counter() - t0) / reps, 4)

    tc0 = dec._stacked_cache(dec.dtar, b)
    dc0 = dec._stacked_cache(dec.ddraft, b)

    def t_prefill():
        tc, last = dec._prefill("t", 32)(dec.tparams, tc0, prompt)
        np.asarray(last)

    def d_prefill():
        dc, last = dec._prefill("d", 32)(dec.dparams, dc0, prompt)
        np.asarray(last)

    out["t_prefill_s"] = timed(t_prefill)
    out["d_prefill_s"] = timed(d_prefill)

    out["plain_generate128_s"] = timed(
        lambda: np.asarray(generate(model, params, prompt, max_new_tokens=128))
    )
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
