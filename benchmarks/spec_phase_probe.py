"""Phase-level timing of the chunked-scan speculative driver: where do
the ~1.7 s per 128-token generate() actually go?  Times prefill, each
chunk dispatch+fetch, and the argmax/pick host step separately."""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    p = os.environ.get("BENCH_PLATFORM")
    if p:
        jax.config.update("jax_platforms", p)

    from bench import llama_mini_config
    from tf_operator_tpu.models import LlamaLM, SpeculativeDecoder
    from tf_operator_tpu.models.speculative import binary_chunks
    from tf_operator_tpu.ops.quant import quantize_tree

    seq = 512
    n_new = 128
    model = LlamaLM(llama_mini_config(seq))
    vocab = model.cfg.vocab_size
    r = np.random.RandomState(0)
    prompt = jnp.asarray(r.randint(0, vocab, size=(1, 32)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    qparams = quantize_tree(params)
    dec = SpeculativeDecoder(model, params, model, qparams, k=4)

    b, p_len = prompt.shape
    out = {}

    def phase_run():
        t = {}
        t0 = time.perf_counter()
        tcache = dec._stacked_cache(dec.dtar, b)
        dcache = dec._stacked_cache(dec.ddraft, b)
        last = None
        off = 0
        for width in binary_chunks(p_len):
            ids = prompt[:, off : off + width]
            tcache, last = dec._prefill("t", width)(dec.tparams, tcache, ids)
            dcache, _ = dec._prefill("d", width)(dec.dparams, dcache, ids)
            off += width
        t1 = jnp.argmax(last, -1).astype(jnp.int32)
        np.asarray(t1)
        t["prefill_s"] = time.perf_counter() - t0

        n0 = jnp.full((b,), p_len, jnp.int32)
        limit = jnp.full((b,), p_len + n_new, jnp.int32)
        rngs = jax.random.split(jax.random.PRNGKey(1), b)
        temp = jnp.float32(1.0)
        bucket = n_new
        width_buf = bucket + dec.k
        state = {
            "out": jnp.zeros((b, width_buf), jnp.int32),
            "tc": tcache, "dc": dcache,
            "n": n0, "t1": t1,
            "rngs": rngs,
            "telem": jnp.zeros((3,), jnp.int32),
        }
        r0 = 32
        chunks = []
        limit_h = np.asarray(limit)
        chunk_r = r0
        while True:
            fn = dec._fused_scan(dec.k, bucket, b, False, chunk_r)
            t0 = time.perf_counter()
            state, packed = fn(dec.tparams, dec.dparams, state, n0, limit, temp)
            t_disp = time.perf_counter() - t0
            t0 = time.perf_counter()
            packed_h = np.asarray(packed)
            t_fetch = time.perf_counter() - t0
            chunks.append((chunk_r, round(t_disp, 4), round(t_fetch, 4)))
            n_h = packed_h[b * width_buf : b * width_buf + b]
            if (n_h >= limit_h).all():
                break
            chunk_r = 8
        t["chunks"] = chunks
        return t

    phase_run()  # compile everything
    out["run1"] = phase_run()
    out["run2"] = phase_run()
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
