"""MFU sweep: run a matrix of ResNet-50 step-time experiments, each in
its own child process (fresh XLA_FLAGS per run; a hung run cannot kill
the sweep — TPU tunnel stalls are a fact of life on this box).

Usage:  python benchmarks/mfu_sweep.py [--quick] [--timeout 900]
Findings go to benchmarks/PROFILE.md.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

#: (label, variant, batch, extra XLA flags)
MATRIX = [
    ("baseline-b256", "baseline", 256, ""),
    ("baseline-b512", "baseline", 512, ""),
    ("s2d-b256", "s2d", 256, ""),
    ("noclip-b256", "noclip", 256, ""),
    ("bnbf16-b256", "bnbf16", 256, ""),
    ("pbf16-b256", "pbf16", 256, ""),
    ("vmem64m-b256", "baseline", 256, "--xla_tpu_scoped_vmem_limit_kib=65536"),
    ("lhs-b256", "baseline", 256, "--xla_tpu_enable_latency_hiding_scheduler=true"),
    (
        "vmem64m-s2d-b512",
        "s2d",
        512,
        "--xla_tpu_scoped_vmem_limit_kib=65536",
    ),
]

QUICK = MATRIX[:3]


def run_one(label, variant, batch, flags, timeout, steps):
    env = dict(os.environ)
    if flags:
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flags).strip()
    cmd = [
        sys.executable,
        os.path.join(HERE, "profile_resnet.py"),
        "--variant", variant,
        "--batch", str(batch),
        "--steps", str(steps),
    ]
    try:
        proc = subprocess.run(
            cmd, env=env, capture_output=True, text=True, timeout=timeout
        )
    except subprocess.TimeoutExpired:
        return {"label": label, "error": f"timeout >{timeout}s"}
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                out = json.loads(line)
                out["label"] = label
                return out
            except json.JSONDecodeError:
                continue
    tail = (proc.stderr or "").strip().splitlines()
    return {"label": label, "error": (tail[-1] if tail else f"rc={proc.returncode}")[:160]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="first 3 rows only")
    ap.add_argument("--timeout", type=int, default=900)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    rows = QUICK if args.quick else MATRIX
    results = []
    for label, variant, batch, flags in rows:
        print(f"--- {label} ...", flush=True)
        res = run_one(label, variant, batch, flags, args.timeout, args.steps)
        results.append(res)
        print(json.dumps(res), flush=True)

    print("\n== sweep summary (sorted by MFU) ==")
    ok = [r for r in results if "mfu" in r]
    for r in sorted(ok, key=lambda r: -r["mfu"]):
        print(
            f"{r['label']:<20} mfu={r['mfu']:.4f}  step={r['step_ms']:.1f}ms  "
            f"ex/s={r['examples_per_sec']:.0f}  b={r['batch_per_chip']}"
        )
    for r in results:
        if "error" in r:
            print(f"{r['label']:<20} ERROR: {r['error']}")


if __name__ == "__main__":
    main()
