"""Category breakdown of a saved xplane trace: where did the step's
device time actually go?

    python benchmarks/trace_categories.py /tmp/rn50-xplane [--md]

Groups the "[XLA Ops]" line (synchronous device ops — these sum to the
critical path) by op family and prints each family's share, with the
async-DMA line ("[Async XLA Ops]") reported separately since those
overlap compute.  This is the trace-proven half of the "what bounds
ResNet at ~0.29 MFU" claim (benchmarks/PROFILE.md): the sweep shows the
plateau, this table names the ops on the critical path.

Importable (r8, VERDICT r5 next #4): ``profile_resnet.py --trace``
calls :func:`category_tables` + :func:`format_markdown` right after
capturing, so every traced run emits the committed-table shape
(benchmarks/FLOPS.md "trace category table") without a second tool
invocation; the tpu_window trace step passes ``--md`` for the same
reason.
"""

from __future__ import annotations

import glob
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List


def categorize(name: str) -> str:
    n = name.lower()
    if "copy-start" in n or "copy-done" in n or n.startswith("%copy"):
        return "copies / DMA"
    if "all-reduce" in n or "reduce-scatter" in n or "all-gather" in n:
        return "collectives"
    # full word only — "convert" also contains "conv", and the int8
    # dequant convert-fusions must not inflate the MXU share
    if "convolution" in n:
        return "convolution (MXU)"
    if "reduce" in n:  # incl. convert_reduce_fusion (BN statistics)
        return "reductions (BN stats etc.)"
    if "dot" in n or "matmul" in n:
        return "matmul (MXU)"
    if "convert" in n:
        return "dtype converts"
    if "fusion" in n:
        return "elementwise fusions"
    if "infeed" in n or "outfeed" in n:
        return "host transfer"
    return "other"


def category_tables(trace_dir: str) -> List[Dict[str, Any]]:
    """Parse the newest xplane under ``trace_dir`` into one table per
    device plane/op line: ``{plane, line, kind, total_s, rows}`` with
    ``rows`` = [(category, seconds, count)] sorted by share desc.
    Returns [] when no xplane exists (the caller prints the miss)."""

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        return []
    path = max(paths, key=os.path.getmtime)
    space = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        space.ParseFromString(f.read())
    tables: List[Dict[str, Any]] = []
    for plane in space.planes:
        # device planes ("/device:TPU:0") on chip; the XLA client
        # executor lines of "/host:CPU" carry the op events on a CPU
        # smoke run (no "XLA Ops" line exists there — every non-python
        # line aggregates into one pseudo-table instead)
        cpu_smoke = plane.name == "/host:CPU"
        if (
            "TPU" not in plane.name
            and "/device:" not in plane.name
            and not cpu_smoke
        ):
            continue
        groups: Dict[str, list] = defaultdict(list)
        for line in plane.lines:
            if cpu_smoke:
                if line.name in ("python", "Steps"):
                    continue
                groups["XLA client ops"].append(line)
            elif line.name in ("XLA Ops", "Async XLA Ops"):
                groups[line.name].append(line)
        for gname, lines in groups.items():
            by_cat = defaultdict(float)
            cnt = defaultdict(int)
            total = 0.0
            for line in lines:
                for ev in line.events:
                    meta = plane.event_metadata.get(ev.metadata_id)
                    name = meta.name if meta else "?"
                    if cpu_smoke and (
                        "thunkexecutor" in name.lower()
                        or name.startswith(("while", "call."))
                    ):
                        # container events (the executor frame, while-
                        # loop and call wrappers) span every op they
                        # contain: counting them would double every
                        # category into "other"
                        continue
                    dur = ev.duration_ps / 1e12
                    cat = categorize(name)
                    by_cat[cat] += dur
                    cnt[cat] += 1
                    total += dur
            if not total:
                continue
            tables.append({
                "plane": plane.name,
                "line": gname,
                "kind": (
                    "critical path (sync ops)"
                    if gname == "XLA Ops"
                    else "overlapped DMA (async)"
                    if gname == "Async XLA Ops"
                    else "cpu smoke (all client lines, threads overlap)"
                ),
                "total_s": total,
                "rows": sorted(
                    ((cat, dur, cnt[cat]) for cat, dur in by_cat.items()),
                    key=lambda r: -r[1],
                ),
            })
    return tables


def category_shares(tables: List[Dict[str, Any]]) -> Dict[str, float]:
    """Per-category share-of-total for the critical-path table (chip:
    the "XLA Ops" sync line; CPU smoke: the aggregated client line).
    This is what the fusedbn A/B (ISSUE 19) diffs between variants:
    the killed chain is ``reductions + elementwise + converts``, so the
    drop in that sum is the category-level proof of the fusion."""

    main = next(
        (t for t in tables if t["line"] == "XLA Ops"),
        next((t for t in tables if t["line"] == "XLA client ops"), None),
    )
    if main is None or not main["total_s"]:
        return {}
    return {cat: dur / main["total_s"] for cat, dur, _ in main["rows"]}


def chain_share(tables: List[Dict[str, Any]]) -> float:
    """The BN-chain share: reductions + elementwise fusions + dtype
    converts as a fraction of critical-path device time."""

    shares = category_shares(tables)
    return sum(
        shares.get(k, 0.0)
        for k in (
            "reductions (BN stats etc.)",
            "elementwise fusions",
            "dtype converts",
        )
    )


def format_text(tables: List[Dict[str, Any]]) -> str:
    out = []
    for t in tables:
        out.append(
            f"\n== {t['plane']} / {t['line']} — {t['kind']}: "
            f"{t['total_s'] * 1e3:.1f} ms total =="
        )
        for cat, dur, n in t["rows"]:
            out.append(
                f"{dur * 1e3:10.2f} ms  {dur / t['total_s'] * 100:5.1f}%  "
                f"x{n:<6d} {cat}"
            )
    return "\n".join(out)


def format_markdown(tables: List[Dict[str, Any]]) -> str:
    """The committed-table shape (benchmarks/FLOPS.md): one markdown
    table per plane/line."""

    out = []
    for t in tables:
        out.append(
            f"\n**{t['plane']} / {t['line']}** — {t['kind']}, "
            f"{t['total_s'] * 1e3:.1f} ms total\n"
        )
        out.append("| category | ms | share | ops |")
        out.append("|---|---|---|---|")
        for cat, dur, n in t["rows"]:
            out.append(
                f"| {cat} | {dur * 1e3:.2f} | "
                f"{dur / t['total_s'] * 100:.1f}% | {n} |"
            )
    return "\n".join(out)


def main() -> int:
    # accepts multiple trace dirs (ISSUE 19: the fusedbn window step
    # passes the A/B pair ``…-stock …-fused``); with 2+ dirs the
    # chain-share diff across them is printed last
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    trace_dirs = args if args else ["/tmp/rn50-xplane"]
    shares = {}
    missing = 0
    for trace_dir in trace_dirs:
        tables = category_tables(trace_dir)
        if not tables:
            print("no xplane found under", trace_dir)
            missing += 1
            continue
        if len(trace_dirs) > 1:
            print(f"\n#### {trace_dir}")
        print(format_text(tables))
        if "--md" in sys.argv[1:]:
            print("\n--- markdown (FLOPS.md 'trace category table') ---")
            print(format_markdown(tables))
        shares[trace_dir] = chain_share(tables)
    if len(shares) > 1:
        print("\n== reduce+elementwise+convert chain share by trace ==")
        for d, s in shares.items():
            print(f"{s * 100:6.1f}%  {d}")
        vals = list(shares.values())
        print(f"drop (first - last): {(vals[0] - vals[-1]) * 100:.1f} pts")
    return 1 if missing else 0


if __name__ == "__main__":
    sys.exit(main())
