"""Category breakdown of a saved xplane trace: where did the step's
device time actually go?

    python benchmarks/trace_categories.py /tmp/rn50-xplane

Groups the "[XLA Ops]" line (synchronous device ops — these sum to the
critical path) by op family and prints each family's share, with the
async-DMA line ("[Async XLA Ops]") reported separately since those
overlap compute.  This is the trace-proven half of the "what bounds
ResNet at ~0.29 MFU" claim (benchmarks/PROFILE.md): the sweep shows the
plateau, this table names the ops on the critical path.
"""

from __future__ import annotations

import glob
import os
import re
import sys
from collections import defaultdict


def categorize(name: str) -> str:
    n = name.lower()
    if "copy-start" in n or "copy-done" in n or n.startswith("%copy"):
        return "copies / DMA"
    if "all-reduce" in n or "reduce-scatter" in n or "all-gather" in n:
        return "collectives"
    # full word only — "convert" also contains "conv", and the int8
    # dequant convert-fusions must not inflate the MXU share
    if "convolution" in n:
        return "convolution (MXU)"
    if "reduce" in n:  # incl. convert_reduce_fusion (BN statistics)
        return "reductions (BN stats etc.)"
    if "dot" in n or "matmul" in n:
        return "matmul (MXU)"
    if "convert" in n:
        return "dtype converts"
    if "fusion" in n:
        return "elementwise fusions"
    if "infeed" in n or "outfeed" in n:
        return "host transfer"
    return "other"


def main() -> int:
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/rn50-xplane"
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        print("no xplane found under", trace_dir)
        return 1
    path = max(paths, key=os.path.getmtime)
    space = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        space.ParseFromString(f.read())
    for plane in space.planes:
        if "TPU" not in plane.name and "/device:" not in plane.name:
            continue
        for line in plane.lines:
            if line.name not in ("XLA Ops", "Async XLA Ops"):
                continue
            by_cat = defaultdict(float)
            cnt = defaultdict(int)
            total = 0.0
            for ev in line.events:
                meta = plane.event_metadata.get(ev.metadata_id)
                name = meta.name if meta else "?"
                dur = ev.duration_ps / 1e12
                cat = categorize(name)
                by_cat[cat] += dur
                cnt[cat] += 1
                total += dur
            if not total:
                continue
            kind = (
                "critical path (sync ops)"
                if line.name == "XLA Ops"
                else "overlapped DMA (async)"
            )
            print(
                f"\n== {plane.name} / {line.name} — {kind}: "
                f"{total*1e3:.1f} ms total =="
            )
            for cat, dur in sorted(by_cat.items(), key=lambda kv: -kv[1]):
                print(
                    f"{dur*1e3:10.2f} ms  {dur/total*100:5.1f}%  "
                    f"x{cnt[cat]:<6d} {cat}"
                )
    return 0


if __name__ == "__main__":
    sys.exit(main())
