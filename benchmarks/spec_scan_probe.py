"""Probe: is the speculative driver's while-loop body the thing that
defeats DMA overlap, or is the round itself just slow?

PROFILE.md (r5 serving tier) traced the fused while-loop driver at
~86 GB/s effective weight bandwidth where plain `lax.scan` decode
sustains ~300 GB/s, and left "restore DMA overlap inside the while
body" as the open engineering item.  This probe isolates the control
structure: the SAME vmapped round (speculative._round_row) executed

  A. inside `_fused`'s `lax.while_loop` (data-dependent trip count,
     one program for the whole generation), vs
  B. inside `_rounds`' `lax.scan` at a FIXED round count (one program
     per chunk, host decides when to stop).

Same weights, same caches, same k, same acceptance stream (greedy,
self-draft int8) — the only variable is while vs scan.  If B's
per-round device wall is materially lower, the fix is a chunked-scan
driver (optimistic first chunk of ceil(N/k) rounds, then top-up
chunks), not kernel surgery.

Usage: python benchmarks/spec_scan_probe.py  (prints one JSON line)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    p = os.environ.get("BENCH_PLATFORM")
    if p:
        jax.config.update("jax_platforms", p)

    from bench import llama_mini_config
    from tf_operator_tpu.models import LlamaLM, SpeculativeDecoder
    from tf_operator_tpu.ops.quant import quantize_tree

    seq = int(os.environ.get("PROBE_SPEC_MAXLEN", "512"))
    n_new = int(os.environ.get("PROBE_SPEC_NEW", "128"))
    rounds = int(os.environ.get("PROBE_SPEC_ROUNDS", "16"))
    out = {"backend": jax.default_backend(), "n_new": n_new, "rounds": rounds}

    model = LlamaLM(llama_mini_config(seq))
    vocab = model.cfg.vocab_size
    r = np.random.RandomState(0)
    prompt = jnp.asarray(r.randint(0, vocab, size=(1, 32)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    qparams = quantize_tree(params)
    dec = SpeculativeDecoder(model, params, model, qparams, k=4)

    def timed(fn, reps=3):
        fn()  # compile + settle
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps

    # A. whole-generation while_loop program (the r5-morning driver)
    dec.use_fused = True
    dec.fused_driver = "while"
    out["fused_while_s"] = round(timed(
        lambda: dec.generate(prompt, max_new_tokens=n_new)
    ), 4)

    # C. the shipped chunked-scan driver end-to-end (optimistic first
    # chunk + top-ups, one small fetch per chunk)
    dec.fused_driver = "scan"
    out["fused_scan_s"] = round(timed(
        lambda: dec.generate(prompt, max_new_tokens=n_new)
    ), 4)
    out["scan_vs_while"] = round(
        out["fused_while_s"] / out["fused_scan_s"], 2
    )

    # B. the same rounds as ONE fixed-length scan program.  Drive the
    # compiled `_rounds` program directly so the host loop's multiple
    # fetches don't pollute the device-side comparison: one dispatch,
    # then a single blocking fetch of the committed-length vector.
    b, p_len = prompt.shape
    tcache = dec._stacked_cache(dec.dtar, b)
    dcache = dec._stacked_cache(dec.ddraft, b)
    last = None
    off = 0
    from tf_operator_tpu.models.speculative import binary_chunks

    for width in binary_chunks(p_len):
        ids = prompt[:, off : off + width]
        tcache, last = dec._prefill("t", width)(dec.tparams, tcache, ids)
        dcache, _ = dec._prefill("d", width)(dec.dparams, dcache, ids)
        off += width
    t1 = jnp.argmax(last, -1).astype(jnp.int32)
    n0 = jnp.full((b,), p_len, jnp.int32)
    limit = jnp.full((b,), p_len + n_new, jnp.int32)
    rounds_fn = dec._rounds(dec.k, rounds)

    def run_scan():
        tc, dc, t1o, n_dev, ms, chunks, acts = rounds_fn(
            dec.tparams, dec.dparams, tcache, dcache, t1, n0, limit
        )
        np.asarray(n_dev)  # one blocking fetch

    out["scan_fixed_s"] = round(timed(run_scan), 4)
    out["scan_rounds_per_s"] = round(rounds / out["scan_fixed_s"], 1)

    # the while program's round count varies with acceptance; report
    # the tokens actually produced so per-round walls can be compared
    # honestly (tokens/round ~= 1 + mean accepted)
    dec.proposed = dec.accepted = 0
    toks = dec.generate(prompt, max_new_tokens=n_new)
    out["acceptance"] = round(dec.acceptance_rate, 3)
    out["fused_tokens"] = int(toks.shape[1] - p_len)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
