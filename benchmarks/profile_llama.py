"""One llama-mini train-step timing: the transformer co-headline's
profiling unit (VERDICT r3: llama MFU is where this framework's own
kernels — flash fwd+bwd, GQA, banded windows — move the number).

Prints ONE JSON line with tokens/sec/chip, step ms, mfu_analytic
(6N + causal-attention model flops) and mfu_xla.

Usage: python benchmarks/profile_llama.py [--seq 1024] [--batch 8]
         [--flash 1|0] [--window N] [--remat] [--accum K] [--platform cpu]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _effective_chunks(s: int, n_chunks: int) -> int:
    """Mirror of llama_loss_chunked's divisor fallback."""

    c = max(1, min(n_chunks, s))
    while s % c:
        c -= 1
    return c


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument(
        "--model", default="mini", choices=["mini", "wide"],
        help="mini = ~120M llama-mini; wide = ~700M d_model-2048 "
        "(the >=0.40-MFU existence-proof shape, VERDICT r4 next #3)",
    )
    ap.add_argument("--batch", type=int, default=8, help="per chip")
    ap.add_argument("--steps", type=int, default=10)
    # "1" forces the kernel (sweeps measure flash AT crossover shapes),
    # "0" disables it, "auto" clears the env var so the dispatcher's
    # measured block-keyed crossover decides — used to verify the auto
    # path routes where the sweep data says it should
    ap.add_argument("--flash", default="1", choices=["0", "1", "auto"])
    ap.add_argument("--window", type=int, default=None)
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--accum", type=int, default=1)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--trace", default=None, help="xplane trace dir")
    ap.add_argument(
        "--chunked-loss", type=int, default=0, metavar="N",
        help="stream the vocab projection + xent over N sequence "
        "chunks (llama_loss_chunked) instead of materializing full "
        "f32 logits",
    )
    args = ap.parse_args()

    os.environ["TPU_OPERATOR_FLASH"] = (
        "" if args.flash == "auto" else args.flash
    )

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import jax.numpy as jnp
    import numpy as np

    from bench import (
        _llama_analytic_flops_per_token,
        _peak_flops,
        _step_flops,
        llama_mini_config,
        llama_wide_config,
        matmul_param_count,
    )
    from tf_operator_tpu.models import (
        LlamaLM,
        llama_loss,
        llama_loss_chunked,
    )
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    devices = jax.devices()
    n_dev = len(devices)
    r = np.random.RandomState(0)
    make_cfg = llama_mini_config if args.model == "mini" else llama_wide_config
    cfg = make_cfg(args.seq, window=args.window)
    lm = {
        "input_ids": jnp.asarray(
            r.randint(0, 32000, size=(args.batch * n_dev, args.seq)), jnp.int32
        )
    }
    trainer = Trainer(
        LlamaLM(cfg),
        TrainerConfig(learning_rate=1e-3, remat=args.remat, accum_steps=args.accum),
        make_mesh({"fsdp": n_dev}),
        (functools.partial(llama_loss_chunked, n_chunks=args.chunked_loss)
         if args.chunked_loss else llama_loss),
        lm,
        init_args=(lm["input_ids"],),
        shardings="logical",
    )
    stats = trainer.benchmark(lm, steps=args.steps, warmup=3)
    tps = stats["steps_per_sec"] * args.batch * args.seq

    # the ONE shared formula (bench.py): windowed runs are scored on
    # their useful per-token context, not the full quadratic
    flops_tok = _llama_analytic_flops_per_token(
        cfg, matmul_param_count(trainer.state.params), args.seq,
        window=args.window,
    )
    peak = _peak_flops(devices[0])
    out = {
        "model": args.model,
        "seq": args.seq,
        "batch_per_chip": args.batch,
        "flash": args.flash,
        "window": args.window,
        "remat": bool(args.remat),
        "chunked_loss": args.chunked_loss,
        # the loss silently drops to the largest divisor of S-1 that
        # is <= the request — record what actually ran
        "chunked_loss_effective": _effective_chunks(
            args.seq - 1, args.chunked_loss
        ) if args.chunked_loss else 0,
        "step_ms": round(stats["step_ms"], 2),
        "tokens_per_sec_per_chip": round(tps, 1),
        "mfu_analytic": round(tps * flops_tok / peak, 4),
        "platform": devices[0].platform,
    }
    flops_xla = _step_flops(trainer, trainer.shard_batch(lm))
    if flops_xla:
        out["mfu_xla"] = round(flops_xla * stats["steps_per_sec"] / peak, 4)
    print(json.dumps(out), flush=True)
    if args.trace:
        # xplane capture of the hot step + top-op table (same tooling
        # as profile_resnet) — the trace-proven half of an MFU-ceiling
        # claim: the sweep shows the plateau, this names the ops
        from profile_resnet import summarize_xplane

        with jax.profiler.trace(args.trace):
            for _ in range(3):
                trainer.train_step(trainer.shard_batch(lm))
            jax.effects_barrier()
        summarize_xplane(args.trace)
    return 0


if __name__ == "__main__":
    sys.exit(main())
