"""Slow-tier budget gate (VERDICT r5 next #8: "cap the slow tier").

tests/conftest.py records every pytest session's wall clock per tier
into benchmarks/SUITE_RECORD.json; this check FAILS (exit 1) when the
most recent slow-tier run exceeded its budget, so a creeping e2e suite
is a round-end error rather than a silent tax.  Run it after the tiers:

    python -m pytest tests/ -m 'not slow' ...   # records tier1
    python -m pytest tests/ -m 'slow' ...       # records slow
    python benchmarks/check_tier_budget.py      # gate

No slow record yet = warn + exit 0 (tier-1-only rounds must not fail),
so the gate only bites rounds that actually ran the slow tier.
"""

from __future__ import annotations

import json
import os
import sys

#: VERDICT r5 target: slow tier < 30 min (at -n 4; serial runs get the
#: same cap — the point is the trend, and serial r5 measured ~11 min
#: for a 42-test sample, so the full suite has headroom to stay under)
SLOW_TIER_BUDGET_S = 1800.0

#: device cost plane (ISSUE 20): the tier-1 session's process compile
#: count, recorded by tests/conftest.py from
#: utils/costplane.process_compile_count().  The baseline is pinned
#: from the committed SUITE_RECORD.json of the round that introduced
#: the ledger; a run exceeding baseline * (1 + slack) means width-class
#: fragmentation (or a new unclassed hot path) crept in — red, don't
#: drift.  Re-pin deliberately when a round legitimately adds programs.
#: (Pinned from the ISSUE 20 introduction round: 88 registrations over
#: the full tier-1 set — wrap() counts per-instance first calls and
#: note() counts classes, so the number is deterministic per test set,
#: independent of XLA cache warmth.)
TIER1_COMPILE_BASELINE = 88
TIER1_COMPILE_SLACK = 0.25

RECORD_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "SUITE_RECORD.json"
)


def check(record: dict, budget_s: float = SLOW_TIER_BUDGET_S):
    """(ok, message) for a parsed SUITE_RECORD.json dict."""

    lines = []
    red = []
    for tier in ("tier1", "slow", "all"):
        row = record.get(tier)
        if row:
            lines.append(
                f"{tier}: {row['wall_s']:.0f}s wall, "
                f"{row.get('collected', '?')} collected, "
                f"exit {row.get('exitstatus', '?')} ({row.get('when', '?')})"
            )
            # 'all' is whatever unmarked pytest invocation ran last
            # (often a targeted local subset) — only the real tiers
            # can redden the gate
            if tier != "all" and row.get("exitstatus") not in (0, None):
                red.append(tier)
    summary = "\n".join(lines) if lines else "no recorded sessions"
    if red:
        # a wall-clock budget on a FAILING tier is meaningless — a red
        # record must never slip past the gate on timing alone
        return False, (
            summary
            + "\nRED TIER RECORD: "
            + ", ".join(
                f"{t} exited {record[t]['exitstatus']}" for t in red
            )
            + " — fix the failures and re-run the tier before gating"
        )
    # compile-count regression gate (ISSUE 20): the tier-1 record
    # carries the session's CompileLedger total; >25% over the pinned
    # baseline reds the round.  Records predating the ledger (no
    # `compiles` key) skip the gate rather than invent a number.
    tier1 = record.get("tier1")
    compiles = (tier1 or {}).get("compiles")
    if compiles is not None:
        ceiling = TIER1_COMPILE_BASELINE * (1.0 + TIER1_COMPILE_SLACK)
        if float(compiles) > ceiling:
            return False, (
                summary
                + f"\nTIER1 COMPILE REGRESSION: {int(compiles)} compiles"
                f" > {ceiling:.0f} (baseline {TIER1_COMPILE_BASELINE}"
                f" +{TIER1_COMPILE_SLACK:.0%}) — a hot path is"
                " fragmenting into new width/K classes; read GET"
                " /debug/compiles (or the costplane ledger in the"
                " failing test) for the trigger attribution, fix the"
                " classing, or re-pin TIER1_COMPILE_BASELINE with a"
                " justification here"
            )
        summary += (
            f"\ntier1 compiles: {int(compiles)} <= {ceiling:.0f}"
            f" (baseline {TIER1_COMPILE_BASELINE})"
        )
    slow = record.get("slow")
    if slow is None:
        return True, summary + "\nslow tier: no record yet (gate skipped)"
    # scheduler contention soak (ISSUE 16): the soak records its
    # decision counts into the slow-tier entry (tests/conftest.py
    # record_suite_extra).  A wedged scheduler that admitted nothing or
    # never exercised a cross-job preemption is a broken soak even if
    # every assertion somehow passed — red the record rather than let
    # the contention coverage rot silently.
    sched = slow.get("schedulerSoak")
    if sched is not None:
        admitted = int(sched.get("admitted", 0) or 0)
        preemptions = int(sched.get("preemptions", 0) or 0)
        if admitted < 1 or preemptions < 1:
            return False, (
                summary
                + f"\nSCHEDULER SOAK WEDGED: admitted={admitted}, "
                f"preemptions={preemptions} — the contention soak ran "
                "without exercising admission + cross-job preemption; "
                "see tests/test_scheduler_soak.py"
            )
        summary += (
            f"\nscheduler soak: {admitted} admissions, "
            f"{preemptions} preemptions, "
            f"{int(sched.get('sweeps', 0) or 0)} sweeps"
        )
    if float(slow["wall_s"]) > budget_s:
        return False, (
            summary
            + f"\nSLOW TIER OVER BUDGET: {slow['wall_s']:.0f}s > "
            f"{budget_s:.0f}s — collapse scenarios (shared-harness jobs, "
            "see tests/test_e2e_scenarios.py's merged boots) or raise "
            "the budget with a justification here"
        )
    return True, (
        summary
        + f"\nslow tier within budget: {slow['wall_s']:.0f}s <= {budget_s:.0f}s"
    )


def main() -> int:
    try:
        with open(RECORD_PATH) as f:
            record = json.load(f)
    except (OSError, ValueError):
        print("no benchmarks/SUITE_RECORD.json yet (gate skipped)")
        return 0
    ok, message = check(record)
    print(message)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
