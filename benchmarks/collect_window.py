"""Turn a measurement window's artifacts into BASELINE.md rows.

    python benchmarks/collect_window.py [--out-dir benchmarks/window_out]

Reads the per-step stdout files `tpu_window.py --out-dir` saved
(bench.out, sweep.out, llama-sweep.out, flash.out, train.out,
multislice.out), parses the numbers, and rewrites the
`<!-- train:begin -->` … `<!-- train:end -->` table in BASELINE.md.  Rows with no fresh data
keep their previous cell text (so a partial window never erases a
previously measured value), except the leading "pending — " prefix is
preserved as-is until a real number replaces it.

Also writes benchmarks/RESULTS.md with the raw parsed summary (sweep
matrices included) for the round's record, and
benchmarks/LAST_MEASURED.json — the machine-readable "most recent real
numbers" ledger that bench.py's error JSON points at when the chip is
unreachable, so a failed driver probe still references the last
measured values instead of a bare `value: 0.0` (VERDICT r4 next #9).

Idempotent and chip-free: safe to run any time, from the watcher or by
hand.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
BASELINE = os.path.join(REPO, "BASELINE.md")

BEGIN, END = "<!-- train:begin -->", "<!-- train:end -->"


def _read(out_dir: str, name: str) -> str:
    try:
        with open(os.path.join(out_dir, name)) as f:
            return f.read()
    except OSError:
        return ""


def _last_json_line(text: str) -> dict | None:
    """Last JSON object in the artifact — single-line (bench.py) or
    MULTI-LINE (measure.py prints `json.dumps(..., indent=1)`): from
    the last line opening an object, try parsing through to EOF."""

    lines = text.strip().splitlines()
    for i in reversed(range(len(lines))):
        s = lines[i].strip()
        if not s.startswith("{"):
            continue
        for candidate in ("\n".join(lines[i:]), s):
            try:
                return json.loads(candidate)
            except json.JSONDecodeError:
                continue
    return None


def _json_lines(text: str) -> list[dict]:
    out = []
    for line in text.strip().splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


def parse_artifacts(out_dir: str) -> dict:
    """Everything the window measured, flattened into one dict."""
    data: dict = {}

    bench = _last_json_line(_read(out_dir, "bench.out"))
    if bench and bench.get("value"):
        data["bench"] = bench
    train = _last_json_line(_read(out_dir, "train.out"))
    if train and "mnist_steps_per_sec_per_chip" in train:
        data["train"] = train
    batching = _last_json_line(_read(out_dir, "batching.out"))
    if batching and "batching_pool_tokens_per_sec" in batching:
        data["batching"] = batching
    spec = _last_json_line(_read(out_dir, "speculative.out"))
    if spec and "speculative_tokens_per_sec" in spec:
        data["speculative"] = spec
    # prefer the ON-CHIP serving row (ISSUE 10's paged-chip step —
    # fused-kernel decode bandwidth lives only there) when it came
    # from the CURRENT window: window_out is never cleared between
    # windows, and a window that dies before the chip step (the
    # tunnel has died mid-window before) must not let a weeks-old
    # chip artifact shadow the round's real data and get restamped
    # with today's date.  Freshness rule: within one window span
    # (24 h, windows run hours) of the CPU smoke the chip row wins —
    # the smoke step runs AFTER paged-chip in a healthy window, so a
    # strict newest-mtime rule would always discard the chip row.
    _PAGED_CHIP_STALE_S = 24 * 3600.0

    def _paged_row(name):
        row = _last_json_line(_read(out_dir, name))
        if not (row and "paged_tokens_per_sec" in row):
            return None, 0.0
        try:
            mtime = os.path.getmtime(os.path.join(out_dir, name))
        except OSError:
            mtime = 0.0
        return row, mtime

    chip_row, chip_mt = _paged_row("paged-chip.out")
    smoke_row, smoke_mt = _paged_row("paged.out")
    # freshness anchor: the smoke artifact when present, else NOW — a
    # missing/corrupt paged.out must not make an arbitrarily old chip
    # artifact look current (smoke_mt would be 0.0 and the age test
    # could never fire)
    anchor = smoke_mt if smoke_row else time.time()
    if chip_row and anchor - chip_mt > _PAGED_CHIP_STALE_S:
        chip_row = None  # stale: from an earlier window
    paged, paged_src = (
        (chip_row, "paged-chip.out") if chip_row else (smoke_row, "paged.out")
    )
    if paged:
        paged["_artifact"] = paged_src
        data["paged"] = paged

    # ISSUE 14: the multi-slice grad-sync smoke (flat vs hierarchical
    # bytes/step + step walls on the slice-aware sim mesh; real-DCN
    # walls ride the chip window like paged-chip)
    ms = _last_json_line(_read(out_dir, "multislice.out"))
    if ms and "multislice_dcn_bytes_ratio" in ms:
        data["multislice"] = ms

    # ISSUE 17: the cross-pod prefix-fabric smoke (2 pools over the
    # real FabricServer wire — remote hit rate, pulled bytes, p99 TTFT
    # local-only vs fleet)
    fab = _last_json_line(_read(out_dir, "fabric.out"))
    if fab and "fabric_remote_hit_rate" in fab:
        data["fabric"] = fab

    # ISSUE 18: speculative decoding on the paged plane — chip row
    # preferred under the same 24h freshness rule as paged above (the
    # CPU smoke runs AFTER speculative-paged-chip in a healthy window)
    def _spec_paged_row(name):
        row = _last_json_line(_read(out_dir, name))
        if not (row and "spec_paged_tokens_per_sec" in row):
            return None, 0.0
        try:
            mtime = os.path.getmtime(os.path.join(out_dir, name))
        except OSError:
            mtime = 0.0
        return row, mtime

    spc_chip, spc_chip_mt = _spec_paged_row("speculative-paged-chip.out")
    spc_smoke, spc_smoke_mt = _spec_paged_row("speculative-paged.out")
    spc_anchor = spc_smoke_mt if spc_smoke else time.time()
    if spc_chip and spc_anchor - spc_chip_mt > _PAGED_CHIP_STALE_S:
        spc_chip = None
    spc, spc_src = (
        (spc_chip, "speculative-paged-chip.out") if spc_chip
        else (spc_smoke, "speculative-paged.out")
    )
    if spc:
        spc["_artifact"] = spc_src
        data["speculative_paged"] = spc

    # ISSUE 19: fused train-mode BatchNorm A/B.  Two possible sources:
    # the dedicated chip step (profile_resnet --variant fusedbn --trace
    # → resnet-fused-chip.out, carries the trace-category chain diff)
    # and the measure.py train leg (train.out, always present).  The
    # chip artifact wins under the same 24h freshness rule as paged —
    # anchored to train.out, which runs after it in a healthy window.
    fbn_chip = _last_json_line(_read(out_dir, "resnet-fused-chip.out"))
    if fbn_chip and "resnet_fusedbn_step_ms_fused" in fbn_chip:
        try:
            fbn_mt = os.path.getmtime(
                os.path.join(out_dir, "resnet-fused-chip.out")
            )
        except OSError:
            fbn_mt = 0.0
        try:
            fbn_anchor = os.path.getmtime(os.path.join(out_dir, "train.out"))
        except OSError:
            fbn_anchor = time.time()
        if fbn_anchor - fbn_mt > _PAGED_CHIP_STALE_S:
            fbn_chip = None
    if fbn_chip:
        fbn_chip["_artifact"] = "resnet-fused-chip.out"
        data["fusedbn"] = fbn_chip
    elif train and "resnet_fusedbn_step_ms_fused" in train:
        fbn = {
            k: v for k, v in train.items() if k.startswith(
                ("resnet_fusedbn_", "fusedbn_trace_")
            )
        }
        fbn["_artifact"] = "train.out"
        data["fusedbn"] = fbn

    flash = _read(out_dir, "flash.out")
    m = re.search(
        r"flash fwd\+bwd @4k: ([\d.]+)ms\s+xla: ([\d.]+)ms\s+speedup ([\d.]+)x",
        flash,
    )
    if m:
        data["flash_fwd_bwd"] = {
            "flash_ms": float(m.group(1)),
            "xla_ms": float(m.group(2)),
            "speedup": float(m.group(3)),
        }
    m = re.search(
        r"windowed fwd\+bwd @8k/w1k: ([\d.]+)ms\s+full: ([\d.]+)ms\s+speedup ([\d.]+)x",
        flash,
    )
    if m:
        data["window_fwd_bwd"] = {
            "win_ms": float(m.group(1)),
            "full_ms": float(m.group(2)),
            "speedup": float(m.group(3)),
        }

    sweep = _json_lines(_read(out_dir, "sweep.out"))
    if sweep:
        data["sweep"] = sweep
    lsweep = _json_lines(_read(out_dir, "llama-sweep.out"))
    if lsweep:
        data["llama_sweep"] = lsweep
    # the wide existence-proof set plus every tuning pass that touched
    # serious-width shapes (wide-xover*.out and any future wide*.out —
    # globbed, so a new pass can't be silently dropped from the "best
    # wide MFU" computation).  Same JSON-row shape; the model=="wide"
    # filter excludes the mini cells mixed into the xover files.  Each
    # row remembers which artifact it came from (provenance rule).
    wide = []
    for path in sorted(glob.glob(os.path.join(out_dir, "wide*.out"))):
        fname = os.path.basename(path)
        for r in _json_lines(_read(out_dir, fname)):
            if "mfu_analytic" in r and r.get("model") == "wide":
                r["_artifact"] = fname
                wide.append(r)
    if wide:
        data["wide"] = wide
    return data


def write_last_measured(data: dict, today: str) -> None:
    """benchmarks/LAST_MEASURED.json: the flat most-recent-real-numbers
    ledger.  Merges over the previous file so a partial window never
    erases an older measurement — each key keeps its own provenance
    (source artifact + date)."""

    path = os.path.join(HERE, "LAST_MEASURED.json")
    try:
        with open(path) as fh:
            ledger = json.load(fh)
    except (OSError, json.JSONDecodeError):
        ledger = {}

    def put(key: str, value, artifact: str, backend: "str | None" = None) -> None:
        if value is not None:
            entry = {
                "value": value,
                "artifact": f"benchmarks/window_out/{artifact}",
                "date": today,
            }
            # backend-aware provenance (the PR 13 batching-row rule,
            # generalized): a CPU smoke re-measure must not wear chip
            # clothes in the machine-readable ledger — and it must not
            # REPLACE a chip-measured value either (entries without a
            # backend tag are chip-grade; bench.py's error fallback
            # points humans at this file)
            if backend and backend != "tpu":
                prev = ledger.get(key)
                if prev is not None and "backend" not in prev:
                    return
                entry["backend"] = backend
            ledger[key] = entry

    b = data.get("bench", {})
    put("resnet50_examples_per_sec_per_chip", b.get("value"), "bench.out")
    put("resnet50_mfu_analytic", b.get("mfu_analytic"), "bench.out")
    put(
        "llama_train_tokens_per_sec_per_chip",
        b.get("llama_train_tokens_per_sec_per_chip"), "bench.out",
    )
    put(
        "llama_decode_tokens_per_sec",
        b.get("llama_decode_tokens_per_sec"), "bench.out",
    )
    put(
        "llama_decode_int8_tokens_per_sec",
        b.get("llama_decode_int8_tokens_per_sec"), "bench.out",
    )
    put(
        "llama_wide_decode_tokens_per_sec",
        b.get("llama_wide_decode_tokens_per_sec"), "bench.out",
    )
    put(
        "llama_wide_decode_int8_tokens_per_sec",
        b.get("llama_wide_decode_int8_tokens_per_sec"), "bench.out",
    )
    put(
        "llama_wide_decode_int8_speedup",
        b.get("llama_wide_decode_int8_speedup"), "bench.out",
    )
    t = data.get("train", {})
    t_backend = t.get("train_backend")
    put("mnist_steps_per_sec_per_chip",
        t.get("mnist_steps_per_sec_per_chip"), "train.out",
        backend=t_backend)
    put("bert_base_steps_per_sec_per_chip",
        t.get("bert_base_steps_per_sec_per_chip"), "train.out",
        backend=t_backend)
    put("bert_base_mfu_analytic",
        t.get("bert_base_mfu_analytic"), "train.out",
        backend=t_backend)
    # r7: the step-sync ledger sweep — the top-K fused step time is the
    # "sync-free" training number; steady syncs/step is the invariant
    # (0.0 when the windowed loop holds).  Read from the sweep dict
    # itself so a non-default MEASURE_TRAIN_K window still lands its
    # headline instead of vanishing behind a hard-coded key.
    ksw = t.get("train_sync_k_sweep") or {}
    if ksw:
        k_top = max(ksw, key=int)
        put(
            f"train_k{k_top}_step_ms",
            ksw[k_top].get("step_ms"), "train.out", backend=t_backend,
        )
    put("train_steady_syncs_per_step",
        t.get("train_steady_syncs_per_step"), "train.out",
        backend=t_backend)
    put("train_prefetch_best_depth",
        t.get("train_prefetch_best_depth"), "train.out",
        backend=t_backend)
    put("train_prefetch_vs_resident",
        t.get("train_prefetch_vs_resident"), "train.out",
        backend=t_backend)
    # ISSUE 14: the multi-slice grad-sync smoke.  Byte/collective
    # accounting is platform-independent (same program structure on any
    # backend — collectives.py docstring), so those keys stay UNtagged
    # and any backend's window may refresh them; only the measured
    # walls carry the backend tag and defer to chip-grade entries.
    ms = data.get("multislice", {})
    ms_backend = ms.get("multislice_backend")
    for key in (
        "multislice_dcn_bytes_ratio",
        "multislice_dcn_bytes_ratio_vs_flat_mesh",
        "multislice_flat_dcn_bytes_per_step",
        "multislice_flat_mesh_dcn_bytes_per_step",
        "multislice_hier_dcn_bytes_per_step",
        "multislice_intra_slice_size",
        "multislice_dcn_collectives_per_step",
        "multislice_allclose_max_loss_err",
    ):
        put(key, ms.get(key), "multislice.out")
    for key in (
        "multislice_flat_step_ms",
        "multislice_hierarchical_step_ms",
        "multislice_step_wall_ratio",
    ):
        put(key, ms.get(key), "multislice.out", backend=ms_backend)
    bt = data.get("batching", {})
    put("batching_pool_tokens_per_sec",
        bt.get("batching_pool_tokens_per_sec"), "batching.out")
    put("batching_speedup", bt.get("batching_speedup"), "batching.out")
    put("batching_best_steps_per_sync",
        bt.get("batching_steps_per_sync"), "batching.out")
    put("batching_admission_dispatches_per_request",
        bt.get("batching_admission_dispatches_per_request"),
        "batching.out")
    pg = data.get("paged", {})
    pg_src = pg.get("_artifact", "paged.out")
    put("paged_tokens_per_sec", pg.get("paged_tokens_per_sec"),
        pg_src)
    put("paged_capacity_ratio", pg.get("paged_capacity_ratio"),
        pg_src)
    put("paged_prefix_hit_rate", pg.get("paged_prefix_hit_rate"),
        pg_src)
    put("paged_p99_ttft_s", pg.get("paged_p99_ttft_s"), pg_src)
    put("paged_equal_slots_wall_ratio",
        pg.get("paged_equal_slots_wall_ratio"), pg_src)
    # ISSUE 10: every decode-bandwidth MEASUREMENT the fused-kernel
    # leg emits (gather/fused tokens-per-sec per ctx x seats, read
    # speedups, the CPU interpret probe) — keyed dynamically so new
    # ctx/seat mixes land without a collector edit.  Config echoes
    # (paged_kernel_windows, backend strings) are not measurements
    # and stay out of the measured-keys ledger.
    _MEASURED_PREFIXES = (
        "paged_kernel_gather_",
        "paged_kernel_fused_",
        "paged_kernel_read_speedup_",
        "paged_kernel_interpret_max_err",
        # ISSUE 12 leg E: budget-on-demand capacity vs the worst-case
        # reservation baseline, per-tier SLO quantiles, preemption and
        # swap traffic under the two-tier oversubscribed trace
        "paged_lazy_capacity_",
        "paged_lazy_tokens_per_sec",
        "paged_worstcase_capacity_concurrent",
        "paged_worstcase_tokens_per_sec",
        "paged_tier_interactive_p99_",
        "paged_tier_batch_p99_",
        "paged_preemptions",
        "paged_swap_out_bytes",
        "paged_swap_in_bytes",
        # ISSUE 13 leg F: uniform vs prefill/decode-split fleet at the
        # same total arena — overall + per-class p99 TTFT, throughput,
        # and the fabric's publish/pull accounting
        "paged_uniform_",
        "paged_disagg_",
    )
    for key in sorted(pg):
        if key.startswith(_MEASURED_PREFIXES) and isinstance(
            pg[key], (int, float)
        ):
            put(key, pg[key], pg_src)
    # ISSUE 17: the cross-pod fabric smoke — every fabric_* measurement
    # (hit rate, pulled bytes, migrate_in count, local-vs-fleet TTFT
    # quantiles), keyed dynamically like the paged legs.  Walls and
    # TTFTs carry the backend tag; the wire/dispatch ACCOUNTING is
    # platform-independent and stays untagged so any backend's window
    # may refresh it.
    fab = data.get("fabric", {})
    fab_backend = fab.get("fabric_backend")
    _FABRIC_WALL_KEYS = ("_ttft_", "_tokens_per_sec")
    for key in sorted(fab):
        if key == "fabric_backend" or not isinstance(
            fab[key], (int, float)
        ):
            continue
        tagged = any(s in key for s in _FABRIC_WALL_KEYS)
        put(key, fab[key], "fabric.out",
            backend=fab_backend if tagged else None)
    sp = data.get("speculative", {})
    # backend-tagged since ISSUE 18: the wide leg runs as a CPU smoke
    # too, and a cpu wall must not displace the chip-grade 0.1x row
    sp_backend = sp.get("speculative_backend")
    put("speculative_speedup", sp.get("speculative_speedup"),
        "speculative.out", backend=sp_backend)
    # legacy pre-paged wide row — kept for provenance; since ISSUE 18
    # the serve_lm guard reads the spec_paged_* rows below
    put("speculative_wide_speedup", sp.get("speculative_wide_speedup"),
        "speculative.out", backend=sp_backend)
    # ISSUE 18: speculative decoding on the paged plane — the rows the
    # serve_lm --speculative guard actually reads.  Walls and TTFT
    # quantiles carry the backend tag (a CPU smoke must never displace
    # a chip row); acceptance and the ledger-pinned dispatches-per-
    # token arithmetic are platform-independent and stay untagged.
    spc = data.get("speculative_paged", {})
    spc_backend = spc.get("spec_paged_backend")
    spc_src = spc.get("_artifact", "speculative-paged.out")
    _SPEC_PAGED_WALL_KEYS = ("_tokens_per_sec", "_speedup", "_ttft_")
    for key in sorted(spc):
        if key == "spec_paged_backend" or not isinstance(
            spc[key], (int, float)
        ):
            continue
        tagged = any(s in key for s in _SPEC_PAGED_WALL_KEYS)
        put(key, spc[key], spc_src,
            backend=spc_backend if tagged else None)
    if (
        "spec_paged_config" in spc
        and isinstance(ledger.get("spec_paged_speedup"), dict)
        and ledger["spec_paged_speedup"].get("date") == today
    ):
        # serve_lm's refusal/lift message names the measured config;
        # only stamp it when THIS run's row actually landed (a cpu
        # smoke blocked by a chip-grade entry must not relabel it)
        ledger["spec_paged_speedup"]["config"] = spc["spec_paged_config"]
    # ISSUE 19: fused train-mode BN — walls/MFU/ratio and the trace
    # chain shares carry the backend tag (a CPU smoke's numbers must
    # never displace a chip-grade cell; CPU chain shares are client-
    # thread aggregates, chip shares are the critical path); the
    # interpret-numerics probe is platform-independent and untagged.
    fbn = data.get("fusedbn", {})
    fbn_backend = fbn.get("resnet_fusedbn_backend")
    fbn_src = fbn.get("_artifact", "train.out")
    _FUSEDBN_UNTAGGED = (
        "resnet_fusedbn_interpret_fwd_err",
        "resnet_fusedbn_interpret_grad_err",
    )
    for key in sorted(fbn):
        if (
            not key.startswith(("resnet_fusedbn_", "fusedbn_trace_"))
            or key in ("resnet_fusedbn_backend", "resnet_fusedbn_impl")
            or not isinstance(fbn[key], (int, float))
        ):
            continue
        put(key, fbn[key], fbn_src,
            backend=None if key in _FUSEDBN_UNTAGGED else fbn_backend)

    wd = data.get("wide")
    if wd:
        best = max(wd, key=lambda r: r["mfu_analytic"])
        put(
            "wide_llama_best_mfu_analytic",
            best["mfu_analytic"],
            best.get("_artifact", "wide.out"),
        )
    f = data.get("flash_fwd_bwd", {})
    put("flash_fwd_bwd_speedup_vs_xla_seq4k", f.get("speedup"), "flash.out")
    w = data.get("window_fwd_bwd", {})
    put("window_fwd_bwd_speedup_seq8k_w1k", w.get("speedup"), "flash.out")
    with open(path, "w") as fh:
        json.dump(ledger, fh, indent=1, sort_keys=True)
        fh.write("\n")


def build_rows(data: dict, today: str) -> dict[str, str]:
    """Map: row-key (first-cell prefix) -> fresh '| metric | value | setup |'
    line.  Only rows with fresh numbers appear.  Every setup cell names
    the window artifact the number was parsed from (VERDICT r4 next #9:
    BASELINE.md rows must be traceable to their evidence)."""
    rows: dict[str, str] = {}
    b = data.get("bench")
    if b:
        mfux = b.get("mfu_xla", "?")
        mfua = b.get("mfu_analytic", "?")
        rows["ResNet-50 examples/sec/chip"] = (
            "| ResNet-50 examples/sec/chip (train, bf16) | "
            f"**{b['value']} @ batch {b.get('batch_per_chip', '?')}**, "
            f"step {b.get('step_ms', '?')} ms, "
            f"**mfu_xla {mfux} / mfu_analytic {mfua}** "
            "(accounting: `benchmarks/FLOPS.md`) "
            f"| 1× v5 lite, `bench.py` → `window_out/bench.out`, {today} |"
        )
        if b.get("pipeline_examples_per_sec_per_chip"):
            ratio = b["pipeline_examples_per_sec_per_chip"] / b["value"]
            wire = ""
            h2d = b.get("h2d_mb_per_sec")
            if h2d is not None and ratio < 0.5:
                # wire-bound: on this box the chip is reached through a
                # network tunnel, so h2d bandwidth — not the framework —
                # caps the live-pipeline rate.  Say so with the numbers.
                wire = (
                    f" — **wire-bound**: measured h2d {h2d} MB/s over the "
                    f"tunnel vs {b.get('pipeline_wire_mb_per_step', '?')} "
                    "MB/step of input; on a real TPU VM (PCIe h2d) the "
                    "CPU smoke shows the loader keeps within ~5% of "
                    "device-resident"
                )
            rows["ResNet-50 with the input pipeline live"] = (
                "| ResNet-50 with the input pipeline live | "
                f"**{b['pipeline_examples_per_sec_per_chip']} ex/s/chip** "
                f"({ratio:.0%} of device-resident), step "
                f"{b.get('pipeline_step_ms', '?')} ms — grain loader from "
                "disk, uint8 wire, on-device normalise, prefetch 3"
                f"{wire} "
                f"| 1× v5 lite, `bench.py` `pipeline_*` → `window_out/bench.out`, {today} |"
            )
        if b.get("llama_train_tokens_per_sec_per_chip"):
            rows["llama-mini train tokens/sec/chip"] = (
                "| llama-mini train tokens/sec/chip (~120M, RoPE+GQA "
                "16q:4kv+SwiGLU, seq 1024, bf16, auto attention — the "
                "block-keyed crossover picks flash 1024x1024 here, the "
                "r5 autotune winner at every shape it tiles) | "
                f"**{b['llama_train_tokens_per_sec_per_chip']} tok/s/chip**, "
                f"step {b.get('llama_step_ms', '?')} ms, mfu_analytic "
                f"{b.get('llama_mfu_analytic', '?')} / mfu_xla "
                f"{b.get('llama_mfu_xla', '?')} "
                f"| 1× v5 lite, `bench.py` `llama_*` → `window_out/bench.out`, {today} |"
            )
        if b.get("llama_decode_tokens_per_sec"):
            int8 = b.get("llama_decode_int8_tokens_per_sec")
            int8_txt = (
                f", int8 weights-only **{int8} tok/s** (`ops/quant.py`)"
                if int8
                else ""
            )
            rows["llama-mini steady decode tokens/sec"] = (
                "| llama-mini steady decode tokens/sec (KV-cache greedy, "
                "batch 8) | "
                f"**{b['llama_decode_tokens_per_sec']} tok/s**{int8_txt} "
                f"| 1× v5 lite, `bench.py` → `window_out/bench.out`, {today} |"
            )
        if b.get("llama_wide_decode_int8_speedup"):
            rows["Wide-llama (~700M) int8 decode"] = (
                "| Wide-llama (~700M) int8 decode (batch-1 greedy — the "
                "weight-bandwidth-bound case int8 exists for; mini's "
                "batch-8 step is only ~60% weight reads, see "
                "PROFILE.md \"int8 decode\") | "
                f"bf16 {b.get('llama_wide_decode_tokens_per_sec', '?')} "
                f"tok/s → int8 "
                f"{b.get('llama_wide_decode_int8_tokens_per_sec', '?')} "
                f"tok/s — **{b['llama_wide_decode_int8_speedup']}×** "
                f"(`ops/quant.py` QTensor-direct) "
                f"| 1× v5 lite, `bench.py` → `window_out/bench.out`, {today} |"
            )
    t = data.get("train")
    if t:
        # provenance follows the artifact's backend (the paged/batching
        # row rule): a CPU-smoke K-sweep must not wear chip clothes,
        # and a smoke artifact without the chip-only BERT/llama legs
        # (MEASURE_TRAIN_TINY) must not clobber the measured chip row
        # with '?' cells
        t_backend = t.get("train_backend", "tpu")
        t_setup = (
            "1× v5 lite" if t_backend == "tpu"
            else f"{t_backend} smoke (sync/prefetch accounting; model "
            "rates are chip-meaningful only)"
        )
        if t.get("bert_base_steps_per_sec_per_chip") is not None:
            bert_mfu = ""
            if t.get("bert_base_mfu_analytic") is not None:
                bert_mfu = (
                    f", **mfu_analytic {t['bert_base_mfu_analytic']}** / "
                    f"mfu_xla {t.get('bert_base_mfu_xla', '?')} "
                    "(accounting: `benchmarks/FLOPS.md` \"BERT\")"
                )
            rows["mnist / BERT-base steps/sec/chip"] = (
                "| mnist / BERT-base steps/sec/chip | "
                f"mnist **{t.get('mnist_steps_per_sec_per_chip', '?')} steps/s** "
                f"({t.get('mnist_examples_per_sec_per_chip', '?')} ex/s); "
                f"BERT-base **{t.get('bert_base_steps_per_sec_per_chip', '?')} "
                f"steps/s** ({t.get('bert_base_examples_per_sec_per_chip', '?')} "
                f"ex/s, seq 128, fsdp){bert_mfu} "
                f"| {t_setup}, `measure.py --section train` → `window_out/train.out`, {today} |"
            )
        ksw = t.get("train_sync_k_sweep")
        if ksw:
            sweep_txt = ", ".join(
                f"K{k}: {row.get('step_ms', '?')} ms/step"
                for k, row in sorted(ksw.items(), key=lambda kv: int(kv[0]))
            )
            steady = t.get("train_steady_syncs_per_step")
            prefetch_txt = ""
            if t.get("train_prefetch_best_depth") is not None:
                prefetch_txt = (
                    f"; live-pipeline prefetch sweep: best depth "
                    f"{t['train_prefetch_best_depth']} at "
                    f"{t.get('train_prefetch_vs_resident', '?')}× of "
                    "device-resident"
                )
            cpu_caveat = (
                "" if t_backend == "tpu" else
                " — CPU walls run AGAINST K (XLA:CPU scan-under-SPMD, "
                "PROFILE.md r7 caveat); the ledger columns are the "
                "transferable signal, the chip window owns the walls"
            )
            rows["Training sync accounting"] = (
                "| Training sync accounting (mnist CNN through the "
                "harness train_loop, StepSyncLedger embedded — "
                "PROFILE.md \"step-sync ledger\") | "
                f"{sweep_txt}; steady-state blocking syncs/step "
                f"**{steady if steady is not None else '?'}** "
                "(K=1 = legacy per-step resolve; K>1 = fused "
                f"lax.scan windows, deferred metric resolve)"
                f"{cpu_caveat}{prefetch_txt} "
                f"| {t_setup}, `measure.py --section train` → `window_out/train.out`, {today} |"
            )
    # ISSUE 19: fused train-mode BatchNorm(+ReLU+residual) A/B
    fbn = data.get("fusedbn")
    if fbn:
        fbn_backend = fbn.get("resnet_fusedbn_backend", "?")
        fbn_on_chip = fbn_backend == "tpu"
        fbn_art = fbn.get("_artifact", "train.out")
        fbn_cmd = (
            "`profile_resnet.py --variant fusedbn`"
            if fbn_art == "resnet-fused-chip.out"
            else "`measure.py --section train`"
        )
        trace_txt = ""
        if fbn.get("fusedbn_trace_chain_share_drop") is not None:
            trace_txt = (
                "; traced reduce+elementwise+convert chain share "
                f"{fbn.get('fusedbn_trace_chain_share_stock', '?')} stock "
                f"→ {fbn.get('fusedbn_trace_chain_share_fused', '?')} "
                "fused (drop "
                f"**{fbn['fusedbn_trace_chain_share_drop']}**, "
                "`trace_categories.py`)"
            )
        caveat = (
            "" if fbn_on_chip else
            " — CPU smoke: walls/MFU are chip-meaningful only (the "
            "pallas kernel needs the TPU backend; this row carries the "
            "accounting + interpret-kernel numerics until the queued "
            "chip window lands)"
        )
        rows["ResNet train fusion"] = (
            "| ResNet train fusion (ISSUE 19: train-mode "
            "BN+ReLU(+residual) as ONE fused custom_vjp op, "
            f"`ops/fused_batchnorm.py`, impl "
            f"{fbn.get('resnet_fusedbn_impl', '?')}) | step "
            f"**{fbn.get('resnet_fusedbn_step_ms_fused', '?')} ms** "
            "fused vs "
            f"{fbn.get('resnet_fusedbn_step_ms_stock', '?')} ms stock — "
            f"**{fbn.get('resnet_fusedbn_step_wall_ratio', '?')}×**; "
            f"MFU {fbn.get('resnet_fusedbn_mfu_fused', '?')} vs "
            f"{fbn.get('resnet_fusedbn_mfu_stock', '?')}; loss max rel "
            f"err {fbn.get('resnet_fusedbn_loss_max_rel_err', '?')}; "
            "interpret-kernel probe fwd/grad err "
            f"{fbn.get('resnet_fusedbn_interpret_fwd_err', '?')}/"
            f"{fbn.get('resnet_fusedbn_interpret_grad_err', '?')}"
            f"{trace_txt}{caveat} "
            f"| {fbn_backend}, {fbn_cmd} → `window_out/{fbn_art}`, "
            f"{today} |"
        )

    ms = data.get("multislice")
    if ms:
        ms_backend = ms.get("multislice_backend", "?")
        ms_setup = (
            "multi-slice TPU" if ms_backend == "tpu"
            else f"{ms_backend} smoke, simulated 2-slice mesh — byte "
            "accounting/program structure are the signal; real-DCN "
            "walls ride the queued chip window"
        )
        mesh_txt = ", ".join(
            f"{ax}{n}" for ax, n in (ms.get("multislice_mesh") or {}).items()
        )
        probe = ms.get("multislice_sync_probe") or {}
        rows["Multi-slice training"] = (
            "| Multi-slice training (slice-aware mesh "
            f"{mesh_txt or '?'}: dp across slices/DCN, fsdp within a "
            "slice/ICI; hierarchical two-stage grad sync, "
            "`parallel/collectives.py`) | cross-slice gradient bytes/"
            f"step **{ms.get('multislice_hier_dcn_bytes_per_step', '?')} "
            "B** hierarchical — "
            f"**{ms.get('multislice_dcn_bytes_ratio', '?')}×** of the "
            "topology-BLIND pre-slice-aware baseline "
            f"({ms.get('multislice_flat_dcn_bytes_per_step', '?')} B "
            "full width, = 1/intra_slice_size "
            f"{ms.get('multislice_intra_slice_size', '?')}) and "
            f"**{ms.get('multislice_dcn_bytes_ratio_vs_flat_mesh', '?')}×**"
            " of the same-mesh flat program "
            f"({ms.get('multislice_flat_mesh_dcn_bytes_per_step', '?')} B"
            " — fsdp-sharded grads are already fragments there, so the "
            "slice-aware layout itself carries most of the win); "
            f"{ms.get('multislice_dcn_collectives_per_step', '?')} fused "
            "cross-slice collective(s)/step; step wall "
            f"{ms.get('multislice_hierarchical_step_ms', '?')} ms hier vs "
            f"{ms.get('multislice_flat_step_ms', '?')} ms flat "
            f"(**{ms.get('multislice_step_wall_ratio', '?')}×**); "
            "loss-trajectory A/B max err "
            f"{ms.get('multislice_allclose_max_loss_err', '?')}; sync "
            f"probe dcn {probe.get('dcn_fragment_s', '?')} s / ici "
            f"{probe.get('ici_reshard_s', '?')} s / flat "
            f"{probe.get('flat_full_s', '?')} s "
            f"| {ms_setup}, `measure.py --section multislice` → "
            f"`window_out/multislice.out`, {today} |"
        )
    bt = data.get("batching")
    if bt:
        n_new = bt.get("batching_new_tokens", "?")
        adm = bt.get("batching_admission_dispatches_per_request")
        ksw = bt.get("batching_k_sweep", {})
        sweep_txt = ", ".join(
            f"K{k}: {row.get('tokens_per_sec', '?')}"
            for k, row in sorted(
                ksw.items(), key=lambda kv: int(kv[0])
            )
        )
        # provenance follows the artifact's backend (the paged-row
        # rule): a CPU-smoke re-measure must not wear chip clothes
        bt_backend = bt.get("batching_backend", "tpu")
        bt_setup = (
            "1× v5 lite" if bt_backend == "tpu"
            else f"{bt_backend} smoke (llama-tiny; ~0 dispatch RTT — "
            "the tunnel-RTT term the chip row amortizes is absent here)"
        )
        bt_model = "llama-mini" if bt_backend == "tpu" else "llama-tiny"
        rows["Serving under concurrency"] = (
            "| Serving under concurrency (8 staggered requests, "
            f"{bt_model}, greedy {n_new} new tokens each) | continuous-"
            f"batching pool **{bt['batching_pool_tokens_per_sec']} "
            f"tok/s** at best K={bt.get('batching_steps_per_sync', '?')} "
            f"vs sequential "
            f"{bt['batching_sequential_tokens_per_sec']} tok/s — "
            f"**{bt['batching_speedup']}×** (`models/batching.py`, "
            "single-dispatch admission: "
            f"{adm if adm is not None else '?'} admission "
            f"dispatches/request; K sweep tok/s: {sweep_txt or '?'}; "
            "full dispatch ledger in the artifact + PROFILE.md "
            "\"dispatch ledger\") "
            f"| {bt_setup}, `measure.py --section batching` → `window_out/batching.out`, {today} |"
        )
    pg = data.get("paged")
    if pg:
        backend = pg.get("paged_backend", "?")
        pg_art = pg.get("_artifact", "paged.out")
        on_chip = backend == "tpu"
        # a chip-fed row's at-capacity number IS the measurement; only
        # the CPU smoke needs the compute-bound caveat
        capacity_caveat = (
            "at-capacity tok/s measured on chip"
            if on_chip
            else "at-capacity tok/s is chip-meaningful only — CPU "
            "smoke is compute-bound by the extra seats"
        )
        provenance = (
            f"1× v5 lite, `measure.py --section paged` → "
            f"`window_out/{pg_art}`"
            if on_chip
            else f"{backend} smoke, `measure.py --section paged` → "
            f"`window_out/{pg_art}`"
        )
        # ISSUE 10 provenance: which decode read produced the row —
        # fused Pallas kernel speedups when the window ran on chip,
        # otherwise the emulation with the interpret numerics probe
        speedups = {
            k: v for k, v in pg.items()
            if k.startswith("paged_kernel_read_speedup_")
        }
        if speedups:
            sp_txt = ", ".join(
                f"{k[len('paged_kernel_read_speedup_'):]}: {v}×"
                for k, v in sorted(speedups.items())
            )
            kernel_txt = (
                f"; decode read: FUSED Pallas paged-attention vs "
                f"gather emulation {sp_txt}"
            )
        else:
            err = pg.get("paged_kernel_interpret_max_err")
            probe_txt = (
                f"interpret probe max err {err}"
                if err is not None
                # pre-leg-D artifact (a window died before both paged
                # steps reran): say so instead of "max err None"
                else "no interpret probe in this artifact"
            )
            kernel_txt = (
                "; decode read: gather emulation (fused kernel needs "
                f"the TPU backend; {probe_txt})"
            )
        rows["Paged KV serving"] = (
            "| Paged KV serving (bursty mixed-length trace, "
            f"{pg.get('paged_trace_requests', '?')} requests, equal "
            f"arena budget of {pg.get('paged_arena_blocks', '?')} "
            "blocks) | capacity "
            f"**{pg.get('paged_capacity_ratio', '?')}×** at the same "
            f"HBM (**{pg.get('paged_concurrent_admitted', '?')} "
            "concurrent** paged vs "
            f"{pg.get('paged_slot_baseline_concurrent', '?')} slot-"
            "bound), prefix-hit rate "
            f"**{pg.get('paged_prefix_hit_rate', '?')}**; equal-seats "
            "wall ratio "
            f"**{pg.get('paged_equal_slots_wall_ratio', '?')}×** "
            "(<1 = paged faster: prefix hits skip prefill; "
            f"{pg.get('paged_equal_slots_tokens_per_sec', '?')} vs "
            f"{pg.get('paged_slot_baseline_tokens_per_sec', '?')} "
            "tok/s); at-capacity "
            f"{pg['paged_tokens_per_sec']} tok/s"
            # a pre-fix artifact without the tier-labeled p99 must not
            # print "p99 TTFT ≤ None" (the interpret-probe rule)
            + (
                f", p99 TTFT ≤ {pg['paged_p99_ttft_s']} s "
                if pg.get("paged_p99_ttft_s") is not None else " "
            )
            + "(`models/batching.PagedContinuousBatchingDecoder`, block-"
            "gated admission + shared prefix cache; ledger in the "
            f"artifact; {capacity_caveat}{kernel_txt}) "
            f"| {provenance}, {today} |"
        )
        # ISSUE 12 leg E: the budget-on-demand + preemption + tier row
        if pg.get("paged_lazy_capacity_concurrent") is not None:
            rows["Tiered oversubscribed serving"] = (
                "| Tiered oversubscribed serving (two-tier bursty "
                f"trace, {pg.get('paged_tier_trace_requests', '?')} "
                "requests at "
                f"{pg.get('paged_tier_trace_demand_ratio', '?')}× "
                "worst-case arena demand, interactive share "
                f"{pg.get('paged_tier_interactive_share', '?')}) | "
                "budget-on-demand admits "
                f"**{pg.get('paged_lazy_capacity_concurrent', '?')} "
                "concurrent** vs "
                f"{pg.get('paged_worstcase_capacity_concurrent', '?')} "
                "worst-case-reserved — "
                f"**{pg.get('paged_lazy_capacity_ratio', '?')}×**; "
                "interactive p99 TTFT "
                f"**{pg.get('paged_tier_interactive_p99_ttft_s', '?')} "
                "s** vs batch "
                f"{pg.get('paged_tier_batch_p99_ttft_s', '?')} s; "
                f"{pg.get('paged_preemptions', '?')} preemption(s), "
                f"swap {pg.get('paged_swap_out_bytes', '?')} B out / "
                f"{pg.get('paged_swap_in_bytes', '?')} B in "
                "(`models/batching.py` lazy reservation + mid-decode "
                "preemption with host KV swap + SLO tiers; "
                f"{'on-chip' if on_chip else 'CPU smoke — tok/s cells are chip-meaningful only'}) "
                f"| {provenance}, {today} |"
            )
        # ISSUE 13 leg F: disaggregated vs uniform fleet at the same
        # total arena under the mixed long-prompt/short-decode trace
        if pg.get("paged_disagg_p99_ttft_s") is not None:
            rows["Disaggregated serving"] = (
                "| Disaggregated serving (prefill/decode-split 2-"
                "replica fleet vs uniform, SAME total arena of "
                f"{pg.get('paged_disagg_arena_blocks_total', '?')} "
                "blocks, mixed long-prompt/short-decode bursty trace, "
                f"{pg.get('paged_disagg_trace_requests', '?')} requests "
                f"at long share "
                f"{pg.get('paged_disagg_long_share', '?')}) | p99 TTFT "
                f"**{pg.get('paged_disagg_p99_ttft_s', '?')} s** split "
                f"vs {pg.get('paged_uniform_p99_ttft_s', '?')} s "
                "uniform — "
                f"**{pg.get('paged_disagg_ttft_p99_speedup', '?')}×** "
                "(short-decode class "
                f"{pg.get('paged_disagg_short_p99_ttft_s', '?')} vs "
                f"{pg.get('paged_uniform_short_p99_ttft_s', '?')} s — "
                "prefill head-of-line blocking off the decode loop; "
                "long class "
                f"{pg.get('paged_disagg_long_p99_ttft_s', '?')} vs "
                f"{pg.get('paged_uniform_long_p99_ttft_s', '?')} s); "
                f"{pg.get('paged_disagg_fabric_publishes', '?')} fabric "
                "publishes, "
                f"{pg.get('paged_disagg_migrate_in_dispatches', '?')} "
                "migrate_in pull(s), tok/s "
                f"{pg.get('paged_disagg_tokens_per_sec', '?')} vs "
                f"{pg.get('paged_uniform_tokens_per_sec', '?')} "
                "(`models/pool_router.py` phase-aware routing + "
                "`prefix_cache.PrefixFabric` migration transport; "
                f"{'on-chip' if on_chip else 'CPU smoke — tok/s gap inflated by multi-core prefill/decode overlap; the p99 ordering is the transferable signal'}) "
                f"| {provenance}, {today} |"
            )
    # ISSUE 17: cross-pod prefix fabric — 2 pools over the real wire
    fab = data.get("fabric")
    if fab:
        fab_backend = fab.get("fabric_backend", "?")
        fab_on_chip = fab_backend == "tpu"
        rows["Cross-pod prefix fabric"] = (
            "| Cross-pod prefix fabric (2-pod shared-system-prompt "
            f"smoke over the REAL FabricServer wire, "
            f"{fab.get('fabric_trace_requests', '?')} requests sharing "
            f"{fab.get('fabric_prefixes', '?')} prefixes of "
            f"{fab.get('fabric_prefix_blocks', '?')} blocks) | remote "
            f"hit rate **{fab.get('fabric_remote_hit_rate', '?')}** "
            f"({fab.get('fabric_pull_hits', '?')} block pulls, "
            f"{fab.get('fabric_pull_bytes', '?')} B over HTTP, "
            f"{fab.get('fabric_pull_failures', '?')} failures), "
            f"{fab.get('fabric_migrate_in_dispatches', '?')} migrate_in "
            "dispatch(es) — one per cold prefix; p99 TTFT fleet "
            f"**{fab.get('fabric_fleet_p99_ttft_s', '?')} s** vs "
            f"{fab.get('fabric_local_p99_ttft_s', '?')} s local-only "
            f"(**{fab.get('fabric_ttft_p99_speedup', '?')}×**; cold "
            f"class {fab.get('fabric_fleet_cold_p99_ttft_s', '?')} vs "
            f"{fab.get('fabric_local_cold_p99_ttft_s', '?')} s) "
            "(`models/fabric_service.py` content-addressed chain pull "
            "→ one migrate_in; "
            + (
                "on-chip"
                if fab_on_chip
                else "CPU smoke — the pull is host HTTP while the "
                "avoided prefill is CPU compute, so the TTFT delta's "
                "sign is box-dependent; the hit-rate/bytes/dispatch "
                "accounting is the transferable signal"
            )
            + ") "
            f"| {fab_backend} smoke, `measure.py --section fabric` → "
            f"`window_out/fabric.out`, {today} |"
        )
    sp = data.get("speculative")
    if sp:
        wide_txt = (
            " — no wide draft≠target row this window"
        )
        if sp.get("speculative_wide_speedup") is not None:
            wide_txt = (
                f"; draft≠target wide-700M target int8 draft: "
                f"**{sp.get('speculative_wide_tokens_per_sec', '?')} "
                f"tok/s** vs plain "
                f"{sp.get('speculative_wide_plain_tokens_per_sec', '?')} "
                f"— **{sp['speculative_wide_speedup']}×**, acceptance "
                f"{sp.get('speculative_wide_acceptance', '?')}"
            )
        elif sp.get("speculative_wide_error"):
            wide_txt = (
                f"; wide row errored: {sp['speculative_wide_error'][:80]}"
            )
        sp_prov = (
            "1× v5 lite"
            if sp.get("speculative_backend") == "tpu"
            else f"{sp.get('speculative_backend', '?')} smoke"
        )
        rows["Self-speculative decode"] = (
            "| Self-speculative decode (llama-mini batch 1, int8 draft "
            "of the same weights, k=4) | "
            f"**{sp['speculative_tokens_per_sec']} tok/s** vs plain "
            f"{sp['speculative_plain_tokens_per_sec']} tok/s — "
            f"**{sp['speculative_speedup']}×**, acceptance "
            f"{sp.get('speculative_acceptance', '?')} "
            f"(`models/speculative.py`){wide_txt}.  Since ISSUE 18 "
            "`serve_lm --speculative` reads the paged-plane row below, "
            "not this one "
            f"| {sp_prov}, `measure.py --section speculative` → `window_out/speculative.out`, {today} |"
        )
    spc = data.get("speculative_paged")
    if spc:
        spc_backend = spc.get("spec_paged_backend", "?")
        spc_on_chip = spc_backend == "tpu"
        spc_art = spc.get("_artifact", "speculative-paged.out")
        spc_cfg = spc.get(
            "spec_paged_config", "int8 self-draft in the shared block arena"
        )
        rows["Speculative paged serving"] = (
            "| Speculative paged serving (ISSUE 18: "
            f"{spc_cfg}) | "
            f"**{spc.get('spec_paged_tokens_per_sec', '?')} tok/s** vs "
            "non-speculative paged pool "
            f"{spc.get('spec_paged_plain_tokens_per_sec', '?')} tok/s "
            "at the same arena — "
            f"**{spc.get('spec_paged_speedup', '?')}×**, acceptance "
            f"{spc.get('spec_paged_acceptance', '?')}, "
            f"**{spc.get('spec_paged_dispatches_per_token', '?')} "
            "dispatches/token** (ledger-pinned 1 draft + 1 verify per "
            "window), interactive p99 TTFT "
            f"{spc.get('spec_paged_p99_ttft_s', '?')}s vs "
            f"{spc.get('spec_paged_plain_p99_ttft_s', '?')}s"
            + (
                ""
                if spc_on_chip
                else " (CPU smoke — walls are backend-tagged; the "
                "acceptance and dispatch arithmetic are the "
                "transferable signal)"
            )
            + ".  `serve_lm --speculative` reads THIS row and refuses "
            "while the best measured ratio is < 1× "
            f"| {spc_backend}, `measure.py --section speculative-paged`"
            f" → `window_out/{spc_art}`, {today} |"
        )
    wd = data.get("wide")
    if wd:
        best = max(wd, key=lambda r: r["mfu_analytic"])
        art = best.get("_artifact", "wide.out")
        rows["Wide-llama (~700M) MFU existence proof"] = (
            "| Wide-llama (~700M) MFU existence proof (d_model 2048, "
            "12L, GQA 16q:8kv, SwiGLU — VERDICT r4 next #3) | best "
            f"**mfu_analytic {best['mfu_analytic']}** / mfu_xla "
            f"{best.get('mfu_xla', '?')} at seq {best.get('seq', '?')} "
            f"batch {best.get('batch_per_chip', '?')} "
            f"(remat {best.get('remat', '?')}, "
            f"{'flash' if best.get('flash') != '0' else 'xla'} "
            f"attention — `{best.get('label', '?')}`), "
            f"{best.get('tokens_per_sec_per_chip', '?')} tok/s/chip; "
            f"{len(wd)} variants measured "
            f"| 1× v5 lite, `llama_sweep.py` wide sets → "
            f"`window_out/{art}`, {today} |"
        )
    f = data.get("flash_fwd_bwd")
    if f:
        rows["Flash vs XLA attention, fwd+bwd"] = (
            "| Flash vs XLA attention, fwd+bwd @ seq 4096 (causal, bf16, "
            "B2 H8 D128) | "
            f"**{f['speedup']:.2f}×** ({f['flash_ms']:.1f} ms vs "
            f"{f['xla_ms']:.1f} ms); fwd-only was ~5× @ seq 8192 (round 1), "
            "runs seq 32k where XLA OOMs "
            f"| 1× v5 lite, `tests/test_tpu_chip.py` → `window_out/flash.out`, {today} |"
        )
    w = data.get("window_fwd_bwd")
    if w:
        rows["Windowed vs full flash attention"] = (
            "| Windowed vs full flash attention, fwd+bwd @ seq 8192 / "
            "window 1024 | "
            f"**{w['speedup']:.2f}×** ({w['win_ms']:.1f} ms vs "
            f"{w['full_ms']:.1f} ms full) "
            f"| 1× v5 lite, `tests/test_tpu_chip.py` → `window_out/flash.out`, {today} |"
        )
    return rows


def rewrite_baseline(rows: dict[str, str], path: str = BASELINE) -> int:
    with open(path) as fh:
        text = fh.read()
    head, rest = text.split(BEGIN, 1)
    table, tail = rest.split(END, 1)
    pending = dict(rows)
    out_lines, replaced = [], 0
    for line in table.strip().splitlines():
        if line.startswith("|"):
            first_cell = line.split("|")[1].strip()
            for key in list(pending):
                if first_cell.lower().startswith(key.lower()):
                    line = pending.pop(key)
                    replaced += 1
                    break
        out_lines.append(line)
    # fresh metrics with no existing row (a measurement added after the
    # table was authored) append rather than vanish
    for key in pending:
        out_lines.append(pending[key])
        replaced += 1
    new = head + BEGIN + "\n" + "\n".join(out_lines) + "\n" + END + tail
    with open(path, "w") as fh:
        fh.write(new)
    return replaced


def write_results(data: dict, today: str) -> None:
    path = os.path.join(HERE, "RESULTS.md")
    with open(path, "w") as fh:
        fh.write(f"# Measurement window results — {today}\n\n")
        fh.write("Raw parsed artifacts from the last completed window\n"
                 "(`benchmarks/window_out/`), collected by "
                 "`collect_window.py`.\n\n")
        for key in (
            "bench", "train", "fusedbn", "batching", "speculative",
            "speculative_paged", "paged", "fabric", "multislice",
            "flash_fwd_bwd", "window_fwd_bwd",
        ):
            if key in data:
                fh.write(f"## {key}\n\n```json\n"
                         + json.dumps(data[key], indent=1) + "\n```\n\n")
        for key in ("sweep", "llama_sweep", "wide"):
            if key in data:
                fh.write(f"## {key}\n\n")
                for row in data[key]:
                    fh.write("- `" + json.dumps(row) + "`\n")
                fh.write("\n")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default=os.path.join(HERE, "window_out"))
    args = ap.parse_args()
    data = parse_artifacts(args.out_dir)
    if not data:
        print("no window artifacts found; BASELINE.md untouched")
        return 1
    today = time.strftime("%Y-%m-%d")
    n = rewrite_baseline(build_rows(data, today))
    write_results(data, today)
    write_last_measured(data, today)
    print(f"updated {n} BASELINE.md rows; wrote benchmarks/RESULTS.md "
          f"(sections: {', '.join(sorted(data))})")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
