"""Llama-mini MFU sweep: the variant matrix for the transformer
co-headline, one child process per run (tunnel-stall-proof, fresh env
per variant — same harness discipline as mfu_sweep.py).

What it answers on the chip:
  - flash vs XLA attention at training shapes (fwd+bwd, seq 1024-4096);
  - whether remat buys a bigger batch that pays for its recompute;
  - the banded-window kernels' wall-clock win at long seq;
  - where MFU lands vs the >=0.40 target on a workload whose hot loop
    is THIS framework's kernels.

Usage: python benchmarks/llama_sweep.py [--quick] [--timeout 600]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

#: (label, extra args for profile_llama.py[, extra env])
MATRIX = [
    ("s1024-flash", ["--seq", "1024", "--batch", "8"]),
    ("s1024-xla", ["--seq", "1024", "--batch", "8", "--flash", "0"]),
    ("s2048-flash", ["--seq", "2048", "--batch", "4"]),
    ("s2048-xla", ["--seq", "2048", "--batch", "4", "--flash", "0"]),
    ("s4096-flash", ["--seq", "4096", "--batch", "2"]),
    ("s4096-w1024", ["--seq", "4096", "--batch", "2", "--window", "1024"]),
    ("s1024-remat-b16", ["--seq", "1024", "--batch", "16", "--remat"]),
    ("s1024-b16", ["--seq", "1024", "--batch", "16"]),
    # flash kernel block autotune (ops/flash_attention.default_flash_blocks
    # reads these env knobs): if a shape wins clearly, pin it as the
    # default in a followup — the committed sweep output is the evidence
    ("s1024-bq256", ["--seq", "1024", "--batch", "8"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256"}),
    ("s1024-bk256", ["--seq", "1024", "--batch", "8"],
     {"TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("s1024-b256x256", ["--seq", "1024", "--batch", "8"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("s2048-b512x256", ["--seq", "2048", "--batch", "4"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("s2048-b256x256", ["--seq", "2048", "--batch", "4"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("s4096-b256x256", ["--seq", "4096", "--batch", "2"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
]

#: the >=0.40-MFU existence proof (VERDICT r4 next #3): llama-mini's
#: d_model 1024 cannot fill the MXU; these run the ~700M d_model-2048
#: config (bench.llama_wide_config) at serious widths.  Ordered so a
#: window that dies mid-step still lands the headline shape first.
WIDE = [
    ("wide-s2048-b2-remat",
     ["--model", "wide", "--seq", "2048", "--batch", "2", "--remat"]),
    ("wide-s2048-b2-remat-xla",
     ["--model", "wide", "--seq", "2048", "--batch", "2", "--remat",
      "--flash", "0"]),
    ("wide-s2048-b4-remat",
     ["--model", "wide", "--seq", "2048", "--batch", "4", "--remat"]),
    ("wide-s4096-b1-remat",
     ["--model", "wide", "--seq", "4096", "--batch", "1", "--remat"]),
    ("wide-s1024-b4-remat",
     ["--model", "wide", "--seq", "1024", "--batch", "4", "--remat"]),
    # non-remat shapes: remat trades recompute for HBM headroom, but at
    # ~700M on a 16G chip the activations may simply fit — if so these
    # are the honest-MFU front-runners (no recomputed flops)
    ("wide-s2048-b2", ["--model", "wide", "--seq", "2048", "--batch", "2"]),
    ("wide-s1024-b4", ["--model", "wide", "--seq", "1024", "--batch", "4"]),
    ("wide-s2048-b2-xla",
     ["--model", "wide", "--seq", "2048", "--batch", "2", "--flash", "0"]),
    # the first >=0.40 existence proof (2026-08-01: mfu_analytic
    # 0.4654, 23,258 tok/s, XLA attention) — at that point XLA beat
    # the flash kernel's then-256x256 blocks at D=128.  SUPERSEDED the
    # same day by the XOVER block-tuning passes below: with 512x512
    # blocks flash wins every wide shape (best mfu 0.6163 at s512).
    # NOTE the wide s2048 xla variants crash in the tunnel's
    # remote-compile helper (HTTP 500, helper exit 1) — infra, not
    # model; see PROFILE.md.
    ("wide-s1024-b4-xla",
     ["--model", "wide", "--seq", "1024", "--batch", "4", "--flash", "0"]),
]

#: head-dim crossover matrix (r5 follow-up): the dispatcher's seq-only
#: MIN_SEQ was tuned at mini's D=64 heads, but at wide's D=128 XLA won
#: seq 1024 by 1.32x — so where (if anywhere) does flash win at D=128,
#: and do bigger q blocks close the gap?  Also retries the two wide
#: -xla variants that died on the transient remote-compile-helper 500,
#: probes batch 8 at the existence-proof shape (more rows may raise
#: the 0.4654 headline if it still fits HBM), and lands the first
#: seq-4096 non-remat wide numbers on both paths.
WIDE_XOVER = [
    ("wx-s2048-b2-xla",
     ["--model", "wide", "--seq", "2048", "--batch", "2", "--flash", "0"]),
    ("wx-s2048-b2-b512x256",
     ["--model", "wide", "--seq", "2048", "--batch", "2"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("wx-s1024-b4-b512x256",
     ["--model", "wide", "--seq", "1024", "--batch", "4"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("wx-s1024-b8-xla",
     ["--model", "wide", "--seq", "1024", "--batch", "8", "--flash", "0"]),
    ("wx-s4096-b1-flash", ["--model", "wide", "--seq", "4096", "--batch", "1"]),
    ("wx-s4096-b1-xla",
     ["--model", "wide", "--seq", "4096", "--batch", "1", "--flash", "0"]),
    ("wx-s2048-b2-b256x512",
     ["--model", "wide", "--seq", "2048", "--batch", "2"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
]

#: second tuning pass after WIDE_XOVER's findings (bq512/bk256 won
#: s1024 at 0.5667; bq256/bk512 won s2048 at 0.5646 — large blocks in
#: EITHER grid dim beat the 256x256 default at D=128): complete the
#: 512-block quadrant at wide, and check whether mini's D=64 shapes
#: also prefer 512 blocks (its committed winners were 256x256 at s1024
#: and bq512/bk256 at s2048; bk512 was never tried on mini).
WIDE_XOVER2 = [
    ("wx2-s1024-b4-b256x512",
     ["--model", "wide", "--seq", "1024", "--batch", "4"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx2-s1024-b4-b512x512",
     ["--model", "wide", "--seq", "1024", "--batch", "4"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx2-s2048-b2-b512x512",
     ["--model", "wide", "--seq", "2048", "--batch", "2"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx2-s4096-b1-b256x512",
     ["--model", "wide", "--seq", "4096", "--batch", "1"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx2-s4096-b1-b512x256",
     ["--model", "wide", "--seq", "4096", "--batch", "1"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("wx2-mini-s1024-b256x512",
     ["--seq", "1024", "--batch", "8"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx2-mini-s1024-b512x256",
     ["--seq", "1024", "--batch", "8"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("wx2-mini-s2048-b256x512",
     ["--seq", "2048", "--batch", "4"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx2-mini-s4096-b256x512",
     ["--seq", "4096", "--batch", "2"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
]

#: the last untried 512x512 cells (bk=512 dominated everywhere in
#: XOVER2; bq256-vs-512 is the remaining 3-10% question per shape)
WIDE_XOVER3 = [
    ("wx3-s4096-b1-b512x512",
     ["--model", "wide", "--seq", "4096", "--batch", "1"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx3-mini-s1024-b512x512",
     ["--seq", "1024", "--batch", "8"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx3-mini-s2048-b512x512",
     ["--seq", "2048", "--batch", "4"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
]

#: 512x512 won every XOVER2/3 cell on both head dims (up to 1.63-2.3x
#: over XLA-fused).  Finish the table: mini s4096 at 512x512, and the
#: seq-512 shapes that decide whether the auto-crossover MIN_SEQ drops
#: below 1024 (at seq 512 the 512 blocks tile exactly — one grid step).
WIDE_XOVER4 = [
    ("wx4-mini-s4096-b512x512",
     ["--seq", "4096", "--batch", "2"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx4-mini-s512-b16-b512x512",
     ["--seq", "512", "--batch", "16"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx4-mini-s512-b16-xla",
     ["--seq", "512", "--batch", "16", "--flash", "0"]),
    ("wx4-wide-s512-b8-b512x512",
     ["--model", "wide", "--seq", "512", "--batch", "8"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx4-wide-s512-b8-xla",
     ["--model", "wide", "--seq", "512", "--batch", "8", "--flash", "0"]),
]

#: MFU frontier pass: 0.6163 landed at wide s512 b8 and MFU rose as
#: seq shrank (attention's share falls, the MXU-dense MLP GEMMs
#: dominate) — so probe bigger batches at s512 and the s256 shapes
#: (256-class blocks tile s256; the 512s don't).  HBM check: b16 s512
#: non-remat has the same activation footprint as the b4 s1024 row
#: that fit.
WIDE_XOVER5 = [
    ("wx5-wide-s512-b16-b512x512",
     ["--model", "wide", "--seq", "512", "--batch", "16"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx5-wide-s256-b32-b256x256",
     ["--model", "wide", "--seq", "256", "--batch", "32"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("wx5-wide-s256-b32-xla",
     ["--model", "wide", "--seq", "256", "--batch", "32", "--flash", "0"]),
    ("wx5-wide-s512-b32-b512x512",
     ["--model", "wide", "--seq", "512", "--batch", "32"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
    ("wx5-mini-s512-b32-b512x512",
     ["--seq", "512", "--batch", "32"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "512", "TPU_OPERATOR_FLASH_BLOCK_K": "512"}),
]

#: 256-class floor calibration: wx5 showed 256-blocks WIN at wide s256
#: (0.675 vs 0.613 XLA) yet the block-keyed floor (1024, measured on
#: mini >= 1024) routes s256 to XLA.  Complete the short-seq cells on
#: mini so the 256-class floor is set from data at the seqs where the
#: class actually runs (s256/s512 shrink the 512 defaults to 256).
WIDE_XOVER6 = [
    ("wx6-mini-s512-b16-b256x256",
     ["--seq", "512", "--batch", "16"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("wx6-mini-s512-b16-xla",
     ["--seq", "512", "--batch", "16", "--flash", "0"]),
    ("wx6-mini-s256-b32-b256x256",
     ["--seq", "256", "--batch", "32"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "256", "TPU_OPERATOR_FLASH_BLOCK_K": "256"}),
    ("wx6-mini-s256-b32-xla",
     ["--seq", "256", "--batch", "32", "--flash", "0"]),
]



#: 1024-block pass: the 128->256->512 win was monotone, so keep going.
#: 1024x1024 wins everywhere it tiles (committed artifact: mini
#: s1024 +8%, s2048 +9%, s4096 +19%; wide s1024 +2%, s2048 +3%,
#: s4096 +7% at 25.4k tok/s); 2048x2048 is past the VMEM wall
#: (pallas stack alloc 30.85M vs the 16M scoped limit — and the
#: compile-helper's "unexpected worker hostname" noise accompanies
#: that OOM, explaining the wide-s2048 XLA "infra" crashes too).
WIDE_XOVER7 = [
    ("wx7-mini-s1024-b1024",
     ["--seq", "1024", "--batch", "8"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "1024", "TPU_OPERATOR_FLASH_BLOCK_K": "1024"}),
    ("wx7-mini-s2048-b1024",
     ["--seq", "2048", "--batch", "4"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "1024", "TPU_OPERATOR_FLASH_BLOCK_K": "1024"}),
    ("wx7-mini-s4096-b1024",
     ["--seq", "4096", "--batch", "2"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "1024", "TPU_OPERATOR_FLASH_BLOCK_K": "1024"}),
    ("wx7-wide-s1024-b1024",
     ["--model", "wide", "--seq", "1024", "--batch", "4"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "1024", "TPU_OPERATOR_FLASH_BLOCK_K": "1024"}),
    ("wx7-wide-s2048-b1024",
     ["--model", "wide", "--seq", "2048", "--batch", "2"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "1024", "TPU_OPERATOR_FLASH_BLOCK_K": "1024"}),
    ("wx7-wide-s4096-b1024",
     ["--model", "wide", "--seq", "4096", "--batch", "1"],
     {"TPU_OPERATOR_FLASH_BLOCK_Q": "1024", "TPU_OPERATOR_FLASH_BLOCK_K": "1024"}),
]



#: longest-context row: does the ~0.57-MFU long-seq plateau hold at 8k?
WIDE_XOVER8 = [
    ("wx8-wide-s8192-b1",
     ["--model", "wide", "--seq", "8192", "--batch", "1"]),
    ("wx8-wide-s8192-b1-remat",
     ["--model", "wide", "--seq", "8192", "--batch", "1", "--remat"]),
    ("wx8-mini-s8192-b1",
     ["--seq", "8192", "--batch", "1"]),
]


def run_one(label, extra, timeout, env_extra=None):
    cmd = [sys.executable, os.path.join(HERE, "profile_llama.py"), *extra]
    env = dict(os.environ, **(env_extra or {}))
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout, env=env
        )
    except subprocess.TimeoutExpired:
        return {"label": label, "error": f"timeout >{timeout}s"}
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                out = json.loads(line)
                out["label"] = label
                return out
            except json.JSONDecodeError:
                continue
    tail = (proc.stderr or "").strip().splitlines()
    # name the real failure, not log noise: the LATEST line that looks
    # like an exception; else the last non-banner line.  rc is always
    # included — a signal death (rc < 0) often leaves no traceback at
    # all, and early E-level init noise must not masquerade as a cause.
    strong = last = None
    for line in reversed(tail):
        s = line.strip()
        if not s or "removed its internal frames" in s or s.startswith(
            "Set JAX_TRACEBACK_FILTERING"
        ):
            continue
        last = last or s
        if "Error" in s or "EXHAUSTED" in s or "Exception" in s:
            strong = s
            break
    return {
        "label": label,
        "error": f"rc={proc.returncode}: {(strong or last or '<no stderr>')[:200]}",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--set", default="main",
        choices=["main", "wide", "wide-xover", "wide-xover2", "wide-xover3",
                 "wide-xover4", "wide-xover5", "wide-xover6",
                 "wide-xover7", "wide-xover8"],
        help="main = the llama-mini variant/autotune matrix; wide = the "
        "~700M existence-proof shapes (their own window step); "
        "wide-xover = the D=128 head-dim flash/XLA crossover matrix; "
        "wide-xover2 = the 512-block completion pass",
    )
    ap.add_argument("--timeout", type=int, default=600)
    args = ap.parse_args()

    matrix = {
        "wide": WIDE, "wide-xover": WIDE_XOVER, "wide-xover2": WIDE_XOVER2,
        "wide-xover3": WIDE_XOVER3, "wide-xover4": WIDE_XOVER4, "wide-xover5": WIDE_XOVER5, "wide-xover6": WIDE_XOVER6,
        "wide-xover7": WIDE_XOVER7, "wide-xover8": WIDE_XOVER8,
    }.get(args.set, MATRIX)
    if args.quick:
        matrix = matrix[:2]  # first two of the SELECTED set
    results = []
    for entry in matrix:
        label, extra = entry[0], entry[1]
        env_extra = entry[2] if len(entry) > 2 else None
        print(f"--- {label} ...", flush=True)
        res = run_one(label, extra, args.timeout, env_extra)
        results.append(res)
        print(json.dumps(res), flush=True)

    print("\n== llama sweep summary (sorted by mfu_analytic) ==")
    ok = [r for r in results if "mfu_analytic" in r]
    for r in sorted(ok, key=lambda r: -r["mfu_analytic"]):
        print(
            f"{r['label']:<18} mfu={r['mfu_analytic']:.4f}  "
            f"tok/s={r['tokens_per_sec_per_chip']:.0f}  "
            f"step={r['step_ms']:.1f}ms"
        )
    for r in results:
        if "error" in r:
            print(f"{r['label']:<18} ERROR: {r['error']}")


if __name__ == "__main__":
    main()
