"""Local performance baselines (BASELINE.md "Locally measurable now").

Measures, on this box:
  1. fake-backend reconcile throughput (jobs/sec to Succeeded), for the
     native (C++) and Python controller runtimes;
  2. job-startup latency on the local-process backend (create →
     Running), the driver-defined control-plane metric;
  3. training steps/sec/chip for mnist CNN and BERT-base on the default
     backend (the real chip when present; bench.py owns ResNet-50).

Usage: python benchmarks/measure.py
           [--section all|reconcile|startup|train|batching|speculative
                      |paged|multislice|fabric]
(batching and speculative are chip-minutes heavy and run only when
named explicitly; fabric is the cross-pod prefix-fabric CPU smoke —
two pools over the real FabricServer wire)
Prints one JSON object; paste results into BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_reconcile(n_jobs: int = 200) -> dict:
    from tests.testutil import new_job
    from tf_operator_tpu.api.types import JobConditionType

    out = {}
    for native in (True, False):
        from tf_operator_tpu.backend.fake import FakeCluster
        from tf_operator_tpu.backend.jobstore import JobStore
        from tf_operator_tpu.controller.controller import TPUJobController

        store = JobStore()
        backend = FakeCluster(delivery="sync")
        from tf_operator_tpu.utils.metrics import Metrics

        c = TPUJobController(store, backend, use_native=native, metrics=Metrics())
        t0 = time.perf_counter()
        for i in range(n_jobs):
            store.create(new_job(f"job-{i}", chief=1, worker=2))
        c.sync_until_quiet()
        backend.run_all("default")
        c.sync_until_quiet()
        for i in range(n_jobs):
            backend.succeed_pod("default", f"job-{i}-chief-0")
        c.sync_until_quiet()
        dt = time.perf_counter() - t0
        done = sum(
            1
            for i in range(n_jobs)
            if store.get("default", f"job-{i}").status.has_condition(
                JobConditionType.SUCCEEDED
            )
        )
        assert done == n_jobs, f"{done}/{n_jobs} succeeded"
        key = "native" if native else "python"
        out[f"reconcile_jobs_per_sec_{key}"] = round(n_jobs / dt, 1)
        spans = c.metrics.histogram("tpujob_sync_duration_seconds")
        out[f"sync_span_{key}"] = {
            "count": spans["count"],
            "mean_ms": round(spans["mean"] * 1e3, 3),
            "p99_le_ms": round(spans["p99_le"] * 1e3, 1),
        }
    return out


def bench_decision_core(iters: int = 20_000) -> dict:
    """The decision core in isolation: one batch sync_decide call
    (success evaluation + all replica plans) — native packed-int32 ABI
    vs the pure-Python twin.  This is the component SURVEY.md §2a calls
    the native hot path; the end-to-end reconcile bench above is
    executor-bound (pod/service writes, cache reads, status updates are
    Python), so the native win shows here, diluted there."""

    from tests.testutil import new_job
    from tf_operator_tpu.api.types import PodPhase, ReplicaType
    from tf_operator_tpu.backend.objects import Pod
    from tf_operator_tpu.controller import plan as planmod

    job = new_job("bench", chief=1, ps=2, worker=4)
    pods_by_type = {}
    phase_cycle = [
        PodPhase.RUNNING,
        PodPhase.PENDING,
        PodPhase.FAILED,
        PodPhase.SUCCEEDED,
    ]
    for rtype, n in ((ReplicaType.CHIEF, 1), (ReplicaType.PS, 2), (ReplicaType.WORKER, 4)):
        pods = []
        for i in range(n):
            pod = Pod()
            pod.metadata.name = f"bench-{rtype.lower_name}-{i}"
            pod.metadata.labels = {"tpujob.dist/replica-index": str(i)}
            pod.phase = phase_cycle[i % len(phase_cycle)]
            if pod.phase is PodPhase.FAILED:
                pod.exit_code = 137
            pods.append(pod)
        pods_by_type[rtype] = pods

    out = {}
    for label, use_native in (("native", True), ("python", False)):
        if use_native and planmod._native() is None:
            continue
        t0 = time.perf_counter()
        for _ in range(iters):
            planmod.sync_decide(job, pods_by_type, use_native=use_native)
        dt = time.perf_counter() - t0
        out[f"sync_decide_per_sec_{label}"] = round(iters / dt)
    if "sync_decide_per_sec_native" in out:
        out["sync_decide_native_speedup"] = round(
            out["sync_decide_per_sec_native"] / out["sync_decide_per_sec_python"], 2
        )
    return out


def bench_startup_latency(n_jobs: int = 8) -> dict:
    from tests.testutil import new_job
    from tf_operator_tpu.api.types import JobConditionType
    from tf_operator_tpu.backend.jobstore import JobStore
    from tf_operator_tpu.backend.local import LocalProcessBackend
    from tf_operator_tpu.controller.controller import TPUJobController
    from tf_operator_tpu.controller.reconciler import ReconcilerConfig

    store = JobStore()
    backend = LocalProcessBackend()
    c = TPUJobController(
        store, backend, config=ReconcilerConfig(resolver=backend.resolver)
    )
    c.run(threadiness=4)
    lat = []
    try:
        for i in range(n_jobs):
            name = f"lat-{i}"
            job = new_job(
                name, worker=1, command=[sys.executable, "-c", "import time; time.sleep(3)"]
            )
            t0 = time.perf_counter()
            store.create(job)
            while True:
                j = store.get("default", name)
                if j and j.status.has_condition(JobConditionType.RUNNING):
                    lat.append(time.perf_counter() - t0)
                    break
                if time.perf_counter() - t0 > 30:
                    raise TimeoutError(name)
                time.sleep(0.002)
            store.delete("default", name)
    finally:
        c.stop()
        backend.close()
    lat.sort()
    return {
        "startup_latency_ms_p50": round(lat[len(lat) // 2] * 1e3, 1),
        "startup_latency_ms_max": round(lat[-1] * 1e3, 1),
    }


def _apply_platform_override(jax) -> None:
    """MEASURE_PLATFORM=cpu etc., via jax.config: this box's
    sitecustomize re-pins JAX_PLATFORMS to the TPU tunnel after process
    start, so env-level selection is NOT sufficient (same reason
    bench.py and tests/conftest.py go through jax.config) — without
    this a CPU smoke run BLOCKS on the tunnel's single-client claim."""

    platform = os.environ.get("MEASURE_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)


def bench_training() -> dict:
    import jax
    import numpy as np

    _apply_platform_override(jax)

    from tf_operator_tpu.models import MnistCNN, bert_base, mlm_loss
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    out = {"train_backend": jax.default_backend()}
    n_dev = len(jax.devices())
    mesh = make_mesh({"dp": n_dev})
    r = np.random.RandomState(0)

    # mnist CNN, batch 256/chip (MEASURE_TRAIN_BATCH overrides — the
    # CPU smoke of the r7 sweeps uses a small batch; the chip default
    # stays 256)
    import jax.numpy as jnp
    import optax

    def mnist_loss(params, state, batch, rng):
        logits = state.apply_fn({"params": params}, batch["image"], train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        return loss, {}

    per_dev = int(os.environ.get("MEASURE_TRAIN_BATCH", "256"))
    out["mnist_batch_per_chip"] = per_dev
    batch = {
        "image": jnp.asarray(r.rand(per_dev * n_dev, 28, 28, 1), jnp.float32),
        "label": jnp.asarray(r.randint(0, 10, size=(per_dev * n_dev,))),
    }
    trainer = Trainer(
        MnistCNN(),
        TrainerConfig(optimizer="sgd", learning_rate=0.05),
        mesh,
        mnist_loss,
        batch,
    )
    stats = trainer.benchmark(batch, steps=30, warmup=5)
    out["mnist_steps_per_sec_per_chip"] = round(stats["steps_per_sec"] / n_dev, 1)
    out["mnist_examples_per_sec_per_chip"] = round(
        stats["examples_per_sec"] / n_dev, 1
    )

    # ---- r7 tentpole: the step-sync ledger K sweep.  The same mnist
    # trainer driven through the harness train loop at steps_per_sync
    # K in {1, 8, 32}; every run embeds its StepSyncLedger, so the
    # artifact itself carries the invariant: K=1 resolves per step
    # (sync count == steps — the legacy/debug baseline), K>1 fuses K
    # steps into one lax.scan dispatch and defers metric resolution
    # one window (``step``-phase syncs == 0 in steady state; only
    # ``window``/``final`` fetches remain).  On the tunneled chip the
    # K=32 step time is the "as fast as the hardware allows" training
    # number; on CPU the same sweep smoke-tests the accounting.
    if os.environ.get("MEASURE_TRAIN_SYNC", "1") != "0":
        from tf_operator_tpu.runtime.harness import train_loop
        from tf_operator_tpu.utils.metrics import StepSyncLedger

        sync_steps = int(os.environ.get("MEASURE_TRAIN_SYNC_STEPS", "64"))
        ks = [
            int(x)
            for x in os.environ.get("MEASURE_TRAIN_K", "1,8,32").split(",")
        ]
        sharded = trainer.shard_batch(batch)
        ksweep = {}
        for k_sync in ks:
            # warmup compiles the window program(s) outside the wall
            train_loop(
                trainer, sharded, max(k_sync, 2), steps_per_sync=k_sync,
                assert_decreasing=False, sync_ledger=StepSyncLedger(),
            )
            led = StepSyncLedger()
            t0 = time.perf_counter()
            train_loop(
                trainer, sharded, sync_steps, steps_per_sync=k_sync,
                assert_decreasing=False, sync_ledger=led,
            )
            wall = time.perf_counter() - t0
            snap = led.snapshot()
            ksweep[str(k_sync)] = {
                "steps": sync_steps,
                "wall_s": round(wall, 3),
                "steps_per_sec": round(sync_steps / wall, 1),
                "step_ms": round(wall / sync_steps * 1e3, 3),
                "steady_step_syncs": led.count("step"),
                "syncs_per_step": snap["_steps"]["syncs_per_step"],
                "ledger": snap,
            }
        out["train_sync_k_sweep"] = ksweep
        k_top = str(max(ks))
        if k_top in ksweep:
            out[f"train_k{k_top}_step_ms"] = ksweep[k_top]["step_ms"]
            out["train_steady_syncs_per_step"] = ksweep[k_top][
                "steady_step_syncs"
            ] / sync_steps

    # ---- device_prefetch depth sweep (r7): the live grain pipeline
    # at prefetch depth 1/2/4/8 against the device-resident rate above
    # — once the steady-state step is sync-free, the input pipeline is
    # the next candidate constraint, and this table shows at which
    # depth (if any) the loader stops being it.
    if os.environ.get("MEASURE_PREFETCH", "1") != "0":
        from tf_operator_tpu.data import (
            device_prefetch,
            ensure_mnist,
            make_loader,
        )

        depths = [
            int(x)
            for x in os.environ.get(
                "MEASURE_PREFETCH_DEPTHS", "1,2,4,8"
            ).split(",")
        ]
        data_dir = os.environ.get(
            "MEASURE_DATA_DIR",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "examples", "data", "mnist-measure",
            ),
        )
        ensure_mnist(data_dir, n=8192)
        psweep = {}
        for depth in depths:
            loader = make_loader(
                data_dir, per_dev * n_dev, process_id=0, process_count=1,
                num_epochs=None,
            )
            batches = device_prefetch(
                loader, trainer.batch_sharding, prefetch=depth
            )
            pstats = trainer.benchmark_stream(batches, steps=20, warmup=3)
            psweep[str(depth)] = {
                "examples_per_sec_per_chip": round(
                    pstats["examples_per_sec"] / n_dev, 1
                ),
                "step_ms": round(pstats["step_ms"], 3),
            }
        out["train_prefetch_sweep"] = psweep
        best = max(
            psweep.items(),
            key=lambda kv: kv[1]["examples_per_sec_per_chip"],
        )
        out["train_prefetch_best_depth"] = int(best[0])
        out["train_prefetch_best_examples_per_sec_per_chip"] = best[1][
            "examples_per_sec_per_chip"
        ]
        out["train_prefetch_vs_resident"] = round(
            best[1]["examples_per_sec_per_chip"]
            / out["mnist_examples_per_sec_per_chip"],
            3,
        ) if out["mnist_examples_per_sec_per_chip"] else None

    # ---- ISSUE 19 tentpole: fused train-mode BatchNorm A/B.  Stock
    # nn.BatchNorm vs norm="fused" on the same ResNet train step —
    # identical init (scope/path parity), identical batch.  Tiny runs
    # use resnet18(width=8) @ 32px so the CPU smoke commits the
    # accounting without burning the window; the chip default is the
    # profile_resnet config (resnet50 @ 224).  The full A/B with the
    # trace-category diff lives in profile_resnet --variant fusedbn;
    # this leg carries the measure.py-side cells for collect_window.
    if os.environ.get("MEASURE_RESNET_FUSEDBN", "1") != "0":
        from bench import _peak_flops as _pk, _step_flops as _sf
        from tf_operator_tpu.models import resnet18, resnet50
        from tf_operator_tpu.ops import fused_batchnorm
        from tf_operator_tpu.parallel.trainer import (
            batchnorm_cross_entropy_loss,
        )

        tiny = bool(os.environ.get("MEASURE_TRAIN_TINY"))
        fb_batch = int(
            os.environ.get("MEASURE_FUSEDBN_BATCH", "2" if tiny else "64")
        )
        fb_img = int(
            os.environ.get("MEASURE_FUSEDBN_IMAGE", "32" if tiny else "224")
        )
        fb_steps = int(
            os.environ.get("MEASURE_FUSEDBN_STEPS", "4" if tiny else "10")
        )

        def _fb_model(**kw):
            if tiny:
                return resnet18(num_classes=10, width=8, **kw)
            return resnet50(**kw)

        out["resnet_fusedbn_backend"] = jax.default_backend()
        out["resnet_fusedbn_impl"] = _fb_model(norm="fused")._resolve_norm()
        fb_batch_d = {
            "image": jnp.asarray(
                r.rand(fb_batch * n_dev, fb_img, fb_img, 3).astype(
                    np.float32
                ),
                dtype=jnp.bfloat16,
            ),
            "label": jnp.asarray(
                r.randint(0, 10 if tiny else 1000, size=(fb_batch * n_dev,))
            ),
        }
        fb_cfg = TrainerConfig(
            optimizer="sgd", learning_rate=0.1, momentum=0.9
        )
        fb_stock = Trainer(
            _fb_model(), fb_cfg, mesh, batchnorm_cross_entropy_loss,
            fb_batch_d,
        )
        fb_fused = Trainer(
            _fb_model(norm="fused"), fb_cfg, mesh,
            batchnorm_cross_entropy_loss, fb_batch_d,
        )
        loss_s = [
            float(fb_stock.train_step(fb_batch_d)["loss"]) for _ in range(3)
        ]
        loss_f = [
            float(fb_fused.train_step(fb_batch_d)["loss"]) for _ in range(3)
        ]
        out["resnet_fusedbn_loss_max_rel_err"] = float(
            np.max(
                np.abs(np.array(loss_s) - np.array(loss_f))
                / np.maximum(np.abs(np.array(loss_s)), 1e-12)
            )
        )
        fb_peak = _pk(jax.devices()[0])
        fb_sharded = fb_stock.shard_batch(fb_batch_d)
        fb_ms = {}
        for fb_tag, fb_tr in (("stock", fb_stock), ("fused", fb_fused)):
            fb_flops = _sf(fb_tr, fb_sharded)
            fb_stats = fb_tr.benchmark(fb_batch_d, steps=fb_steps, warmup=2)
            fb_ms[fb_tag] = fb_stats["step_ms"]
            out[f"resnet_fusedbn_step_ms_{fb_tag}"] = round(
                fb_stats["step_ms"], 2
            )
            if fb_flops:
                out[f"resnet_fusedbn_mfu_{fb_tag}"] = round(
                    fb_flops * fb_stats["steps_per_sec"] / fb_peak, 4
                )
        out["resnet_fusedbn_step_wall_ratio"] = (
            round(fb_ms["stock"] / fb_ms["fused"], 3)
            if fb_ms["fused"]
            else None
        )
        # interpret-numerics probe: the real kernel body through the
        # pallas interpreter, fwd + grad vs the xla reference — always
        # committed so even a CPU artifact carries kernel evidence
        fb_x = jnp.asarray(
            np.random.RandomState(1).rand(4, 9, 9, 24), jnp.float32
        )
        fb_g = jnp.full((24,), 1.3, jnp.float32)
        fb_b = jnp.full((24,), 0.2, jnp.float32)

        def _fb_probe(impl):
            def f(x):
                y, _, _ = fused_batchnorm(
                    x, fb_g, fb_b, relu=True, impl=impl
                )
                return jnp.sum(y * y)

            y, _, _ = fused_batchnorm(fb_x, fb_g, fb_b, relu=True, impl=impl)
            return y, jax.grad(f)(fb_x)

        fb_yr, fb_dr = _fb_probe("xla")
        fb_yi, fb_di = _fb_probe("pallas-interpret")
        out["resnet_fusedbn_interpret_fwd_err"] = float(
            jnp.max(jnp.abs(fb_yi - fb_yr))
        )
        out["resnet_fusedbn_interpret_grad_err"] = float(
            jnp.max(jnp.abs(fb_di - fb_dr))
        )

    if os.environ.get("MEASURE_TRAIN_TINY"):
        # CPU smoke of the mnist + K-sweep + prefetch accounting only:
        # BERT-base/llama-mini steps are chip work (a CPU run would
        # burn the window budget compiling them for meaningless rates)
        return out

    # BERT-base MLM, seq 128, batch 32/chip
    from examples.bert_pretrain import synthetic_mlm_batch

    mlm = {
        k: jnp.asarray(v)
        for k, v in synthetic_mlm_batch(0, 32 * n_dev, 128, 30522).items()
    }
    bert_trainer = Trainer(
        bert_base(max_len=128),
        TrainerConfig(learning_rate=1e-4),
        make_mesh({"fsdp": n_dev}),
        mlm_loss,
        mlm,
        init_args=(mlm["input_ids"],),
        shardings="logical",
    )
    stats = bert_trainer.benchmark(mlm, steps=20, warmup=5)
    out["bert_base_steps_per_sec_per_chip"] = round(
        stats["steps_per_sec"] / n_dev, 2
    )
    out["bert_base_examples_per_sec_per_chip"] = round(
        stats["examples_per_sec"] / n_dev, 1
    )
    # BERT-base MFU (VERDICT r5 next #7): the second named north-star
    # model finally gets an efficiency number.  Analytic accounting =
    # 6 flops/matmul-param + full (bidirectional) attention — the
    # encoder variant of the llama formula, benchmarks/FLOPS.md "BERT";
    # mfu_xla from XLA cost analysis of the compiled step for the
    # cross-check (they should agree within the FLOPS.md error bars).
    from bench import (
        _peak_flops,
        _step_flops,
        encoder_analytic_flops_per_token,
        matmul_param_count,
    )

    bert_seq = 128
    n_matmul = matmul_param_count(bert_trainer.state.params)
    flops_tok = encoder_analytic_flops_per_token(
        bert_trainer.model.cfg, n_matmul, bert_seq
    )
    peak = _peak_flops(jax.devices()[0])
    bert_tps = stats["steps_per_sec"] * 32 * bert_seq  # tokens/s/chip
    out["bert_base_mfu_analytic"] = round(bert_tps * flops_tok / peak, 4)
    flops_xla = _step_flops(bert_trainer, bert_trainer.shard_batch(mlm))
    if flops_xla:
        out["bert_base_mfu_xla"] = round(
            flops_xla * stats["steps_per_sec"] / peak, 4
        )

    # llama-mini (~120M: RoPE + GQA 16q:4kv + SwiGLU), seq 1024, bf16 —
    # exercises the flash fwd+bwd kernels at a realistic long-ish seq.
    # ONE config definition (bench.llama_mini_config) shared with
    # bench.py and benchmarks/profile_llama.py
    from bench import llama_mini_config
    from tf_operator_tpu.models import LlamaLM, llama_loss

    seq, per_chip = 1024, 8
    cfg = llama_mini_config(seq)
    lm = {"input_ids": jnp.asarray(r.randint(0, 32000, size=(per_chip * n_dev, seq)), jnp.int32)}
    lm_trainer = Trainer(
        LlamaLM(cfg),
        TrainerConfig(learning_rate=1e-3),
        make_mesh({"fsdp": n_dev}),
        llama_loss,
        lm,
        init_args=(lm["input_ids"],),
        shardings="logical",
    )
    stats = lm_trainer.benchmark(lm, steps=10, warmup=3)
    out["llama_mini_tokens_per_sec_per_chip"] = round(
        stats["steps_per_sec"] * per_chip * seq, 1
    )

    # serving-side: greedy decode throughput with the live sharded
    # params (jitted once; second call is the steady-state number)
    prompt = lm["input_ids"][:8, :16]
    n_new = 64
    np.asarray(lm_trainer.generate(prompt, max_new_tokens=n_new))  # compile
    t0 = time.perf_counter()
    np.asarray(lm_trainer.generate(prompt, max_new_tokens=n_new))
    dt = time.perf_counter() - t0
    out["llama_mini_decode_tokens_per_sec"] = round(8 * n_new / dt, 1)
    return out


def bench_multislice() -> dict:
    """ISSUE 14: flat vs hierarchical gradient sync on a slice-aware
    mesh — the training twin of the paged serving legs.

    Builds a 2-slice simulated mesh (``dp`` across slices/DCN, ``fsdp``
    within a slice/ICI — ``MEASURE_MULTISLICE_SLICES`` overrides), runs
    the SAME mnist trainer with ``grad_sync="flat"`` and
    ``"hierarchical"``, and records: the plan's byte ledger (the
    acceptance number: hierarchical cross-slice bytes/step ≤
    1/intra_slice_size + ε of flat), slope-timed step walls for both
    programs, the loss-trajectory allclose probe, and the
    ``train_dcn_sync_seconds{fabric=}`` phase probe
    (collectives.measure_sync_seconds).

    On this box the section runs as a CPU smoke (8 virtual devices —
    both fabrics are host RAM, so the byte ledger and program structure
    are the signal and the wall cells are smoke-grade); the real-DCN
    walls ride the queued chip window like the paged-chip legs."""

    import jax

    _apply_platform_override(jax)

    import numpy as np

    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.parallel import collectives
    from tf_operator_tpu.parallel.mesh import mesh_axis_links
    from tf_operator_tpu.utils.metrics import Metrics

    out = {"multislice_backend": jax.default_backend()}
    n_dev = len(jax.devices())
    slices = int(os.environ.get("MEASURE_MULTISLICE_SLICES", "2"))
    if n_dev < 2 * slices:
        out["multislice_error"] = (
            f"need >= {2 * slices} devices for a {slices}-slice mesh with "
            f"intra-slice width, have {n_dev}"
        )
        return out
    mesh = make_mesh({"dp": slices, "fsdp": -1}, slices=slices)
    links = mesh_axis_links(mesh)
    out["multislice_slices"] = slices
    out["multislice_mesh"] = {
        ax: int(s) for ax, s in mesh.shape.items() if s > 1
    }
    out["multislice_axis_fabric"] = {
        ax: links[ax] for ax, s in mesh.shape.items() if s > 1
    }

    import jax.numpy as jnp
    import optax

    def mnist_loss(params, state, batch, rng):
        logits = state.apply_fn({"params": params}, batch["image"], train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        return loss, {}

    from tf_operator_tpu.models import MnistCNN

    per_dev = int(os.environ.get("MEASURE_MULTISLICE_BATCH", "32"))
    r = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(r.rand(per_dev * n_dev, 28, 28, 1), jnp.float32),
        "label": jnp.asarray(r.randint(0, 10, size=(per_dev * n_dev,))),
    }
    steps = int(os.environ.get("MEASURE_MULTISLICE_STEPS", "20"))

    trainers = {}
    for mode in ("flat", "hierarchical"):
        trainers[mode] = Trainer(
            MnistCNN(),
            TrainerConfig(optimizer="sgd", learning_rate=0.05),
            mesh,
            mnist_loss,
            batch,
            grad_sync=mode,
        )
    plan = trainers["hierarchical"].grad_sync_plan
    led = plan.ledger()
    out["multislice_intra_slice_size"] = led["intra_slice_size"]
    out["multislice_flat_dcn_bytes_per_step"] = led["flat_dcn_bytes_per_step"]
    out["multislice_flat_mesh_dcn_bytes_per_step"] = led[
        "flat_mesh_dcn_bytes_per_step"
    ]
    out["multislice_hier_dcn_bytes_per_step"] = led["hier_dcn_bytes_per_step"]
    # two baselines, two ratios (collectives.py "Byte accounting
    # convention"): vs the topology-BLIND pre-slice-aware mesh (the
    # acceptance number) and vs the same-mesh flat program (what the
    # measured walls A/B — near 1.0 on fsdp-heavy models, where the
    # slice-aware layout + ZeRO sharding already won the traffic)
    out["multislice_dcn_bytes_ratio"] = led["dcn_bytes_ratio"]
    out["multislice_dcn_bytes_ratio_vs_flat_mesh"] = led[
        "dcn_bytes_ratio_vs_flat_mesh"
    ]
    out["multislice_dcn_collectives_per_step"] = led[
        "dcn_collectives_per_step"
    ]
    out["multislice_grad_sync_ledger"] = led

    # numerics probe: the two programs track each other (deterministic
    # loss, bf16 schedule drift bounds the gap)
    max_err = 0.0
    for _ in range(5):
        lh = float(
            trainers["hierarchical"].train_step(
                trainers["hierarchical"].shard_batch(batch)
            )["loss"]
        )
        lf = float(
            trainers["flat"].train_step(trainers["flat"].shard_batch(batch))[
                "loss"
            ]
        )
        max_err = max(max_err, abs(lh - lf))
    out["multislice_allclose_max_loss_err"] = round(max_err, 6)

    for mode in ("flat", "hierarchical"):
        stats = trainers[mode].benchmark(batch, steps=steps, warmup=3)
        out[f"multislice_{mode}_step_ms"] = round(stats["step_ms"], 3)
    out["multislice_step_wall_ratio"] = round(
        out["multislice_hierarchical_step_ms"]
        / out["multislice_flat_step_ms"],
        3,
    )

    probe_metrics = Metrics()
    probe = collectives.measure_sync_seconds(
        mesh, nbytes=4 << 20, metrics=probe_metrics
    )
    out["multislice_sync_probe"] = {
        k: (round(v, 6) if isinstance(v, float) else v)
        for k, v in probe.items()
    }
    return out


def bench_batching() -> dict:
    """Serving throughput under concurrency: aggregate decode tokens/s
    for 8 staggered requests through the continuous-batching pool
    (models/batching.py) vs the same 8 served back-to-back, one
    ChunkedServingDecoder call each (today's one-request-at-a-time
    server).  The pool's step cost is ~constant in occupancy, so its
    win should approach min(8, slots)× on a weight-bandwidth-bound
    chip decode.

    r6 (VERDICT r5 next #5): the pool runs at every steps_per_sync K
    in MEASURE_BATCHING_K (the crossover sweep — more tokens per host
    round trip amortize the tunnel RTT), and every run embeds its
    DispatchLedger (per-phase dispatch counts x measured per-dispatch
    wall), so the artifact itself proves where the wall went: with
    single-dispatch admission the pool's dispatch count is
    n_req + ceil-ish(n_new/K) syncs vs the sequential baseline's
    ~(chunks+1) x n_req — the "tunnel overhead" claim as arithmetic,
    not prose."""

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import llama_mini_config
    from tf_operator_tpu.models import LlamaLM
    from tf_operator_tpu.models.batching import ContinuousBatchingDecoder
    from tf_operator_tpu.models.decode import ChunkedServingDecoder

    _apply_platform_override(jax)
    out = {"batching_backend": jax.default_backend()}
    seq = int(os.environ.get("MEASURE_BATCHING_MAXLEN", "512"))
    n_req = 8
    # keep the budget a power of two: ChunkedServingDecoder rounds
    # budgets UP to the next power of two, so e.g. 96 would make the
    # sequential baseline run 128 compiled steps while only 96 are
    # credited — inflating the pool's "speedup" by padding, not merit
    n_new = int(os.environ.get("MEASURE_BATCHING_NEW", "64"))
    if os.environ.get("MEASURE_BATCHING_TINY"):  # CPU smoke: tiny model
        from tf_operator_tpu.models import llama_tiny

        model = llama_tiny(vocab_size=256, max_len=seq)
    else:
        model = LlamaLM(llama_mini_config(seq))
    vocab = model.cfg.vocab_size
    r = np.random.RandomState(0)
    init_ids = jnp.asarray(r.randint(0, vocab, size=(1, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), init_ids)["params"]
    prompts = [
        r.randint(0, vocab, size=(int(l),)).astype(np.int32)
        for l in r.randint(8, 48, size=(n_req,))
    ]
    total = n_req * n_new
    out["batching_new_tokens"] = n_new

    seq_dec = ChunkedServingDecoder(model, params)

    def sequential_run():
        return [
            np.asarray(seq_dec.generate(jnp.asarray(p[None, :]), n_new))
            for p in prompts
        ]

    sequential_run()  # compile
    seq_dec.ledger.reset()  # count the steady-state run only
    t0 = time.perf_counter()
    sequential_run()
    dt_seq = time.perf_counter() - t0
    out["batching_sequential_tokens_per_sec"] = round(total / dt_seq, 1)
    out["batching_sequential_dispatches"] = seq_dec.ledger.snapshot()

    # K sweep: one pool per steps_per_sync value (the step program is
    # compiled per K).  Decoders are reused across warmup + timed runs
    # so retrace/compile never lands in the timed window.
    ks = [
        int(x)
        for x in os.environ.get("MEASURE_BATCHING_K", "8,32,128").split(",")
    ]
    sweep = {}
    best = None
    for k_sync in ks:
        pool_dec = ContinuousBatchingDecoder(
            model, params, slots=8, steps_per_sync=k_sync
        )

        def pool_run():
            rids = []
            for p in prompts:
                rids.append(pool_dec.submit(p, max_new_tokens=n_new))
                pool_dec.step()  # staggered arrivals: pool never drains
            pool_dec.run()
            return [pool_dec.result(rid) for rid in rids]

        pool_run()  # compile
        pool_dec.ledger.reset()
        t0 = time.perf_counter()
        pool_run()
        dt_pool = time.perf_counter() - t0
        row = {
            "tokens_per_sec": round(total / dt_pool, 1),
            "wall_s": round(dt_pool, 3),
            "speedup_vs_sequential": round(dt_seq / dt_pool, 2),
            "dispatches": pool_dec.ledger.snapshot(),
        }
        sweep[str(k_sync)] = row
        if best is None or row["tokens_per_sec"] > best[1]["tokens_per_sec"]:
            best = (k_sync, row)
    out["batching_k_sweep"] = sweep
    k_best, row_best = best
    out["batching_steps_per_sync"] = k_best
    out["batching_pool_tokens_per_sec"] = row_best["tokens_per_sec"]
    out["batching_speedup"] = row_best["speedup_vs_sequential"]
    out["batching_dispatches"] = row_best["dispatches"]
    adm = row_best["dispatches"].get("admission", {}).get("count", 0)
    out["batching_admission_dispatches_per_request"] = round(
        adm / n_req, 2
    )
    return out


def bench_paged() -> dict:
    """Paged KV-cache serving vs the slot-based pool at the SAME HBM
    arena budget (ISSUE 8 acceptance): replay a bursty mixed-length
    trace — 60% of requests share a multi-block system prompt, budgets
    and tail lengths drawn from a spread — through both pools and
    record sustained tokens/sec, p99 TTFT, max concurrent requests
    admitted, and the prefix-cache hit rate.

    Equal-budget framing: the slot baseline runs S seats, each pinning
    a full max_len KV cache (S × max_len/block_size blocks of HBM);
    the paged pool gets EXACTLY that many arena blocks but 4×S seats —
    admission is gated on blocks free, so mixed-length traffic packs
    strictly more concurrent requests into the same memory.  Both runs
    embed their DispatchLedger; the paged run's admission entries
    carry prefix_tokens, so "full hit = zero prefill work" is visible
    in the artifact, not just in the test pin.

    Leg D (ISSUE 10) measures decode BANDWIDTH: steady-state decode
    windows only, gather-emulation vs the fused Pallas paged-attention
    read, across context lengths x seat mixes
    (``paged_kernel_{gather,fused}_tokens_per_sec_c{CTX}_s{SEATS}`` +
    ``paged_kernel_read_speedup_*``).  The fused numbers exist only on
    the TPU backend; the CPU smoke records an interpreter-mode
    numerics probe instead.

    CPU smoke: MEASURE_PAGED_TINY=1 swaps in llama_tiny (the
    tpu_window step runs this so the accounting is exercised every
    window without chip minutes)."""

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models.batching import (
        ContinuousBatchingDecoder,
        PagedContinuousBatchingDecoder,
    )
    from tf_operator_tpu.utils.metrics import SLO_BUCKETS, Metrics

    _apply_platform_override(jax)
    out = {"paged_backend": jax.default_backend()}
    seq = int(os.environ.get("MEASURE_PAGED_MAXLEN", "512"))
    block = int(os.environ.get("MEASURE_PAGED_BLOCK", "16"))
    slots_base = int(os.environ.get("MEASURE_PAGED_SLOTS", "4"))
    n_req = int(os.environ.get("MEASURE_PAGED_REQUESTS", "24"))
    k_sync = int(os.environ.get("MEASURE_PAGED_K", "32"))
    burst = int(os.environ.get("MEASURE_PAGED_BURST", "8"))
    if os.environ.get("MEASURE_PAGED_TINY"):
        from tf_operator_tpu.models import llama_tiny

        model = llama_tiny(vocab_size=256, max_len=seq)
    else:
        from bench import llama_mini_config
        from tf_operator_tpu.models import LlamaLM

        model = LlamaLM(llama_mini_config(seq))
    vocab = model.cfg.vocab_size
    r = np.random.RandomState(0)
    init_ids = jnp.asarray(r.randint(0, vocab, size=(1, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), init_ids)["params"]

    # the bursty mixed-length trace: shared system prompt (2 full
    # blocks) on 60% of requests, tails 4..seq/4, budgets 8..64
    sys_prefix = r.randint(0, vocab, size=(2 * block,)).astype(np.int32)
    trace = []
    for _ in range(n_req):
        tail = r.randint(
            0, vocab, size=(int(r.randint(4, max(5, seq // 4))),)
        ).astype(np.int32)
        prompt = (
            np.concatenate([sys_prefix, tail]) if r.rand() < 0.6 else tail
        )
        budget = int(r.choice([8, 16, 32, 64]))
        if prompt.size + budget > seq:
            prompt = prompt[: seq - budget]
        trace.append((prompt, budget))
    total_new = sum(b for _, b in trace)
    out["paged_trace_requests"] = n_req
    out["paged_trace_new_tokens"] = total_new
    out["paged_arena_blocks"] = slots_base * (seq // block)

    def replay(make_pool):
        """Burst-submit the trace, drive to drain; returns
        (wall, max_concurrent, pool, metrics)."""

        metrics = Metrics()
        metrics.set_buckets("serve_ttft_seconds", SLO_BUCKETS)
        pool = make_pool(metrics)
        # warmup TWICE: the cold pass compiles the miss-path width
        # classes; the second pass runs against the now-published
        # prefix blocks and compiles the REMAINDER width classes the
        # hit path admits at (without it those compiles land in the
        # timed window and masquerade as paging overhead)
        for _ in range(2):
            for p, budget in trace:
                pool.submit(p, budget)
            pool.run()
        pool.ledger.reset()
        metrics2 = Metrics()
        metrics2.set_buckets("serve_ttft_seconds", SLO_BUCKETS)
        pool.metrics = metrics2
        # steady-state hit accounting: the warmup published the shared
        # prefix blocks (deliberate — the timed replay models a warm
        # server); count only the timed run's hits/misses
        prefix = getattr(pool, "prefix", None)
        hits0 = (prefix.hits, prefix.misses) if prefix else (0, 0)
        pool._hit_base = hits0
        max_conc = 0
        t0 = time.perf_counter()
        i = 0
        while True:
            for p, budget in trace[i : i + burst]:
                pool.submit(p, budget)
            i += burst
            active = pool.step()
            with pool._lock:
                max_conc = max(max_conc, len(pool._active))
            if i >= len(trace) and active == 0:
                with pool._lock:
                    if not pool._queue:
                        break
        wall = time.perf_counter() - t0
        return wall, max_conc, pool, metrics2

    # leg A — slot baseline: S seats, each pinning a contiguous
    # max_len cache (the r6 pool)
    wall_s, conc_s, slot_pool, m_s = replay(
        lambda m: ContinuousBatchingDecoder(
            model, params, slots=slots_base, steps_per_sync=k_sync,
            metrics=m, model_label="paged-bench",
        )
    )
    out["paged_slot_baseline_tokens_per_sec"] = round(total_new / wall_s, 1)
    out["paged_slot_baseline_concurrent"] = conc_s
    out["paged_slot_baseline_p99_ttft_s"] = m_s.histogram(
        "serve_ttft_seconds", model="paged-bench", mode="pool",
        tier="batch",
    ).get("p99_le")
    out["paged_slot_baseline_dispatches"] = slot_pool.ledger.snapshot()

    # leg B — paging overhead isolated: SAME seats, SAME HBM, only
    # the cache layout differs.  wall_B/wall_A is the pure cost of
    # the block-table gather/scatter round trip per program
    wall_e, _, eq_pool, m_e = replay(
        lambda m: PagedContinuousBatchingDecoder(
            model, params, slots=slots_base, steps_per_sync=k_sync,
            kv_blocks=slots_base * (seq // block), kv_block_size=block,
            metrics=m, model_label="paged-bench",
        )
    )
    out["paged_equal_slots_tokens_per_sec"] = round(total_new / wall_e, 1)
    out["paged_equal_slots_p99_ttft_s"] = m_e.histogram(
        "serve_ttft_seconds", model="paged-bench", mode="pool",
        tier="batch",
    ).get("p99_le")
    # < 1.0 = paged is FASTER at equal resources (prefix-cache hits
    # skip prefill work and outweigh the gather/scatter layout cost)
    out["paged_equal_slots_wall_ratio"] = round(wall_e / wall_s, 2)

    # leg C — the capacity claim: the SAME block budget, 4x the
    # seats.  Admission is block-gated, so mixed-length traffic packs
    # more concurrent requests into the same HBM; tokens/sec here is
    # the chip-relevant number (decode is weight-bandwidth-bound at
    # small batch — more seats amortize the weight reads).  On the
    # CPU smoke the extra seats COST compute instead, so judge this
    # leg's tokens/sec only from an on-chip window.
    wall_p, conc_p, paged_pool, m_p = replay(
        lambda m: PagedContinuousBatchingDecoder(
            model, params, slots=4 * slots_base, steps_per_sync=k_sync,
            kv_blocks=slots_base * (seq // block), kv_block_size=block,
            metrics=m, model_label="paged-bench",
        )
    )
    out["paged_tokens_per_sec"] = round(total_new / wall_p, 1)
    out["paged_concurrent_admitted"] = conc_p
    out["paged_p99_ttft_s"] = m_p.histogram(
        "serve_ttft_seconds", model="paged-bench", mode="pool",
        tier="batch",
    ).get("p99_le")
    out["paged_dispatches"] = paged_pool.ledger.snapshot()
    h0, m0 = paged_pool._hit_base
    hits = paged_pool.prefix.hits - h0
    misses = paged_pool.prefix.misses - m0
    out["paged_prefix_hit_rate"] = round(hits / max(1, hits + misses), 3)
    out["paged_speedup_vs_slot"] = round(wall_s / wall_p, 2)
    out["paged_capacity_ratio"] = round(conc_p / max(1, conc_s), 2)

    # leg D — decode BANDWIDTH (ISSUE 10): steady-state decode windows
    # only (admission excluded), gather-emulation vs the fused Pallas
    # paged-attention read, at several context lengths x seat mixes.
    # The emulation materializes the contiguous view per program
    # (~2x KV traffic); the fused step reads the arena once — the
    # ratio is the on-chip number that gates the paged pool's
    # at-capacity tokens/sec.  On CPU the compiled kernel cannot run:
    # the fused leg is skipped (recorded as such) and a tiny
    # interpreter-mode probe pins the kernel path's numerics instead,
    # so every CPU-smoke window still proves the kernel alive.
    from tf_operator_tpu.models.kv_blocks import blocks_for

    windows = int(os.environ.get("MEASURE_PAGED_WINDOWS", "8"))
    ctx_raw = os.environ.get("MEASURE_PAGED_CTX", "")
    # +2 windows of budget: admission yields 1 token, the untimed
    # warmup step K more, the timed region windows*K — seats must NOT
    # hit their budget inside the timed region, or the one-time retire
    # jit compile + dispatch lands in the measured wall and deflates
    # the bandwidth numbers
    budget_d = (windows + 2) * k_sync
    if ctx_raw:
        ctxs = [int(c) for c in ctx_raw.split(",") if c.strip()]
    else:
        ctxs = sorted({max(block, seq // 8), max(2 * block, seq // 2)})
    # a ctx must leave room for the decode budget (prompt + budget <=
    # max_len is the pool's submit contract) — when the window/K
    # config leaves no valid ctx, SKIP leg D with a recorded reason
    # instead of crashing the section and losing legs A-C's artifact;
    # a PARTIAL drop is recorded too (no silent caps — a missing
    # long-context cell must be visible in the artifact)
    dropped = [c for c in ctxs if c + budget_d > seq]
    ctxs = [c for c in ctxs if c + budget_d <= seq]
    if dropped and ctxs:
        out["paged_kernel_ctx_dropped"] = (
            f"{dropped}: ctx + decode budget {budget_d} exceeds "
            f"max_len={seq}"
        )
    mixes = [slots_base, 4 * slots_base]
    on_tpu = jax.default_backend() == "tpu"
    out["paged_kernel_backend"] = jax.default_backend()
    out["paged_kernel_windows"] = windows

    def decode_leg(kernel_mode: str, ctx: int, seats: int):
        """tokens/sec over ``windows`` steady-state decode windows at
        full occupancy (seats x ctx context, K tokens per window)."""

        rd = np.random.RandomState(1234 + ctx + seats)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=seats, steps_per_sync=k_sync,
            kv_blocks=seats * blocks_for(ctx + budget_d, block),
            kv_block_size=block, paged_kernel=kernel_mode,
        )
        for _ in range(seats):
            pool.submit(
                rd.randint(0, vocab, size=(ctx,)).astype(np.int32),
                budget_d,
            )
        pool.step()  # admissions + first window (compiles)
        t0 = time.perf_counter()
        for _ in range(windows):
            pool.step()
        wall = time.perf_counter() - t0
        return round(seats * k_sync * windows / wall, 1)

    if not ctxs:
        out["paged_kernel_decode_leg"] = (
            f"skipped: decode budget {budget_d} (windows={windows} x "
            f"K={k_sync}) leaves no valid context length under "
            f"max_len={seq} — lower MEASURE_PAGED_WINDOWS/"
            "MEASURE_PAGED_K or raise MEASURE_PAGED_MAXLEN"
        )
    for ctx in ctxs:
        for seats in mixes:
            gather = decode_leg("off", ctx, seats)
            out[f"paged_kernel_gather_tokens_per_sec_c{ctx}_s{seats}"] = gather
            if on_tpu:
                fused = decode_leg("on", ctx, seats)
                out[f"paged_kernel_fused_tokens_per_sec_c{ctx}_s{seats}"] = fused
                out[f"paged_kernel_read_speedup_c{ctx}_s{seats}"] = round(
                    fused / max(1e-9, gather), 2
                )
    if not on_tpu:
        # interpreter probe: the REAL kernel, tiny shape — numerics
        # pinned against the gather reference in every smoke window
        from tf_operator_tpu.ops.paged_attention import paged_attention

        rp = np.random.RandomState(7)
        ka = jnp.asarray(rp.randn(5, 2, 8, 32), jnp.float32)
        va = jnp.asarray(rp.randn(5, 2, 8, 32), jnp.float32)
        qp = jnp.asarray(rp.randn(2, 4, 32), jnp.float32)
        tbl = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
        ln = jnp.asarray([9, 15], jnp.int32)
        got = paged_attention(qp, ka, va, tbl, ln, impl="pallas-interpret")
        ref = paged_attention(qp, ka, va, tbl, ln, impl="xla")
        out["paged_kernel_interpret_max_err"] = float(
            jnp.max(jnp.abs(got - ref))
        )
        out["paged_kernel_fused_leg"] = (
            "skipped: compiled kernel needs the TPU backend "
            "(interpret probe above pins the kernel path)"
        )

    # leg E — two-tier bursty oversubscription (ISSUE 12): an
    # interactive minority + batch majority whose WORST-CASE block
    # demand runs ~1.5x the arena.  Budget-on-demand admission packs
    # strictly more concurrent seats than PR 8's worst-case
    # reservation at the same arena (paged_lazy_capacity_* vs
    # paged_worstcase_capacity_concurrent), and the preemption/swap
    # machinery keeps interactive p99 TTFT honest while batch degrades
    # gracefully — per-tier quantiles, preemption and swap-byte
    # counts all land in the artifact.
    seats_e = 4 * slots_base
    arena_e = slots_base * (seq // block)
    rt = np.random.RandomState(42)
    trace_e = []
    demand = 0
    target_demand = int(1.5 * arena_e)
    while demand < target_demand:
        p_len = int(rt.randint(4, max(5, seq // 8)))
        budget = int(rt.choice([32, 48, 64]))
        if p_len + budget > seq:
            budget = seq - p_len
        tier = "interactive" if rt.rand() < 0.25 else "batch"
        prompt = rt.randint(0, vocab, size=(p_len,)).astype(np.int32)
        trace_e.append((prompt, budget, tier))
        demand += blocks_for(p_len + budget, block)
    out["paged_tier_trace_requests"] = len(trace_e)
    out["paged_tier_trace_demand_ratio"] = round(demand / arena_e, 2)
    out["paged_tier_interactive_share"] = round(
        sum(1 for _, _, t in trace_e if t == "interactive")
        / len(trace_e), 2,
    )

    def replay_tiered(reserve: str):
        metrics = Metrics()
        metrics.set_buckets("serve_ttft_seconds", SLO_BUCKETS)
        metrics.set_buckets("serve_queue_wait_seconds", SLO_BUCKETS)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=seats_e, steps_per_sync=k_sync,
            kv_blocks=arena_e, kv_block_size=block, metrics=metrics,
            model_label="paged-bench", reserve=reserve,
            age_boost_seconds=2.0,
        )
        # warmup compiles the admission width classes off the clock
        for p, budget, tier in trace_e[: max(4, burst)]:
            pool.submit(p, budget, tier=tier)
        pool.run()
        pool.ledger.reset()
        metrics2 = Metrics()
        metrics2.set_buckets("serve_ttft_seconds", SLO_BUCKETS)
        metrics2.set_buckets("serve_queue_wait_seconds", SLO_BUCKETS)
        pool.metrics = metrics2
        pool.preemptions = 0
        max_conc = 0
        new_toks = sum(b for _, b, _ in trace_e)
        t0 = time.perf_counter()
        i = 0
        while True:
            for p, budget, tier in trace_e[i : i + burst]:
                pool.submit(p, budget, tier=tier)
            i += burst
            active = pool.step()
            with pool._lock:
                max_conc = max(max_conc, len(pool._active))
            if i >= len(trace_e) and active == 0:
                with pool._lock:
                    if not pool._queue:
                        break
        wall = time.perf_counter() - t0
        pool.alloc.check()
        return wall, max_conc, pool, metrics2, new_toks

    wall_lz, conc_lz, pool_lz, m_lz, toks_e = replay_tiered("lazy")
    out["paged_lazy_capacity_concurrent"] = conc_lz
    out["paged_lazy_tokens_per_sec"] = round(toks_e / wall_lz, 1)
    for tier in ("interactive", "batch"):
        out[f"paged_tier_{tier}_p99_ttft_s"] = m_lz.histogram(
            "serve_ttft_seconds", model="paged-bench", mode="pool",
            tier=tier,
        ).get("p99_le")
        out[f"paged_tier_{tier}_p99_queue_wait_s"] = m_lz.histogram(
            "serve_queue_wait_seconds", model="paged-bench", mode="pool",
            tier=tier,
        ).get("p99_le")
    out["paged_preemptions"] = pool_lz.preemptions
    swap = pool_lz.swap.snapshot()
    out["paged_swap_out_bytes"] = swap["bytes_out_total"]
    out["paged_swap_in_bytes"] = swap["bytes_in_total"]
    out["paged_tier_dispatches"] = pool_lz.ledger.snapshot()

    wall_wc, conc_wc, pool_wc, _, _ = replay_tiered("worst-case")
    out["paged_worstcase_capacity_concurrent"] = conc_wc
    out["paged_worstcase_tokens_per_sec"] = round(toks_e / wall_wc, 1)
    out["paged_lazy_capacity_ratio"] = round(conc_lz / max(1, conc_wc), 2)
    # worst-case admissions cover the whole budget so the GROW path
    # never preempts, but the tier policy still may (an interactive
    # admission evicting a batch seat) — record, don't assume zero
    out["paged_worstcase_preemptions"] = pool_wc.preemptions

    # leg F — DISAGGREGATED serving (ISSUE 13): at the SAME total
    # arena and seat count, a prefill/decode phase-split fleet (1
    # prefill + 1 decode replica over the prefix-cache fabric) vs the
    # uniform 2-replica pool, under a mixed long-prompt/short-decode
    # bursty trace where 60% of the long prompts share a multi-block
    # system prefix.  The split removes prefill head-of-line blocking
    # from the decode loop (a long chunked prefill admission no longer
    # stalls a replica's decode batch) and the fabric shares prefix
    # work ACROSS replicas (a uniform fleet's per-replica caches
    # cannot).  p99 TTFT is computed EXACTLY from the per-request
    # autopsies (no histogram bucket rounding).  CPU-smoke caveats:
    # both fleets' replicas share this box's cores, so tokens/sec
    # mainly proves accounting — the p99 TTFT comparison is the
    # chip-transferable number (HOL blocking is scheduling, not
    # compute).  MEASURE_PAGED_DISAGG=0 skips the leg.
    if os.environ.get("MEASURE_PAGED_DISAGG", "1") != "0":
        out.update(_bench_disaggregated(
            model, params, vocab, seq=seq, block=block,
            slots_base=slots_base, k_sync=k_sync, burst=burst,
        ))
    return out


def _bench_disaggregated(model, params, vocab, *, seq, block, slots_base,
                         k_sync, burst) -> dict:
    """bench_paged leg F (see its comment): uniform vs phase-split
    fleet at equal total arena; returns paged_uniform_* /
    paged_disagg_* keys."""

    import threading

    import numpy as np

    from tf_operator_tpu.models.batching import (
        PagedContinuousBatchingDecoder,
    )
    from tf_operator_tpu.models.pool_router import PoolRouter
    from tf_operator_tpu.models.prefix_cache import PrefixFabric
    from tf_operator_tpu.utils.metrics import Metrics

    out = {}
    arena_rep = slots_base * (seq // block)  # per replica; total = 2x
    n_req = int(os.environ.get("MEASURE_PAGED_DISAGG_REQUESTS", "24"))
    # one SHAPE plan, two content realizations: the warmup replays the
    # same prompt lengths/budgets with DIFFERENT tokens, so every
    # admission width class compiles off the clock while the timed
    # run's prompt content stays COLD — both fleets really pay the
    # long prefills the leg exists to compare (a content-identical
    # warmup would pre-publish the prefixes into every cache and
    # erase the effect)
    shape_r = np.random.RandomState(99)
    long_p = min(seq // 2, seq - 24)
    plan = []  # (is_long, tail_len, budget)
    for _ in range(n_req):
        if shape_r.rand() < 0.35:
            plan.append((True, 8, 8))
        else:
            plan.append((False, int(shape_r.randint(4, 12)),
                         int(shape_r.choice([8, 16]))))

    def make_trace(seed):
        r = np.random.RandomState(seed)
        sys_prefix = r.randint(
            0, vocab, size=(long_p - 8,)
        ).astype(np.int32)
        trace = []
        for is_long, tail_len, budget in plan:
            tail = r.randint(0, vocab, size=(tail_len,)).astype(np.int32)
            prompt = (
                np.concatenate([sys_prefix, tail]) if is_long else tail
            )
            trace.append((prompt, budget))
        return trace

    warm_trace, trace = make_trace(77), make_trace(1234)
    total_new = sum(b for _, b in trace)
    out["paged_disagg_trace_requests"] = n_req
    out["paged_disagg_long_share"] = round(
        sum(1 for is_long, _, _ in plan if is_long) / n_req, 2
    )
    out["paged_disagg_arena_blocks_total"] = 2 * arena_rep

    def replay_fleet(tag, make_pools):
        metrics = Metrics()
        pools = make_pools(metrics)
        router = PoolRouter(pools)
        stop = threading.Event()

        def drive(p):
            while not stop.is_set():
                if p.step() == 0:
                    time.sleep(0.001)

        threads = [
            threading.Thread(target=drive, args=(p,), daemon=True)
            for p in pools
        ]
        for t in threads:
            t.start()

        def run_trace(run, replay):
            rids = [None] * len(replay)

            def one(j):
                rids[j] = router.submit(
                    replay[j][0], replay[j][1],
                    trace_id=f"{tag}-{run}-{j}",
                )

            subs = []
            for j0 in range(0, len(replay), burst):
                batch = [
                    threading.Thread(target=one, args=(j,))
                    for j in range(j0, min(j0 + burst, len(replay)))
                ]
                for t in batch:
                    t.start()
                subs.extend(batch)
                time.sleep(0.02)  # bursty, not all-at-once
            for t in subs:
                t.join()
            for rid in rids:
                assert router.result_wait(rid, timeout=600) is not None

        try:
            # shape-identical, content-fresh warmup (see plan comment)
            run_trace("warm", warm_trace)
            t0 = time.perf_counter()
            run_trace("timed", trace)
            wall = time.perf_counter() - t0
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        ttfts = [
            router.request_autopsy(f"{tag}-timed-{j}")["ttft_seconds"]
            for j in range(len(trace))
        ]
        return wall, ttfts, pools

    wall_u, ttft_u, _ = replay_fleet(
        "uni",
        lambda m: [
            PagedContinuousBatchingDecoder(
                model, params, slots=slots_base, steps_per_sync=k_sync,
                kv_blocks=arena_rep, kv_block_size=block, metrics=m,
                model_label="paged-bench", replica_label=str(i),
            )
            for i in range(2)
        ],
    )

    def split_pools(m):
        fabric = PrefixFabric(metrics=m, model_label="paged-bench")
        return [
            PagedContinuousBatchingDecoder(
                model, params, slots=slots_base, steps_per_sync=k_sync,
                kv_blocks=arena_rep, kv_block_size=block, metrics=m,
                model_label="paged-bench", replica_label="p0",
                role="prefill", fabric=fabric,
            ),
            PagedContinuousBatchingDecoder(
                model, params, slots=slots_base, steps_per_sync=k_sync,
                kv_blocks=arena_rep, kv_block_size=block, metrics=m,
                model_label="paged-bench", replica_label="d0",
                role="decode", fabric=fabric,
            ),
        ]

    wall_d, ttft_d, pools_d = replay_fleet("dis", split_pools)
    p99 = lambda xs: round(float(np.percentile(np.asarray(xs), 99)), 4)
    shorts = [j for j, (is_long, _, _) in enumerate(plan) if not is_long]
    longs = [j for j, (is_long, _, _) in enumerate(plan) if is_long]
    out["paged_uniform_tokens_per_sec"] = round(total_new / wall_u, 1)
    out["paged_uniform_p99_ttft_s"] = p99(ttft_u)
    out["paged_uniform_mean_ttft_s"] = round(float(np.mean(ttft_u)), 4)
    out["paged_disagg_tokens_per_sec"] = round(total_new / wall_d, 1)
    out["paged_disagg_p99_ttft_s"] = p99(ttft_d)
    out["paged_disagg_mean_ttft_s"] = round(float(np.mean(ttft_d)), 4)
    # per-class quantiles: the short-decode class is the one prefill
    # head-of-line blocking victimizes in a uniform fleet
    if shorts:
        out["paged_uniform_short_p99_ttft_s"] = p99(
            [ttft_u[j] for j in shorts]
        )
        out["paged_disagg_short_p99_ttft_s"] = p99(
            [ttft_d[j] for j in shorts]
        )
    if longs:
        out["paged_uniform_long_p99_ttft_s"] = p99(
            [ttft_u[j] for j in longs]
        )
        out["paged_disagg_long_p99_ttft_s"] = p99(
            [ttft_d[j] for j in longs]
        )
    # > 1.0 = the phase split BEATS the uniform pool on p99 TTFT
    out["paged_disagg_ttft_p99_speedup"] = round(
        p99(ttft_u) / max(1e-9, p99(ttft_d)), 2
    )
    fabric = pools_d[0].fabric
    snap = fabric.snapshot()
    out["paged_disagg_fabric_publishes"] = snap["publishes"]
    out["paged_disagg_fabric_blocks"] = snap["blocks"]
    out["paged_disagg_fabric_hit_rate"] = round(
        snap["hits"] / max(1, snap["hits"] + snap["misses"]), 3
    )
    dec_phases = pools_d[1].ledger.snapshot()
    out["paged_disagg_migrate_in_dispatches"] = dec_phases.get(
        "migrate_in", {}
    ).get("count", 0)
    out["paged_disagg_decode_dispatches"] = dec_phases
    return out


def bench_fabric() -> dict:
    """Cross-pod prefix fabric (ISSUE 17): a 2-pod shared-system-prompt
    smoke over the REAL wire.  Pod A prefills + publishes the shared
    prefixes into its local fabric and serves them on a FabricServer;
    pod B then replays a request stream whose prompts share those
    prefixes TWICE — once LOCAL-ONLY (no fabric: every cold prefix pays
    a full prefill) and once FLEET (peered at pod A: each cold prefix
    arrives as a chain-tail HTTP pull + ONE migrate_in dispatch).
    Records the remote hit rate, pulled bytes by transport, migrate_in
    dispatch count, and the p99 TTFT delta local-only vs fleet — the
    cold class (first request per prefix) is where the wire actually
    substitutes for prefill work.

    CPU-smoke caveats: the pull is host HTTP + host scatter while the
    avoided prefill is CPU compute, so the TTFT delta's SIGN depends on
    the box — the accounting (hit rate, bytes, exactly one migrate_in
    per cold prefix) is the transferable signal; on chips the avoided
    prefill is the dominant term."""

    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models import llama_tiny
    from tf_operator_tpu.models.batching import (
        PagedContinuousBatchingDecoder,
    )
    from tf_operator_tpu.models.fabric_service import (
        FabricServer,
        FleetFabric,
    )
    from tf_operator_tpu.models.prefix_cache import PrefixFabric
    from tf_operator_tpu.utils.metrics import Metrics

    _apply_platform_override(jax)
    out = {"fabric_backend": jax.default_backend()}
    vocab, seq, block = 96, 128, 16
    n_prefix = int(os.environ.get("MEASURE_FABRIC_PREFIXES", "4"))
    n_req = int(os.environ.get("MEASURE_FABRIC_REQUESTS", "16"))
    prefix_blocks = 3
    model = llama_tiny(vocab_size=vocab, max_len=seq)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    # one SHAPE plan, two content realizations (the leg-F warmup rule):
    # the warmup compiles every admission/pull width class while the
    # timed run's prefix CONTENT stays cold in pod B's local cache
    shape_r = np.random.RandomState(7)
    plan = [
        (i % n_prefix, int(shape_r.randint(4, 13)), 8)
        for i in range(n_req)
    ]

    def make_trace(seed):
        r = np.random.RandomState(seed)
        pre = [
            r.randint(
                0, vocab, size=(prefix_blocks * block,)
            ).astype(np.int32)
            for _ in range(n_prefix)
        ]
        return pre, [
            (
                np.concatenate([
                    pre[pi],
                    r.randint(0, vocab, size=(t,)).astype(np.int32),
                ]),
                b,
            )
            for pi, t, b in plan
        ]

    warm_prefixes, warm_trace = make_trace(77)
    prefixes, trace = make_trace(1234)

    # pod A: publisher — local fabric + its wire server
    mA = Metrics()
    fabA = FleetFabric(
        PrefixFabric(metrics=mA, model_label="fabric-bench"),
        metrics=mA, model_label="fabric-bench",
    )
    poolA = PagedContinuousBatchingDecoder(
        model, params, slots=4, kv_block_size=block, metrics=mA,
        model_label="fabric-bench", fabric=fabA,
    )
    srvA = FabricServer(fabA).start()
    stopA = threading.Event()

    def _driveA():
        while not stopA.is_set():
            if poolA.step() == 0:
                time.sleep(0.001)

    tA = threading.Thread(target=_driveA, daemon=True)
    tA.start()

    def replay(tag, make_fabric):
        m = Metrics()
        fab = make_fabric(m)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=block, metrics=m,
            model_label="fabric-bench", fabric=fab,
        )
        stop = threading.Event()

        def drive():
            while not stop.is_set():
                if pool.step() == 0:
                    time.sleep(0.001)

        t = threading.Thread(target=drive, daemon=True)
        t.start()

        def run(run_tag, replay_trace):
            rids = [
                pool.submit(p, b, trace_id=f"{tag}-{run_tag}-{j}")
                for j, (p, b) in enumerate(replay_trace)
            ]
            for rid in rids:
                assert pool.result_wait(rid, timeout=600) is not None

        try:
            run("warm", warm_trace)
            t0 = time.perf_counter()
            run("timed", trace)
            wall = time.perf_counter() - t0
        finally:
            stop.set()
            t.join(timeout=30)
        ttfts = [
            pool.request_log.get(f"{tag}-timed-{j}")["ttft_seconds"]
            for j in range(len(trace))
        ]
        return wall, ttfts, pool, fab

    try:
        # publish BOTH realizations on A (warmup pulls must cross the
        # wire too, or the fleet leg's timed run compiles on the clock)
        for p in warm_prefixes + prefixes:
            pub = poolA.publish_to_fabric(p, timeout=600.0)
            assert pub["published"] == prefix_blocks
        wall_l, ttft_l, _, _ = replay("loc", lambda m: None)
        wall_f, ttft_f, pool_f, fab_f = replay(
            "fleet",
            lambda m: FleetFabric(
                PrefixFabric(metrics=m, model_label="fabric-bench"),
                peers=[srvA.addr], metrics=m,
                model_label="fabric-bench",
            ),
        )
    finally:
        stopA.set()
        tA.join(timeout=30)
        fabA.stop()
        srvA.stop()

    p99 = lambda xs: round(float(np.percentile(np.asarray(xs), 99)), 4)
    cold = list(range(n_prefix))  # plan is i % n_prefix: first per prefix
    total_new = sum(b for _, b in trace)
    out["fabric_trace_requests"] = n_req
    out["fabric_prefixes"] = n_prefix
    out["fabric_prefix_blocks"] = prefix_blocks
    out["fabric_local_tokens_per_sec"] = round(total_new / wall_l, 1)
    out["fabric_fleet_tokens_per_sec"] = round(total_new / wall_f, 1)
    out["fabric_local_p99_ttft_s"] = p99(ttft_l)
    out["fabric_fleet_p99_ttft_s"] = p99(ttft_f)
    out["fabric_local_cold_p99_ttft_s"] = p99([ttft_l[j] for j in cold])
    out["fabric_fleet_cold_p99_ttft_s"] = p99([ttft_f[j] for j in cold])
    # > 1.0 = the remote pull BEATS recomputing the prefix locally
    out["fabric_ttft_p99_speedup"] = round(
        p99(ttft_l) / max(1e-9, p99(ttft_f)), 2
    )
    fab_f.stop()
    snap = fab_f.snapshot()
    pulls = snap["pulls"]
    out["fabric_pull_hits"] = pulls.get("hit", 0)
    out["fabric_remote_hit_rate"] = round(
        pulls.get("hit", 0) / max(1, sum(pulls.values())), 3
    )
    out["fabric_pull_bytes"] = snap["bytes_pulled"]
    out["fabric_pull_failures"] = sum(snap["pull_failures"].values())
    out["fabric_migrate_in_dispatches"] = pool_f.ledger.snapshot().get(
        "migrate_in", {}
    ).get("count", 0)
    out["fabric_publishes"] = fabA.snapshot()["publishes"]
    return out


def _spec_pair(model, params, qparams, prompt, n_new, prefix, out) -> None:
    """Measure plain greedy generate vs SpeculativeDecoder (int8
    self-draft) for one model; writes `{prefix}_*` rows + the decoder's
    dispatch ledger into `out`."""

    import jax
    import numpy as np

    from tf_operator_tpu.models import SpeculativeDecoder, generate

    plain = jax.jit(
        lambda p, ids: generate(model, p, ids, max_new_tokens=n_new)
    )
    np.asarray(plain(params, prompt))  # compile
    t0 = time.perf_counter()
    np.asarray(plain(params, prompt))
    dt_plain = time.perf_counter() - t0

    dec = SpeculativeDecoder(model, params, model, qparams, k=4)
    dec.generate(prompt, max_new_tokens=n_new)  # compile
    dec.ledger.reset()  # count the steady-state call only
    t0 = time.perf_counter()
    dec.generate(prompt, max_new_tokens=n_new)
    dt_spec = time.perf_counter() - t0
    out[f"{prefix}_new_tokens"] = n_new
    out[f"{prefix}_plain_tokens_per_sec"] = round(n_new / dt_plain, 1)
    out[f"{prefix}_tokens_per_sec"] = round(n_new / dt_spec, 1)
    out[f"{prefix}_speedup"] = round(dt_plain / dt_spec, 2)
    out[f"{prefix}_acceptance"] = round(dec.acceptance_rate, 3)
    out[f"{prefix}_dispatches"] = dec.ledger.snapshot()


def bench_speculative() -> dict:
    """Speculative decode, two measured configurations at batch 1 (the
    latency-bound serving case speculation exists for):

    - `speculative_*`: target = llama-mini bf16, draft = the SAME
      weights int8-quantized (no second model to train) — the headline
      since r4, 0.1x on this box (tunnel-dispatch + thin 120M
      economics, PROFILE.md "r5 serving");
    - `speculative_wide_*` (r6, VERDICT r5 next #2): target = the
      ~700M wide-llama, draft = ITS int8 tree — the weight-bandwidth-
      bound configuration where verification's width-k weight reads
      and the draft's halved HBM traffic actually pay (wide decode is
      1.53x int8-vs-bf16, BASELINE.md).  serve_lm --speculative
      refuses when the BEST of these measured rows is < 1x.

    Each row embeds the decoder's DispatchLedger so the dispatch
    arithmetic (fused driver = prompt prefills + ONE generate
    dispatch) is part of the artifact."""

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import llama_mini_config, llama_wide_config
    from tf_operator_tpu.models import LlamaLM
    from tf_operator_tpu.ops.quant import quantize_tree

    _apply_platform_override(jax)
    out = {"speculative_backend": jax.default_backend()}
    seq = int(os.environ.get("MEASURE_SPEC_MAXLEN", "512"))
    n_new = int(os.environ.get("MEASURE_SPEC_NEW", "128"))
    tiny = bool(os.environ.get("MEASURE_SPEC_TINY"))
    if tiny:  # CPU smoke
        from tf_operator_tpu.models import llama_tiny

        model = llama_tiny(vocab_size=256, max_len=seq)
    else:
        model = LlamaLM(llama_mini_config(seq))
    vocab = model.cfg.vocab_size
    r = np.random.RandomState(0)
    prompt = jnp.asarray(r.randint(0, vocab, size=(1, 32)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    _spec_pair(
        model, params, quantize_tree(params), prompt, n_new,
        "speculative", out,
    )

    # the draft!=target weight-bound configuration.  ~700M init is
    # chip-minutes on its own; skipped on the tiny CPU smoke and
    # gate-able via MEASURE_SPEC_WIDE=0.  A skipped leg records a
    # PARSEABLE reason pointing at the paged plane's ledger phases
    # (draft/verify — the row serving actually reads since ISSUE 18),
    # not the dead pre-paged prefill/generate key names.
    if tiny or os.environ.get("MEASURE_SPEC_WIDE", "1") == "0":
        out["speculative_wide_skipped"] = (
            ("tiny CPU smoke" if tiny else "MEASURE_SPEC_WIDE=0")
            + " — no wide draft!=target row this run; the serving-"
            "facing speculative measurement is the paged-plane row "
            "(spec_paged_*, ledger phases draft+verify, --section "
            "speculative-paged)"
        )
    else:
        try:
            wcfg = llama_wide_config(
                int(os.environ.get("MEASURE_SPEC_WIDE_MAXLEN", "512"))
            )
            wmodel = LlamaLM(wcfg)
            wprompt = jnp.asarray(
                np.random.RandomState(1).randint(0, 32000, size=(1, 32)),
                jnp.int32,
            )
            wparams = wmodel.init(jax.random.PRNGKey(0), wprompt)["params"]
            # bf16-stored baseline, same honesty rule as bench.py's
            # wide-decode row: fp32 storage would double baseline HBM
            # traffic and flatter the speculative ratio
            wparams = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.bfloat16), wparams
            )
            _spec_pair(
                wmodel, wparams, quantize_tree(wparams), wprompt,
                int(os.environ.get("MEASURE_SPEC_WIDE_NEW", "64")),
                "speculative_wide", out,
            )
        except Exception as exc:  # additive, never fatal to the mini row
            out["speculative_wide_error"] = repr(exc)[:200]
    return out


def bench_speculative_paged() -> dict:
    """Speculative decoding ON THE PAGED PLANE (ISSUE 18): the serving
    row serve_lm's ``--speculative`` guard reads.  An int8 self-draft
    (the target weights quantized — no second model to train) pages
    its KV through the SAME BlockAllocator arena, verification of all
    K draft tokens is ONE fused multi-query dispatch, and
    accept/rollback happen in-graph — steady state is exactly one
    ``draft`` + one ``verify`` ledger dispatch per window.  Measured
    against the NON-speculative paged pool at the SAME arena and seat
    count over an interactive trace:

    - ``spec_paged_speedup``: wall-clock tokens/sec ratio — the
      guard's >1x lift criterion;
    - ``spec_paged_dispatches_per_token``: the CPU-honest acceptance
      metric — 2 dispatches/window over tokens actually emitted;
      < 1.0 means speculation beats one-dispatch-per-token in
      DISPATCH COUNT regardless of this box's walls;
    - ``spec_paged_acceptance`` + per-tier p99 TTFT for both pools.

    CPU smoke: MEASURE_SPEC_TINY=1 swaps in llama_tiny (the
    tpu_window step runs this every round)."""

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models.batching import (
        PagedContinuousBatchingDecoder,
    )
    from tf_operator_tpu.ops.quant import quantize_tree
    from tf_operator_tpu.utils.metrics import SLO_BUCKETS, Metrics

    _apply_platform_override(jax)
    out = {"spec_paged_backend": jax.default_backend()}
    tiny = bool(os.environ.get("MEASURE_SPEC_TINY"))
    seq = int(os.environ.get(
        "MEASURE_SPEC_PAGED_MAXLEN", "192" if tiny else "512"
    ))
    block = int(os.environ.get("MEASURE_SPEC_PAGED_BLOCK", "16"))
    slots = int(os.environ.get("MEASURE_SPEC_PAGED_SLOTS", "4"))
    n_req = int(os.environ.get("MEASURE_SPEC_PAGED_REQUESTS", "8"))
    # long enough that steady-state windows, not admission prefill,
    # carry the wall — the ratio is meaningless otherwise
    n_new = int(os.environ.get("MEASURE_SPEC_PAGED_NEW", "96"))
    spec_k = int(os.environ.get("MEASURE_SPEC_K", "4"))
    if tiny:
        from tf_operator_tpu.models import llama_tiny

        model = llama_tiny(vocab_size=256, max_len=seq)
        cfg_name = "llama-tiny"
    else:
        from bench import llama_mini_config
        from tf_operator_tpu.models import LlamaLM

        model = LlamaLM(llama_mini_config(seq))
        cfg_name = "llama-mini"
    vocab = model.cfg.vocab_size
    r = np.random.RandomState(0)
    init_ids = jnp.asarray(r.randint(0, vocab, size=(1, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), init_ids)["params"]
    qparams = quantize_tree(params)

    # all-interactive trace (speculation is tier-gated to interactive:
    # the latency class it exists for); mixed prompt lengths
    trace = []
    for _ in range(n_req):
        p_len = int(r.randint(4, max(5, seq // 4)))
        budget = min(n_new, seq - p_len)
        prompt = r.randint(0, vocab, size=(p_len,)).astype(np.int32)
        trace.append((prompt, budget))
    total_new = sum(b for _, b in trace)
    arena = slots * (seq // block)
    out["spec_paged_requests"] = n_req
    out["spec_paged_new_tokens"] = total_new
    out["spec_paged_arena_blocks"] = arena
    out["spec_paged_k"] = spec_k
    out["spec_paged_config"] = (
        f"{cfg_name} target + int8 self-draft, k={spec_k}, "
        "tier=interactive, shared block arena"
    )

    def replay(speculative: bool):
        kw = (
            dict(draft_model=model, draft_params=qparams, spec_k=spec_k)
            if speculative else {}
        )
        metrics = Metrics()
        metrics.set_buckets("serve_ttft_seconds", SLO_BUCKETS)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=slots, kv_blocks=arena,
            kv_block_size=block, metrics=metrics,
            model_label="spec-paged-bench", **kw,
        )
        # warmup compiles the width classes (admission + draft
        # prefill) off the clock
        for p, budget in trace:
            pool.submit(p, budget, tier="interactive")
        pool.run()
        pool.ledger.reset()
        metrics2 = Metrics()
        metrics2.set_buckets("serve_ttft_seconds", SLO_BUCKETS)
        pool.metrics = metrics2
        if speculative:
            pool.spec_windows = pool.spec_proposed = 0
            pool.spec_accepted = pool.spec_rollbacks = 0
            pool.spec_emitted = 0
        t0 = time.perf_counter()
        for p, budget in trace:
            pool.submit(p, budget, tier="interactive")
        pool.run()
        wall = time.perf_counter() - t0
        pool.alloc.check()
        return wall, pool, metrics2

    wall_p, pool_p, m_p = replay(False)
    out["spec_paged_plain_tokens_per_sec"] = round(total_new / wall_p, 1)
    out["spec_paged_plain_p99_ttft_s"] = m_p.histogram(
        "serve_ttft_seconds", model="spec-paged-bench", mode="pool",
        tier="interactive",
    ).get("p99_le")

    wall_s, pool_s, m_s = replay(True)
    out["spec_paged_tokens_per_sec"] = round(total_new / wall_s, 1)
    out["spec_paged_p99_ttft_s"] = m_s.histogram(
        "serve_ttft_seconds", model="spec-paged-bench", mode="pool",
        tier="interactive",
    ).get("p99_le")
    out["spec_paged_speedup"] = round(wall_p / wall_s, 2)
    snap = pool_s.spec_snapshot()
    out["spec_paged_acceptance"] = round(snap["acceptance_rate"], 3)
    out["spec_paged_dispatches_per_token"] = round(
        snap["dispatches_per_token"], 3
    )
    out["spec_paged_windows"] = int(snap["spec_windows"])
    out["spec_paged_rollbacks"] = int(snap["spec_rollbacks"])
    out["spec_paged_dispatches"] = pool_s.ledger.snapshot()
    return out


def write_baseline(out: dict) -> None:
    """Regenerate the control-plane table in BASELINE.md between the
    measured:begin/end markers (VERDICT r2 item 9: the scoreboard must
    not rot — this function IS how the table gets its numbers)."""

    import datetime

    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "BASELINE.md")
    with open(path) as f:
        text = f.read()
    begin, end = "<!-- measured:begin -->", "<!-- measured:end -->"
    i, j = text.index(begin), text.index(end)
    today = datetime.date.today().isoformat()
    span_n = out.get("sync_span_native", {})
    rows = [
        "| Metric | Value | Setup |",
        "|---|---|---|",
        (
            f"| Fake-backend reconcile throughput | **{out['reconcile_jobs_per_sec_native']} jobs/s**"
            f" (native runtime), {out['reconcile_jobs_per_sec_python']} jobs/s (Python runtime)"
            " — 3-replica jobs driven create→Succeeded | in-proc fake cluster,"
            f" `benchmarks/measure.py`, {today} |"
        ),
        (
            f"| Per-sync span | mean {span_n.get('mean_ms', '?')} ms, p99 ≤"
            f" {span_n.get('p99_le_ms', '?')} ms (native runtime;"
            " `tpujob_sync_duration_seconds` histogram) |"
            f" `benchmarks/measure.py`, {today} |"
        ),
        (
            f"| Decision core (one batch sync_decide, 7-pod job) | native {out['sync_decide_per_sec_native']}/s,"
            f" python {out['sync_decide_per_sec_python']}/s"
            f" ({out['sync_decide_native_speedup']}× — see `benchmarks/NATIVE.md` for why python wins at small jobs) |"
            f" `benchmarks/measure.py`, {today} |"
        ),
        (
            f"| Job-startup latency, local-process backend | **p50 {out['startup_latency_ms_p50']} ms**,"
            f" max {out['startup_latency_ms_max']} ms (create → Running condition) |"
            f" subprocess pods, localhost, `benchmarks/measure.py`, {today} |"
        ),
    ]
    new = text[: i + len(begin)] + "\n" + "\n".join(rows) + "\n" + text[j:]
    with open(path, "w") as f:
        f.write(new)
    print(f"wrote control-plane table to {path}")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--section",
        choices=[
            "all", "reconcile", "startup", "train", "batching",
            "speculative", "speculative-paged", "paged", "multislice",
            "fabric",
        ],
        default="all",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="regenerate BASELINE.md's control-plane table from this run "
        "(runs reconcile + startup sections)",
    )
    args = parser.parse_args()
    if args.section == "multislice" and os.environ.get(
        "MEASURE_PLATFORM", "cpu"
    ) == "cpu":
        # the 2-slice sim needs virtual devices, and the flag must land
        # before the first jax import (sections are exclusive, so jax
        # is not yet imported here).  The single TPU chip on this box
        # cannot form a multi-slice mesh — real-DCN walls ride the
        # queued chip window; MEASURE_PLATFORM=tpu opts a real
        # multi-slice world in.
        os.environ.setdefault("MEASURE_PLATFORM", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    out = {}
    if args.write_baseline:
        out.update(bench_reconcile())
        out.update(bench_decision_core())
        out.update(bench_startup_latency())
        print(json.dumps(out, indent=1))
        write_baseline(out)
        return 0
    if args.section in ("all", "reconcile"):
        out.update(bench_reconcile())
        out.update(bench_decision_core())
    if args.section in ("all", "startup"):
        out.update(bench_startup_latency())
    if args.section in ("all", "train"):
        out.update(bench_training())
    if args.section == "batching":  # not in "all": needs chip minutes
        out.update(bench_batching())
    if args.section == "speculative":  # not in "all": needs chip minutes
        out.update(bench_speculative())
    if args.section == "speculative-paged":  # not in "all": chip minutes
        out.update(bench_speculative_paged())
    if args.section == "paged":  # not in "all": needs chip minutes
        out.update(bench_paged())
    if args.section == "multislice":  # not in "all": needs its own jax env
        out.update(bench_multislice())
    if args.section == "fabric":  # not in "all": spins pools + wire
        out.update(bench_fabric())
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
