"""Deterministic on-disk text corpus for byte-level LM training.

Same contract as the image datasets in data/synthetic.py (no network on
this box — SURVEY.md §7): a *learnable* procedural corpus generated
once to disk, then always read through the grain pipeline with
per-process disjoint shards.

Learnable by construction: sentences come from a small templated
grammar over a fixed word list, so a byte-level model can learn word
spellings, spaces, and template structure — loss drops far below the
uniform-bytes ln(256) ≈ 5.55 floor within tens of steps (tested).
Byte-level means the tokenizer is the identity on uint8: vocab 256, no
vocabulary files, fully deterministic.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from tf_operator_tpu.data.synthetic import _exists, commit_arrays

_NOUNS = (
    "operator worker slice tensor kernel gradient token shard mesh ring "
    "queue batch buffer device compiler schedule"
).split()
_VERBS = (
    "schedules reduces shards rotates compiles streams permutes gathers "
    "fuses drains adopts restarts"
).split()
_ADJS = (
    "sharded fused causal atomic idle hot replicated factored lazy strict"
).split()


def text_meta(n_chars: int = 1 << 20, seq_len: int = 256, seed: int = 0) -> dict:
    return {
        "kind": "grammar_bytes",
        "n_chars": n_chars,
        "seq_len": seq_len,
        "seed": seed,
    }


def _generate_corpus(n_chars: int, seed: int) -> str:
    r = np.random.RandomState(seed)
    parts = []
    total = 0
    while total < n_chars:
        s = (
            f"the {r.choice(_ADJS)} {r.choice(_NOUNS)} "
            f"{r.choice(_VERBS)} the {r.choice(_NOUNS)}. "
        )
        parts.append(s)
        total += len(s)
    return "".join(parts)[:n_chars]


def ensure_text(
    directory: str, n_chars: int = 1 << 20, seq_len: int = 256, seed: int = 0
) -> str:
    """Generate (idempotent) and return the corpus directory.

    Layout: ``tokens.npy`` [n_windows, seq_len] uint8 (non-overlapping
    windows of the byte stream) + the meta commit record.
    """

    meta = text_meta(n_chars, seq_len, seed)
    if _exists(directory, meta):
        return directory
    text = _generate_corpus(n_chars, seed)
    tokens = np.frombuffer(text.encode("ascii"), dtype=np.uint8)
    n_windows = len(tokens) // seq_len
    windows = tokens[: n_windows * seq_len].reshape(n_windows, seq_len)
    commit_arrays(directory, {"tokens.npy": windows}, meta)
    return directory


def decode_bytes(arr) -> str:
    """uint8/int token array → printable string (the 'detokenizer')."""

    b = np.asarray(arr).reshape(-1).astype(np.uint8).tobytes()
    return b.decode("ascii", errors="replace")


class TextWindowSource:
    """grain RandomAccessDataSource over the tokens.npy layout
    (memory-mapped — workers share page cache)."""

    def __init__(self, directory: str):
        self.tokens = np.load(os.path.join(directory, "tokens.npy"), mmap_mode="r")

    def __len__(self) -> int:
        return len(self.tokens)

    def __getitem__(self, idx: int) -> dict:
        return {"input_ids": np.asarray(self.tokens[idx])}


def make_text_loader(
    directory: str,
    per_process_batch: int,
    *,
    process_id: Optional[int] = None,
    process_count: Optional[int] = None,
    seed: int = 0,
    shuffle: bool = True,
    num_epochs: Optional[int] = None,
    worker_count: int = 0,
):
    """Sharded grain DataLoader yielding {"input_ids": [B, S] uint8}
    per-process batches from DISJOINT window shards (same sharding
    contract as data/loader.py's image loader)."""

    import grain.python as grain

    if process_id is None or process_count is None:
        import jax

        process_id = jax.process_index() if process_id is None else process_id
        process_count = jax.process_count() if process_count is None else process_count

    source = TextWindowSource(directory)
    sampler = grain.IndexSampler(
        num_records=len(source),
        shard_options=grain.ShardOptions(
            shard_index=process_id, shard_count=process_count, drop_remainder=True
        ),
        shuffle=shuffle,
        num_epochs=num_epochs,
        seed=seed,
    )
    return grain.DataLoader(
        data_source=source,
        sampler=sampler,
        operations=[grain.Batch(per_process_batch, drop_remainder=True)],
        worker_count=worker_count,
    )


def as_lm_batches(loader):
    """Loader dicts → int32 model batches (the byte 'tokenizer' is a
    cast; vocab is 256)."""

    for batch in loader:
        yield {"input_ids": batch["input_ids"].astype(np.int32)}
