"""grain input pipeline: per-process sharded loading + device prefetch.

Parity: the reference's examples read real MNIST through TF input
pipelines, sharded per worker by the distribution strategy (SURVEY.md
§2 example rows).  TPU-native shape: a grain DataLoader per process
over a disjoint shard of the on-disk dataset (ShardOptions = this
process's slice of the index space), worker threads/processes doing the
host-side work, and a double-buffered device_put so the host→device
copy of batch N+1 overlaps the compute of batch N.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np

import grain.python as grain


class NpySource:
    """grain RandomAccessDataSource over the synthetic.py npy layout.

    Memory-mapped: processes share page cache, no full-array resident
    copy per worker.
    """

    def __init__(self, directory: str):
        self.images = np.load(os.path.join(directory, "images.npy"), mmap_mode="r")
        self.labels = np.load(os.path.join(directory, "labels.npy"), mmap_mode="r")
        assert len(self.images) == len(self.labels)

    def __len__(self) -> int:
        return len(self.images)

    def __getitem__(self, idx: int) -> dict:
        return {
            "image": np.asarray(self.images[idx]),
            "label": np.asarray(self.labels[idx]),
        }


def make_loader(
    directory: str,
    per_process_batch: int,
    *,
    process_id: Optional[int] = None,
    process_count: Optional[int] = None,
    seed: int = 0,
    shuffle: bool = True,
    num_epochs: Optional[int] = None,
    worker_count: int = 0,
) -> grain.DataLoader:
    """A sharded grain DataLoader yielding per-process batches.

    process_id/process_count default to jax.process_index()/count —
    each process reads a DISJOINT shard of the dataset (tested by
    tests/test_data.py), which is what makes the global batch a true
    sample without duplication.
    """

    if process_id is None or process_count is None:
        import jax

        process_id = jax.process_index() if process_id is None else process_id
        process_count = jax.process_count() if process_count is None else process_count

    source = NpySource(directory)
    sampler = grain.IndexSampler(
        num_records=len(source),
        shard_options=grain.ShardOptions(
            shard_index=process_id, shard_count=process_count, drop_remainder=True
        ),
        shuffle=shuffle,
        num_epochs=num_epochs,
        seed=seed,
    )
    return grain.DataLoader(
        data_source=source,
        sampler=sampler,
        operations=[grain.Batch(per_process_batch, drop_remainder=True)],
        worker_count=worker_count,
    )


def _normalize(batch: dict, image_dtype) -> dict:
    """uint8 [0,255] -> image_dtype [0,1); labels -> int32."""

    return {
        "image": (batch["image"].astype(np.float32) / 255.0).astype(image_dtype),
        "label": batch["label"].astype(np.int32),
    }


def device_prefetch(
    loader,
    sharding_tree,
    *,
    image_dtype=np.float32,
    prefetch: Optional[int] = None,
    normalize_on_device: bool = False,
) -> Iterator[dict]:
    """Iterate device-resident global batches, transfer overlapped.

    Each yielded element is the GLOBAL batch laid out on the mesh
    (jax.make_array_from_process_local_data from this process's shard).
    Keeping ``prefetch`` batches in flight lets the host→device copy of
    the next batch run while the current step computes — jax transfers
    are async, so simply staying ahead of consumption is enough.

    ``prefetch`` (the depth knob): None reads ``TPU_OPERATOR_PREFETCH``
    (default 2).  Depth trades host memory (depth × batch bytes staged)
    against tolerance for loader jitter; once the training step is
    sync-free (steps_per_sync > 1) the pipeline is the remaining
    constraint candidate, and ``measure.py --section train`` sweeps
    this knob so PROFILE.md shows where more depth stops paying.

    ``normalize_on_device=True`` ships the uint8 pixels as-is (4-8x
    less transfer traffic) and casts/scales on device — the right mode
    whenever host→device bandwidth is the constraint.
    """

    import collections

    import jax

    if prefetch is None:
        prefetch = int(os.environ.get("TPU_OPERATOR_PREFETCH", "2"))
    if prefetch < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {prefetch}")

    scale = None
    if normalize_on_device:
        import jax.numpy as jnp

        dt = jnp.dtype(image_dtype)
        scale = jax.jit(
            lambda a: a.astype(dt) / 255.0,
            out_shardings=sharding_tree["image"],
        )

    def put(host_batch):
        if normalize_on_device:
            batch = {
                "image": np.ascontiguousarray(host_batch["image"]),
                "label": host_batch["label"].astype(np.int32),
            }
        else:
            batch = _normalize(host_batch, image_dtype)
        out = {
            k: jax.make_array_from_process_local_data(sharding_tree[k], v)
            for k, v in batch.items()
        }
        if normalize_on_device:
            out["image"] = scale(out["image"])
        return out

    buf = collections.deque()
    it = iter(loader)
    try:
        while len(buf) < prefetch:
            buf.append(put(next(it)))
    except StopIteration:
        pass
    while buf:
        out = buf.popleft()
        try:
            buf.append(put(next(it)))
        except StopIteration:
            pass
        yield out
