"""Deterministic on-disk datasets, procedurally generated once.

No network on this box (SURVEY.md §7 environment facts), so the "real
MNIST" the reference's examples download is replaced by a *learnable*
procedural dataset written to disk once and then always read through
the grain input pipeline — loading, sharding, host→device transfer are
exactly the real path; only the pixels are synthetic.

Learnable by construction: each class has a fixed random template and
every example is its class template plus noise, so a model that learns
the templates beats chance by a wide margin (tests assert accuracy
climbs).  uint8 on disk, normalised on device — the honest layout
(decode/augment happens host-side in the reference pipelines too).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

_META = "meta.json"


def wait_for_dataset(
    directory: str, timeout: float = 120.0, meta: Optional[dict] = None
) -> str:
    """Block until another process finishes generating ``directory``.

    Multi-process jobs generate on the coordinator only (one writer);
    the rest call this.  Pass ``meta`` (the exact parameter dict the
    coordinator generates with — ``mnist_meta()`` etc.) so a STALE
    dataset from different parameters doesn't satisfy the wait while
    the coordinator is mid-rewrite.
    """

    deadline = time.time() + timeout
    while time.time() < deadline:
        if meta is not None:
            if _exists(directory, meta):
                return directory
        elif os.path.exists(os.path.join(directory, _META)):
            return directory
        time.sleep(0.2)
    raise TimeoutError(f"dataset never appeared at {directory}")


def mnist_meta(n: int = 16384, seed: int = 0, classes: int = 10) -> dict:
    return {"kind": "mnist-like", "n": n, "seed": seed, "classes": classes}


def commit_arrays(directory: str, arrays: dict, meta: dict) -> None:
    """Two-phase commit for any name→array dataset layout: retract meta
    first (readers poll it — see wait_for_dataset), write data files
    via tmp+rename so a reader never mmaps a half-written array, land
    meta last as the commit record.  This also makes REgeneration
    (stale meta from different parameters) safe."""

    os.makedirs(directory, exist_ok=True)
    meta_path = os.path.join(directory, _META)
    try:
        os.remove(meta_path)
    except FileNotFoundError:
        pass
    pid = os.getpid()
    for name, arr in arrays.items():
        # tmp must end in .npy or np.save appends the suffix itself
        tmp = os.path.join(directory, f".{name[:-4]}.{pid}.tmp.npy")
        np.save(tmp, arr)
        os.replace(tmp, os.path.join(directory, name))
    tmp = os.path.join(directory, f".{_META}.{pid}.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, meta_path)


def _write(directory: str, images: np.ndarray, labels: np.ndarray, meta: dict) -> None:
    commit_arrays(directory, {"images.npy": images, "labels.npy": labels}, meta)


def _exists(directory: str, meta: dict) -> bool:
    path = os.path.join(directory, _META)
    try:
        with open(path) as f:
            return json.load(f) == meta
    except (OSError, ValueError):
        return False


def ensure_mnist(
    directory: str, n: int = 16384, seed: int = 0, classes: int = 10
) -> str:
    """28x28x1 uint8 dataset in the MNIST shape; idempotent."""

    meta = mnist_meta(n, seed, classes)
    if _exists(directory, meta):
        return directory
    r = np.random.RandomState(seed)
    templates = r.rand(classes, 28, 28, 1).astype(np.float32)
    labels = r.randint(0, classes, size=(n,)).astype(np.int32)
    noise = r.rand(n, 28, 28, 1).astype(np.float32)
    images = 0.7 * templates[labels] + 0.3 * noise
    _write(directory, (images * 255).astype(np.uint8), labels, meta)
    return directory


def ensure_imagenet_like(
    directory: str,
    n: int = 512,
    size: int = 224,
    classes: int = 1000,
    seed: int = 0,
) -> str:
    """224x224x3 uint8 dataset in the ImageNet shape (bench input
    pipeline); idempotent.  Templates are stored at low resolution and
    upsampled so generation stays fast and the file is the only big
    artifact (~n*size*size*3 bytes)."""

    meta = {
        "kind": "imagenet-like",
        "n": n,
        "size": size,
        "seed": seed,
        "classes": classes,
    }
    if _exists(directory, meta):
        return directory
    r = np.random.RandomState(seed)
    labels = r.randint(0, classes, size=(n,)).astype(np.int32)
    small = size // 8
    images = np.empty((n, size, size, 3), dtype=np.uint8)
    # per-class template at low res; repeat-upsample + noise per example
    templates = r.rand(min(classes, 64), small, small, 3).astype(np.float32)
    for i in range(n):
        t = templates[labels[i] % len(templates)]
        up = np.repeat(np.repeat(t, 8, axis=0), 8, axis=1)
        img = 0.7 * up + 0.3 * r.rand(size, size, 3).astype(np.float32)
        images[i] = (img * 255).astype(np.uint8)
    _write(directory, images, labels, meta)
    return directory
