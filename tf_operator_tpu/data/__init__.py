"""Input pipeline: on-disk datasets + grain loaders with per-process
sharding (SURVEY.md §7 step 8 — the real data path the reference's
examples have and synthetic tensors skip)."""

from tf_operator_tpu.data.loader import (
    NpySource,
    device_prefetch,
    make_loader,
)
from tf_operator_tpu.data.synthetic import (
    ensure_imagenet_like,
    ensure_mnist,
    wait_for_dataset,
)
from tf_operator_tpu.data.text import (
    as_lm_batches,
    decode_bytes,
    ensure_text,
    make_text_loader,
)

__all__ = [
    "NpySource",
    "as_lm_batches",
    "decode_bytes",
    "device_prefetch",
    "ensure_imagenet_like",
    "ensure_mnist",
    "ensure_text",
    "make_loader",
    "make_text_loader",
    "wait_for_dataset",
]
