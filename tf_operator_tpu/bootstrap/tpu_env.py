"""TPU/JAX bootstrap env — the TF_CONFIG twin for the ICI/DCN world.

Parity target (SURVEY.md §2c, §5 "Distributed communication backend"):
where the reference injects TF_CONFIG so TF strategies bootstrap
gRPC/NCCL, we inject the env that lets a JAX process join the job:

- ``TPUJOB_*``: this framework's canonical vars, consumed by
  ``tf_operator_tpu.runtime.initialize()`` →
  ``jax.distributed.initialize(coordinator_address, num_processes,
  process_id)``.
- ``MEGASCALE_*``: multi-slice (DCN) topology for libtpu/XLA when a job
  spans multiple TPU_SLICE replicas.
- ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES``: libtpu multi-host
  discovery within a slice.

Process-id assignment is deterministic: replicas are numbered in
REPLICA_TYPE_ORDER, then by index — the same ordering the cluster spec
uses, so process 0 is always the coordinator replica's index 0.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from tf_operator_tpu.api.types import (
    DEFAULT_COORDINATOR_PORT,
    ReplicaType,
    TPUJob,
    replica_name,
)
from tf_operator_tpu.bootstrap.cluster_spec import (
    AddressResolver,
    _replica_port,
    coordinator_replica,
    dns_resolver,
)

ENV_COORDINATOR = "TPUJOB_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "TPUJOB_NUM_PROCESSES"
ENV_PROCESS_ID = "TPUJOB_PROCESS_ID"
ENV_REPLICA_TYPE = "TPUJOB_REPLICA_TYPE"
ENV_REPLICA_INDEX = "TPUJOB_REPLICA_INDEX"
ENV_JOB_NAME = "TPUJOB_NAME"

#: fleet-telemetry injection (ISSUE 15) — set per pod by the
#: RECONCILER (not gen_tpu_env: the port is allocated at pod-create
#: time, not derivable from the spec).  When present, the training
#: harness boots a pod-side telemetry server on 127.0.0.1:<port>
#: (/metrics, /traces, /debug/flightrecorder — runtime/telemetry.py);
#: unset/0 = no server, the library-user default.
ENV_TELEMETRY_PORT = "TPUJOB_TELEMETRY_PORT"
#: trace-stitching context (ISSUE 15): the reconciler's ``pod.create``
#: span rides the pod env, the harness roots its train-loop trace
#: under it, and the operator-side scraper folds the pod's spans back
#: into its own TraceStore — ONE id spans reconcile→boot→train.
ENV_TRACE_ID = "TPUJOB_TRACE_ID"
ENV_PARENT_SPAN_ID = "TPUJOB_PARENT_SPAN_ID"
#: cross-pod KV fabric injection (ISSUE 17) — set per pod by the
#: reconciler exactly like the telemetry port.  A serving pod
#: (examples/serve_lm.py) boots its FabricServer on 127.0.0.1:<port>
#: so peers can pull published prefix blocks; unset/0 = no fabric
#: server, the single-pod default.
ENV_FABRIC_PORT = "TPUJOB_FABRIC_PORT"


def detected_slice_topology() -> Tuple[int, "int | None"]:
    """(num_slices, slice_id-or-None) from the MEGASCALE env THIS module
    injects into multi-slice workers (``gen_tpu_env`` below) — the
    worker-side read of the injection contract.  Single-slice worlds
    (no MEGASCALE vars, or the 1-slice degenerate where gen_tpu_env
    injects nothing) report ``(1, None)``.  ``parallel/mesh.make_mesh``
    consults this when no explicit ``slices=`` is passed, so a trainer
    launched by the operator builds a slice-aware mesh with zero
    configuration."""

    import os

    try:
        n = int(os.environ.get("MEGASCALE_NUM_SLICES", "1") or "1")
    except ValueError:
        n = 1
    sid_raw = os.environ.get("MEGASCALE_SLICE_ID")
    sid: "int | None" = None
    if sid_raw not in (None, ""):
        try:
            sid = int(sid_raw)
        except ValueError:
            sid = None
    return max(1, n), sid


def _process_table(job: TPUJob) -> List[Tuple[ReplicaType, int]]:
    """Global process numbering: coordinator replica type first (its index
    0 must be process 0), then the remaining types in canonical order.
    One entry per POD — each host of a multi-host slice is its own JAX
    process (pod index = slice*H + host)."""

    coord = coordinator_replica(job)
    ordered = job.spec.ordered_types()
    if coord in ordered:
        ordered = [coord] + [t for t in ordered if t is not coord]
    table: List[Tuple[ReplicaType, int]] = []
    for rtype in ordered:
        # PS/evaluator replicas are not JAX collective participants; they
        # still get entries so every replica has a stable process id.
        table.extend((rtype, i) for i in range(job.spec.pod_count(rtype)))
    return table


def gen_tpu_env(
    job: TPUJob,
    rtype: ReplicaType,
    index: int,
    resolve: AddressResolver = dns_resolver,
) -> Dict[str, str]:
    """Env block for one replica — injected next to TF_CONFIG."""

    coord_type = coordinator_replica(job)
    if coord_type is None:
        return {}
    coord_port = _replica_port(job, coord_type)
    # the coordinator port must be the jax.distributed one, not the TF
    # gRPC port, when the coordinator replica kept the default 2222
    if coord_port == 2222:
        coord_port = DEFAULT_COORDINATOR_PORT
    coord_addr = resolve(job, coord_type, 0, coord_port)

    table = _process_table(job)
    process_id = table.index((rtype, index))
    env = {
        ENV_JOB_NAME: job.metadata.name,
        ENV_COORDINATOR: coord_addr,
        ENV_NUM_PROCESSES: str(len(table)),
        ENV_PROCESS_ID: str(process_id),
        ENV_REPLICA_TYPE: rtype.lower_name,
        ENV_REPLICA_INDEX: str(index),
    }

    # Multi-slice (DCN) topology: each TPU_SLICE replica is one slice;
    # ``index`` is a POD index (slice*H + host), so the slice id is the
    # pod index divided by the hosts-per-slice expansion factor.
    slice_spec = job.spec.replica_specs.get(ReplicaType.TPU_SLICE)
    hosts = slice_spec.slice_host_count() if slice_spec is not None else 1
    if slice_spec is not None and int(slice_spec.replicas or 0) > 1:
        env["MEGASCALE_COORDINATOR_ADDRESS"] = coord_addr.rsplit(":", 1)[0]
        env["MEGASCALE_NUM_SLICES"] = str(int(slice_spec.replicas or 0))
        if rtype is ReplicaType.TPU_SLICE:
            env["MEGASCALE_SLICE_ID"] = str(index // hosts)

    # Intra-slice libtpu discovery — the multi-host expansion contract
    # (VERDICT round 1 item 6, now implemented): a slice whose topology
    # spans H hosts runs as H pods; each gets TPU_WORKER_ID = its host
    # ordinal and TPU_WORKER_HOSTNAMES = the host list of ITS OWN slice
    # only (never other slices — that would contradict the MEGASCALE
    # inter-slice topology above).
    if rtype is ReplicaType.TPU_SLICE:
        slice_id = index // hosts
        host_id = index % hosts
        slice_pods = range(slice_id * hosts, (slice_id + 1) * hosts)
        hostnames = [
            resolve(job, ReplicaType.TPU_SLICE, p, 0).rsplit(":", 1)[0]
            for p in slice_pods
        ]
        env["TPU_WORKER_ID"] = str(host_id)
        env["TPU_WORKER_HOSTNAMES"] = ",".join(hostnames)

    return env


def worker_env(
    job: TPUJob,
    rtype: ReplicaType,
    index: int,
    resolve: AddressResolver = dns_resolver,
    tf_config: bool = True,
) -> Dict[str, str]:
    """Everything createNewPod injects: TF_CONFIG + the TPU twin.

    PS-topology jobs get the *sparse* cluster-spec variant for
    worker/evaluator replicas (SURVEY.md §2 "TF_CONFIG generation":
    the reference's sparse variant for PS-style jobs): parameter-server
    training never opens worker↔worker channels, so each worker sees
    the full chief/ps lists but only its own worker entry (as index 0,
    the TF sparse-cluster convention).  Chief and PS replicas keep the
    full view either way.
    """

    from tf_operator_tpu.api.types import ReplicaType
    from tf_operator_tpu.bootstrap.cluster_spec import gen_tf_config

    env: Dict[str, str] = {}
    if tf_config:
        has_ps = (
            ReplicaType.PS in job.spec.replica_specs
            and job.spec.pod_count(ReplicaType.PS) > 0
        )
        env["TF_CONFIG"] = gen_tf_config(
            job, rtype, index, resolve, sparse=has_ps
        )
    env.update(gen_tpu_env(job, rtype, index, resolve))
    return env
