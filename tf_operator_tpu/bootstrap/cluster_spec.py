"""TF_CONFIG generation — the reference's semantic crown jewel.

Parity: ``SetClusterSpec`` / ``genTFConfigJSONStr`` / ``genClusterSpec``
(SURVEY.md §2 "TF_CONFIG generation", expected upstream
``pkg/controller.v1/tensorflow/tensorflow.go``).  Produces per-pod JSON:

    {"cluster": {"chief": ["<job>-chief-0.<ns>.svc:2222"],
                 "ps": [...], "worker": [...]},
     "task": {"type": "worker", "index": 2},
     "environment": "cloud"}

Hostnames are the headless-service DNS names ``<job>-<type>-<idx>`` —
the naming contract shared with the service reconciler.  Address
resolution is pluggable so the local-process backend can substitute
``127.0.0.1:<port>`` for DNS names.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional

from tf_operator_tpu.api.types import (
    DEFAULT_PORT,
    DEFAULT_PORT_NAME,
    ReplicaType,
    TPUJob,
    replica_name,
)

#: maps (job, rtype, index, port) -> "host:port"
AddressResolver = Callable[[TPUJob, ReplicaType, int, int], str]


def dns_resolver(job: TPUJob, rtype: ReplicaType, index: int, port: int) -> str:
    """Cluster-DNS form: ``<job>-<type>-<idx>.<namespace>.svc:<port>``."""

    return f"{replica_name(job.metadata.name, rtype, index)}.{job.metadata.namespace}.svc:{port}"


def _replica_port(job: TPUJob, rtype: ReplicaType) -> int:
    spec = job.spec.replica_specs[rtype]
    main = spec.template.main_container()
    if main is not None:
        port = main.port_named(DEFAULT_PORT_NAME)
        if port is not None:
            return port.container_port
    return DEFAULT_PORT


def gen_cluster_spec(
    job: TPUJob, resolve: AddressResolver = dns_resolver
) -> Dict[str, List[str]]:
    """The ``cluster`` dict: every replica's stable address, by role."""

    cluster: Dict[str, List[str]] = {}
    for rtype in job.spec.ordered_types():
        port = _replica_port(job, rtype)
        cluster[rtype.lower_name] = [
            # pod_count: one entry per pod — multi-host slices list every
            # host (they each run one pod with a stable service name)
            resolve(job, rtype, i, port)
            for i in range(job.spec.pod_count(rtype))
        ]
    return cluster


def gen_tf_config(
    job: TPUJob,
    rtype: ReplicaType,
    index: int,
    resolve: AddressResolver = dns_resolver,
    sparse: bool = False,
) -> str:
    """The TF_CONFIG JSON string for one replica.

    ``sparse``: PS-style jobs don't need every worker to know every other
    worker — the sparse variant keeps the full PS/chief lists but trims
    the task's own role list to just this task (SURVEY.md §2 notes this
    as a reference variant for PS-style jobs; [U] detail).
    """

    native_cfg = _gen_tf_config_native(job, rtype, index, resolve, sparse)
    if native_cfg is not None:
        return native_cfg
    cluster = gen_cluster_spec(job, resolve)
    if sparse and rtype in (ReplicaType.WORKER, ReplicaType.EVALUATOR):
        own = cluster[rtype.lower_name][index]
        cluster[rtype.lower_name] = [own]
        task_index = 0
    else:
        task_index = index
    config = {
        "cluster": cluster,
        "task": {"type": rtype.lower_name, "index": task_index},
        "environment": "cloud",
    }
    return json.dumps(config, sort_keys=True)


def _gen_tf_config_native(
    job: TPUJob,
    rtype: ReplicaType,
    index: int,
    resolve: AddressResolver,
    sparse: bool,
) -> Optional[str]:
    """Native (C++) fast path: only for the DNS resolver, whose address
    format the native generator reproduces.  Returns None to fall back."""

    if resolve is not dns_resolver:
        return None
    try:
        from tf_operator_tpu.native import available, gen_tf_config_native
    except Exception:  # noqa: BLE001 - import cycle / build issues
        return None
    if not available():
        return None
    desc = ",".join(
        f"{t.lower_name}={job.spec.pod_count(t)}"
        f":{_replica_port(job, t)}"
        for t in job.spec.ordered_types()
    )
    try:
        return gen_tf_config_native(
            job.metadata.name,
            job.metadata.namespace,
            desc,
            rtype.lower_name,
            index,
            sparse,
        )
    except ValueError:
        return None


def coordinator_replica(job: TPUJob) -> Optional[ReplicaType]:
    """Which replica type hosts the coordinator: chief-like if present,
    else TPU slice, else worker (index 0 of whichever wins)."""

    for rtype in (
        ReplicaType.CHIEF,
        ReplicaType.MASTER,
        ReplicaType.TPU_SLICE,
        ReplicaType.WORKER,
    ):
        spec = job.spec.replica_specs.get(rtype)
        if spec is not None and int(spec.replicas or 0) > 0:
            return rtype
    return None
