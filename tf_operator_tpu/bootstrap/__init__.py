"""Cluster-bootstrap env injection (SURVEY.md §2 "TF_CONFIG generation").

Two payloads, one injection point (the reconciler's createNewPod):

- ``cluster_spec``: the reference-compatible ``TF_CONFIG`` JSON for
  TensorFlow distribution strategies.
- ``tpu_env``: the TPU-native twin — jax.distributed coordinator vars +
  megascale/libtpu multi-host vars so workloads bootstrap XLA collectives
  over ICI/DCN (SURVEY.md §2c).
"""

from tf_operator_tpu.bootstrap.cluster_spec import gen_cluster_spec, gen_tf_config  # noqa: F401
from tf_operator_tpu.bootstrap.tpu_env import gen_tpu_env  # noqa: F401
