"""Elastic autoscaler: the subsystem that ACTS on the alert engine.

PR 6 closed the observe→alert gap (utils/alerts.py fires on SLO burn,
stalls, queue depth); this module closes alert→act (ROADMAP item 3,
SURVEY.md §2b "Elastic" — the reference reserved replica-set
scale-in/out for v1.x).  A job declares ``spec.autoscaling`` policies
(api/types.AutoscalingPolicy) binding SIGNALS — registered alert rules
or gauge families — to one replica set each, and the autoscaler
evaluates them on a host-side loop:

- **serving** policies scale INTO pressure: any breaching signal
  (queue-wait burn rate firing, admission queue depth over threshold)
  adds ``step`` replicas up to ``max_replicas``; once every signal has
  been quiet for ``stabilization_seconds`` the policy sheds replicas
  back toward ``min_replicas``.  Serving replicas are stateless pool
  members behind a shared admission queue, so scale events touch only
  the new/removed indices.
- **training** policies scale AWAY from distress: a breaching signal
  (watchdog stalls, preemption) SHEDS replicas so the job re-shards
  onto the survivors — the reconciler restarts the whole replica set
  at the new world size (the size is baked into each pod's bootstrap
  env) and the training processes resume from the latest async
  checkpoint (parallel/checkpoint.restore_latest redistributes the
  artifact onto whatever mesh the survivors form —
  tests/test_elastic.py).  Sustained quiet grows the set back toward
  the spec's declared size.  EVERY training resize is gated by
  checkpoint freshness (``max_checkpoint_age_seconds``): a resize may
  only throw away work a sufficiently fresh checkpoint bounds, and an
  UNKNOWN age refuses the resize rather than guessing (skips are
  recorded and visible on ``GET /autoscaler``).

Anti-flap design (all three must agree before a decision lands):
``cooldown_seconds`` floors the time between decisions (both
directions); ``stabilization_seconds`` is temporal hysteresis — the
relief direction engages only after sustained quiet; gauge signals add
LEVEL hysteresis — a breached gauge stays latched until it drops to
``threshold * hysteresis_ratio``, so a level hovering at its threshold
cannot oscillate decisions.  Alert signals inherit the alert engine's
own dwell + resolved-hold absorption.

The autoscaler never edits the stored job spec: decisions land in an
in-memory **desired-replica overlay** the reconciler applies to its
working copy each sync (``apply()``), so the user's declaration stays
the baseline and an operator restart falls back to it.  Every decision
is visible three ways (the acceptance contract): a ``ScaledUp`` /
``ScaledDown`` Normal event on the job, an entry in the bounded
decision log served at ``GET /autoscaler``, and the
``observedHealth.autoscaler`` status block the health rollup
publishes.

Process-scope honesty (same contract as the alert engine, documented
in docs/ARCHITECTURE.md): gauge bindings and alert bindings read the
registry/engine of THIS process.  The checkpoint-freshness gate is the
exception — it prefers the job's summary series
(``checkpoint_time_unix``, republished pod-side by the trainer), which
crosses the process boundary, over the process-local gauge.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import (
    AutoscalingPolicy,
    ReplicaType,
    SignalBinding,
    TPUJob,
)
from tf_operator_tpu.utils.logging import FieldLogger, _root

#: decision log length — GET /autoscaler serves the tail, newest first
MAX_DECISIONS = 256

#: ISSUE 20: stock rules whose FIRING state vetoes every scale
#: decision (both modes, both directions) — scaling a recompiling or
#: regressing fleet treats a software problem with hardware.  Names
#: are pinned against utils/alerts.default_rules by
#: tests/test_autoscaling_lint.py; the refusal lands in last_skip +
#: autoscaler_skipped_total{reason="cost_plane"}.
COST_PLANE_VETO_RULES = ("compile-storm", "step-time-regression")


def default_serving_policy(
    min_replicas: int = 1, max_replicas: int = 4
) -> AutoscalingPolicy:
    """The stock serving policy (examples + the static lint gate):
    scale on the queue-wait burn-rate alert, blocks-free pressure, or
    a sustained preemption rate.  Since ISSUE 12 the paged pool
    reserves decode budget ON DEMAND, so ``kv_blocks_pressure`` is
    COMMITTED pressure — (blocks actually allocated + queued block
    demand) / usable, refreshed per decode window; the worst-case
    reservation the old scheme pinned is exported separately as
    ``kv_blocks_reserved`` and may exceed the arena (the
    oversubscription gamble).  Committed pressure is what admission
    really gates on, so the policy and the 0.9 alert act on real
    oversubscription, not the worst-case shadow.  Scale-up triggers
    at 0.85 (before the 0.9 alert pages); the ``serve-preemption-rate``
    alert binding adds the thrash signal — when the oversubscription
    gamble keeps losing (seats swapping through the host arena),
    replicas scale out BEFORE interactive TTFT burns.  Signal names
    here are pinned against the live rule set / emitted families by
    tests/test_autoscaling_lint.py — renaming either orphans this
    policy and fails tier-1."""

    return AutoscalingPolicy(
        replica_type=ReplicaType.WORKER,
        mode="serving",
        min_replicas=min_replicas,
        max_replicas=max_replicas,
        signals=[
            SignalBinding(kind="alert", name="serve-queue-wait-burn"),
            SignalBinding(
                kind="gauge", name="kv_blocks_pressure", threshold=0.85
            ),
            SignalBinding(kind="alert", name="serve-preemption-rate"),
        ],
    )


def default_disaggregated_policies(
    min_replicas: int = 1, max_replicas: int = 4
) -> List[AutoscalingPolicy]:
    """The stock DISAGGREGATED serving policy pair (ISSUE 13): a
    phase-split fleet runs two replica classes — prefill (mapped to
    the PS replica set: the auxiliary compute tier, never decodes) and
    decode (the WORKER set) — and each scales INDEPENDENTLY off its
    own slice of the same gauge, ``kv_blocks_pressure{role=}``.  A
    long-prompt burst saturates the prefill replicas' arenas without
    touching decode residency, so only the PS policy breaches; a
    residency pile-up (many long decodes) breaches only the WORKER
    policy.  The decode class keeps the unified policy's queue-wait
    burn + preemption-rate alert bindings (those SLOs are decode-side
    by construction).  Role label keys and thresholds are pinned by
    tests/test_autoscaling_lint.py like the unified stock policy."""

    return [
        AutoscalingPolicy(
            replica_type=ReplicaType.PS,
            mode="serving",
            min_replicas=min_replicas,
            max_replicas=max_replicas,
            signals=[
                SignalBinding(
                    kind="gauge", name="kv_blocks_pressure",
                    threshold=0.85, labels={"role": "prefill"},
                ),
            ],
        ),
        AutoscalingPolicy(
            replica_type=ReplicaType.WORKER,
            mode="serving",
            min_replicas=min_replicas,
            max_replicas=max_replicas,
            signals=[
                SignalBinding(
                    kind="gauge", name="kv_blocks_pressure",
                    threshold=0.85, labels={"role": "decode"},
                ),
                SignalBinding(kind="alert", name="serve-queue-wait-burn"),
                SignalBinding(kind="alert", name="serve-preemption-rate"),
            ],
        ),
    ]


def default_training_policy(
    min_replicas: int = 1, max_replicas: int = 8
) -> AutoscalingPolicy:
    """The stock training policy: shed replicas on sustained stalls
    (the watchdog rule dwells before firing), resize-gated on a fresh
    checkpoint."""

    return AutoscalingPolicy(
        replica_type=ReplicaType.WORKER,
        mode="training",
        min_replicas=min_replicas,
        max_replicas=max_replicas,
        signals=[SignalBinding(kind="alert", name="watchdog-stall")],
    )


def default_slice_training_policy(
    min_slices: int = 1, max_slices: int = 4
) -> AutoscalingPolicy:
    """The stock SLICE-topology training policy (ISSUE 14): the scaled
    unit is a whole TPU_SLICE replica — shedding one re-shards ``dp``
    onto the survivor slices (the slice-aware mesh keeps model axes on
    ICI at any slice count) and resumes from the async checkpoint, via
    exactly the PR 7 bounce the WORKER policy uses (the reconciler's
    ``_bounce_for_reshard`` is replica-type-generic, and the bounced
    pods' regenerated bootstrap env carries the survivor
    ``MEGASCALE_NUM_SLICES``).  Signals: the reconciler-set
    ``tpujob_gang_waiting_replicas`` gauge — nonzero while the job's
    gang group sits Pending, i.e. a capacity shrink revoked the grant
    and the declared slice count no longer fits the pool (the
    kubesim/fake ``/_capacity`` semantics) — plus the watchdog-stall
    alert for the slice that dies without returning capacity.  Every
    resize stays checkpoint-age gated.  Names pinned by
    tests/test_autoscaling_lint.py like the other stock policies."""

    return AutoscalingPolicy(
        replica_type=ReplicaType.TPU_SLICE,
        mode="training",
        min_replicas=min_slices,
        max_replicas=max_slices,
        signals=[
            SignalBinding(
                kind="gauge", name="tpujob_gang_waiting_replicas",
                threshold=0.0,
            ),
            SignalBinding(kind="alert", name="watchdog-stall"),
        ],
    )


def job_checkpoint_age(
    job: TPUJob, now: float, metrics=None, series=None
) -> Optional[float]:
    """Seconds since the job's newest durable checkpoint, or None
    (unknown).  Three sources, freshest wins within each tier:

    1. the POD-scope stamp in the job's summary series
       (``checkpoint_time_unix`` — utils/summaries, crosses the
       process boundary on disk);
    2. the FEDERATED ``checkpoint_last_success_unix{job=}`` series the
       telemetry scraper mirrors from each pod's /metrics (ISSUE 15 —
       the network path that closed the PR-6 process-scope gap: a
       wedged subprocess trainer's stale stamp now reaches the
       operator registry and drives the stock checkpoint-age rule);
    3. this process's own unlabeled gauge (embedded single-process
       runs, where checkpointer and operator share a registry).

    The job's newest stamp across its pods wins (the checkpoint is a
    job-global artifact; any pod reporting a fresh durable save means
    the job has one).  Shared by the reconciler's health rollup (which
    passes its already-read ``series`` tail to avoid a second disk
    read) and the autoscaler's resize gate so the two can never
    disagree."""

    from tf_operator_tpu.utils.summaries import (
        ANNOTATION_SUMMARY_DIR,
        latest_checkpoint_time,
    )

    sdir = job.metadata.annotations.get(ANNOTATION_SUMMARY_DIR)
    if sdir:
        try:
            t = latest_checkpoint_time(sdir, series=series)
        except OSError:
            t = None
        if t is not None:
            return max(0.0, now - t)
    if metrics is not None:
        best = 0.0
        for labels, v in metrics.gauge_series(
            "checkpoint_last_success_unix"
        ).items():
            d = dict(labels)
            # unlabeled = this process's own checkpointer; job-labeled
            # = federated from one of THIS job's pods (other jobs'
            # series must never gate this job's resize)
            if not d or d.get("job") == job.key:
                best = max(best, v)
        if best > 0:
            return max(0.0, now - best)
    return None


@dataclass
class ScalingDecision:
    """One applied scale decision — what the event, the /autoscaler
    log entry, and the observedHealth block all describe."""

    time: float
    job_key: str
    replica_type: ReplicaType
    mode: str
    direction: str  # "up" | "down"
    from_replicas: int
    to_replicas: int
    reason: str
    #: training resizes restart the replica set (re-shard + resume)
    reshard: bool = False
    signals: Dict[str, Any] = field(default_factory=dict)

    @property
    def event_reason(self) -> str:
        return "ScaledUp" if self.direction == "up" else "ScaledDown"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "time": round(self.time, 3),
            "job": self.job_key,
            "replicaType": self.replica_type.value,
            "mode": self.mode,
            "direction": self.direction,
            "from": self.from_replicas,
            "to": self.to_replicas,
            "reason": self.reason,
            "reshard": self.reshard,
            "signals": dict(self.signals),
        }


class _PolicyState:
    """Runtime state of one (job, replica-type) policy."""

    __slots__ = (
        "desired", "last_scale", "quiet_since", "breaching", "latched",
        "reshard_pending", "last_skip", "signals", "last_decision",
        "spec_replicas",
    )

    def __init__(self):
        #: the overlay; None = the spec governs
        self.desired: Optional[int] = None
        #: the STORED spec's replica count, recorded before any overlay
        #: (the reconciler's working copy is mutated by apply(), so the
        #: health block cannot read the baseline off the job later)
        self.spec_replicas: Optional[int] = None
        self.last_scale = 0.0
        #: unix since which every signal has been quiet (None while any
        #: breaches, or before the first evaluation)
        self.quiet_since: Optional[float] = None
        self.breaching = False
        #: per-gauge-signal hysteresis latch: name -> bool
        self.latched: Dict[str, bool] = {}
        #: a training resize decided but not yet executed by the
        #: reconciler (the replica-set bounce)
        self.reshard_pending = False
        #: last safety-gate refusal, for /autoscaler visibility
        self.last_skip: Optional[Dict[str, Any]] = None
        #: last measured signal values
        self.signals: Dict[str, Any] = {}
        self.last_decision: Optional[ScalingDecision] = None


class Autoscaler:
    """Evaluate every cached job's ``spec.autoscaling`` policies.

    ``evaluate_once(now)`` is the whole engine (tests drive it with a
    synthetic clock, the alert-engine pattern); ``start()`` runs it on
    a daemon thread every ``interval`` seconds.  The controller
    ``attach()``es a job lister and a decision callback; the
    reconciler consults ``apply()``/``take_reshard()`` during sync.
    """

    def __init__(
        self,
        metrics=None,
        alerts=None,
        interval: float = 5.0,
        max_decisions: int = MAX_DECISIONS,
    ):
        if metrics is None:
            from tf_operator_tpu.utils.metrics import default_metrics

            metrics = default_metrics
        self.metrics = metrics
        if alerts is None:
            from tf_operator_tpu.utils.alerts import default_engine

            alerts = default_engine
        #: utils/alerts.AlertEngine backing alert-kind signal bindings
        #: (set to None explicitly and they measure as unknown — never
        #: breaching, visible in the snapshot)
        self.alerts = alerts
        self.interval = float(interval)
        self._lock = threading.Lock()
        #: (job_key, ReplicaType) -> _PolicyState
        self._state: Dict[Tuple[str, ReplicaType], _PolicyState] = {}
        self._decisions: deque = deque(maxlen=max_decisions)
        self._callbacks: List[Callable[[ScalingDecision], None]] = []
        self._list_jobs: Optional[Callable[[], List[TPUJob]]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._log = FieldLogger(_root, component="autoscaler")

    # -- wiring -------------------------------------------------------------

    def attach(
        self,
        list_jobs: Callable[[], List[TPUJob]],
        on_decision: Optional[Callable[[ScalingDecision], None]] = None,
    ) -> None:
        """Wire the job source (the controller's informer cache) and an
        optional per-decision callback (the controller uses it to emit
        the Normal event and re-enqueue the job)."""

        with self._lock:
            self._list_jobs = list_jobs
            if on_decision is not None:
                self._callbacks.append(on_decision)

    def detach(
        self,
        list_jobs: Optional[Callable[[], List[TPUJob]]] = None,
        on_decision: Optional[Callable[[ScalingDecision], None]] = None,
    ) -> None:
        """Reverse of attach (controller shutdown): a long-lived
        (process-global) autoscaler must not pin dead controllers.
        ``list_jobs`` is the lister the caller installed — the lister
        is only cleared if it is still the active one, so a stopped
        controller can never sever a successor that re-attached."""

        with self._lock:
            if list_jobs is None or self._list_jobs is list_jobs:
                self._list_jobs = None
            if on_decision is not None:
                try:
                    self._callbacks.remove(on_decision)
                except ValueError:
                    pass

    def forget(self, job_key: str) -> None:
        """Drop all state for a deleted job — including its
        per-job gauge series (a deleted job must not keep exporting a
        desired replica count)."""

        with self._lock:
            for k in [k for k in self._state if k[0] == job_key]:
                del self._state[k]
        self.metrics.clear_gauge("autoscaler_desired_replicas", job=job_key)

    # -- reconciler surface -------------------------------------------------

    def apply(self, job: TPUJob) -> None:
        """Overlay desired replica counts onto ``job`` (the
        reconciler's per-sync working clone, never the stored object):
        downstream planning — pod create/scale-in, services, gang
        sizing, success evaluation — then sees one consistent world."""

        if job.spec.autoscaling is None:
            return
        for pol in job.spec.autoscaling.policies:
            spec = job.spec.replica_specs.get(pol.replica_type)
            if spec is None:
                continue
            with self._lock:
                st = self._state.get((job.key, pol.replica_type))
                if st is None:
                    continue
                # the pre-overlay value IS the stored spec's: remember
                # it for the health block (the mutated working copy
                # can't answer "what did the user declare" afterwards)
                st.spec_replicas = int(spec.replicas or 0)
                if st.desired is not None:
                    spec.replicas = st.desired

    def take_reshard(self, job_key: str) -> List[ReplicaType]:
        """Replica types with a decided-but-unexecuted training resize:
        the reconciler bounces their pods (delete all; next sync
        recreates at the new world size) and then ``consume_reshard``s.
        Peek-only — safe to call every sync."""

        with self._lock:
            return [
                rt
                for (jk, rt), st in self._state.items()
                if jk == job_key and st.reshard_pending
            ]

    def consume_reshard(self, job_key: str, rtype: ReplicaType) -> None:
        with self._lock:
            st = self._state.get((job_key, rtype))
            if st is not None:
                st.reshard_pending = False

    def health_block(self, job: TPUJob) -> Optional[Dict[str, Any]]:
        """The ``observedHealth.autoscaler`` sub-block for one job
        (JSON-shaped, round-trips through serde), or None when the job
        declares no autoscaling."""

        if job.spec.autoscaling is None:
            return None
        out: Dict[str, Any] = {}
        with self._lock:
            for pol in job.spec.autoscaling.policies:
                st = self._state.get((job.key, pol.replica_type))
                spec = job.spec.replica_specs.get(pol.replica_type)
                spec_replicas = (
                    st.spec_replicas
                    if st is not None and st.spec_replicas is not None
                    else int(spec.replicas or 0) if spec else 0
                )
                entry: Dict[str, Any] = {
                    "mode": pol.mode,
                    "desiredReplicas": (
                        st.desired
                        if st is not None and st.desired is not None
                        else spec_replicas
                    ),
                    "specReplicas": spec_replicas,
                    "minReplicas": pol.min_replicas,
                    "maxReplicas": pol.max_replicas,
                    "breaching": bool(st.breaching) if st else False,
                }
                if st is not None and st.last_decision is not None:
                    d = st.last_decision
                    entry["lastDecision"] = {
                        "direction": d.direction,
                        "to": d.to_replicas,
                        "time": round(d.time, 3),
                        "reason": d.reason,
                    }
                if st is not None and st.last_skip is not None:
                    entry["lastSkip"] = dict(st.last_skip)
                out[pol.replica_type.value] = entry
        return out

    # -- reads --------------------------------------------------------------

    def decisions(self) -> List[ScalingDecision]:
        with self._lock:
            return list(self._decisions)

    def snapshot(self) -> Dict[str, Any]:
        """The GET /autoscaler JSON body: per-policy live state
        (breaching first — the thing needing attention leads, the
        alerts-panel convention) plus the decision log newest first."""

        with self._lock:
            policies = []
            for (job_key, rtype), st in self._state.items():
                entry: Dict[str, Any] = {
                    "job": job_key,
                    "replicaType": rtype.value,
                    "desiredReplicas": st.desired,
                    "breaching": st.breaching,
                    "reshardPending": st.reshard_pending,
                    "signals": dict(st.signals),
                }
                if st.last_decision is not None:
                    entry["lastDecision"] = st.last_decision.to_dict()
                if st.last_skip is not None:
                    entry["lastSkip"] = dict(st.last_skip)
                policies.append(entry)
            decisions = [d.to_dict() for d in reversed(self._decisions)]
        policies.sort(key=lambda p: (not p["breaching"], p["job"], p["replicaType"]))
        return {"policies": policies, "decisions": decisions}

    # -- evaluation ---------------------------------------------------------

    def evaluate_once(self, now: Optional[float] = None) -> List[ScalingDecision]:
        """One sweep over every autoscaled job; returns the decisions
        issued this sweep."""

        now = time.time() if now is None else float(now)
        with self._lock:
            lister = self._list_jobs
        if lister is None:
            return []
        try:
            jobs = list(lister())
        except Exception as e:  # noqa: BLE001 - engine outlives cache bugs
            self._log.error("job lister failed: %s: %s", type(e).__name__, e)
            return []
        self.metrics.inc("autoscaler_evaluations_total")
        issued: List[ScalingDecision] = []
        live_keys = set()
        for job in jobs:
            if job.spec.autoscaling is None:
                continue
            live_keys.add(job.key)
            if job.invalid_reason or job.is_terminal():
                continue
            for pol in job.spec.autoscaling.policies:
                try:
                    d = self._evaluate_policy(job, pol, now)
                except Exception as e:  # noqa: BLE001 - one bad policy must not stop the sweep
                    self._log.error(
                        "policy evaluation failed for %s/%s: %s: %s",
                        job.key, pol.replica_type.value, type(e).__name__, e,
                    )
                    continue
                if d is not None:
                    issued.append(d)
        # GC state of jobs that no longer declare autoscaling (removed
        # block = revert to the declared spec and forget history) or
        # that the cache no longer knows
        with self._lock:
            stale = [k for k in self._state if k[0] not in live_keys]
            for k in stale:
                del self._state[k]
            callbacks = list(self._callbacks)
        for k in {jk for jk, _ in stale}:
            self.metrics.clear_gauge("autoscaler_desired_replicas", job=k)
        for d in issued:
            for fn in callbacks:
                try:
                    fn(d)
                except Exception as e:  # noqa: BLE001 - see AlertEngine.subscribe
                    self._log.error(
                        "decision callback failed for %s: %s: %s",
                        d.job_key, type(e).__name__, e,
                    )
        return issued

    def _evaluate_policy(
        self, job: TPUJob, pol: AutoscalingPolicy, now: float
    ) -> Optional[ScalingDecision]:
        key = (job.key, pol.replica_type)
        with self._lock:
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = _PolicyState()
        breach, values = self._measure_signals(pol, st)
        spec = job.spec.replica_specs.get(pol.replica_type)
        spec_replicas = int(spec.replicas or 0) if spec else 0
        st.spec_replicas = spec_replicas  # cache jobs are pre-overlay
        current = st.desired if st.desired is not None else spec_replicas

        st.breaching = breach
        st.signals = values

        # ISSUE 20 cost-plane gate: NO scale decision, either
        # direction, while the fleet is recompiling or regressing.
        # Scaling up a width-thrashing fleet multiplies the recompiles
        # onto fresh replicas (every new pod cold-compiles the same
        # thrash); scaling down during a step-time regression removes
        # capacity exactly when each replica delivers less of it.  Act
        # on the cause first — the refusal is recorded, never silent.
        veto = self._cost_plane_veto()
        if veto is not None:
            skip = {
                "time": round(now, 3),
                "wanted": None,
                "reason": f"scaling refused: {veto} firing (cost plane)",
            }
            if (
                st.last_skip is None
                or st.last_skip["reason"] != skip["reason"]
                or now - st.last_skip["time"] >= pol.cooldown_seconds
            ):
                self.metrics.inc(
                    "autoscaler_skipped_total", reason="cost_plane"
                )
                self._log.warning(
                    "autoscaler %s/%s: %s", job.key,
                    pol.replica_type.value, skip["reason"],
                )
                st.last_skip = skip
            return None

        decision: Optional[ScalingDecision] = None
        cooled = now - st.last_scale >= pol.cooldown_seconds
        if breach:
            st.quiet_since = None
            if pol.mode == "serving":
                target = min(current + pol.step, pol.max_replicas)
                if target > current and cooled:
                    decision = self._decide(
                        job, pol, st, now, current, target,
                        reason="scale-up: "
                        + ", ".join(sorted(n for n, v in values.items() if v.get("breaching"))),
                    )
            else:  # training: shed toward min, re-shard onto survivors
                target = max(current - pol.step, pol.min_replicas)
                if target < current and cooled:
                    decision = self._gated_resize(
                        job, pol, st, now, current, target,
                        reason="distress scale-down: "
                        + ", ".join(sorted(n for n, v in values.items() if v.get("breaching"))),
                    )
        else:
            if st.quiet_since is None:
                st.quiet_since = now
            stabilized = now - st.quiet_since >= pol.stabilization_seconds
            if pol.mode == "serving":
                target = max(current - pol.step, pol.min_replicas)
                if target < current and stabilized and cooled:
                    decision = self._decide(
                        job, pol, st, now, current, target,
                        reason=f"signals quiet {now - st.quiet_since:.0f}s",
                    )
            else:  # training: recover toward the declared size
                baseline = min(spec_replicas, pol.max_replicas)
                target = min(current + pol.step, baseline)
                if target > current and stabilized and cooled:
                    decision = self._gated_resize(
                        job, pol, st, now, current, target,
                        reason="capacity recovered: signals quiet "
                        f"{now - st.quiet_since:.0f}s",
                    )
        return decision

    def _gated_resize(
        self, job, pol, st, now: float, current: int, target: int, reason: str
    ) -> Optional[ScalingDecision]:
        """Training resizes pass the checkpoint-freshness gate first: a
        re-shard resumes from the latest checkpoint, so the resize may
        only discard work the checkpoint bounds.  Unknown age = refuse
        (recorded, never silent)."""

        age = job_checkpoint_age(job, now, metrics=self.metrics)
        if age is None or age > pol.max_checkpoint_age_seconds:
            why = (
                "checkpoint age unknown"
                if age is None
                else f"checkpoint {age:.0f}s old (> {pol.max_checkpoint_age_seconds:g}s)"
            )
            skip = {
                "time": round(now, 3),
                "wanted": target,
                "reason": f"resize refused: {why}",
            }
            # log/count at most once per cooldown window — the gate can
            # refuse every tick for as long as the checkpoint is stale
            if st.last_skip is None or now - st.last_skip["time"] >= pol.cooldown_seconds:
                self.metrics.inc(
                    "autoscaler_skipped_total", reason="checkpoint_stale"
                )
                self._log.warning(
                    "autoscaler %s/%s: %s", job.key,
                    pol.replica_type.value, skip["reason"],
                )
                st.last_skip = skip
            else:
                st.last_skip = {**st.last_skip, "wanted": target}
            return None
        st.last_skip = None
        return self._decide(
            job, pol, st, now, current, target,
            reason=f"{reason} (checkpoint {age:.0f}s fresh)", reshard=True,
        )

    def _decide(
        self, job, pol, st, now: float, current: int, target: int,
        reason: str, reshard: bool = False,
    ) -> ScalingDecision:
        d = ScalingDecision(
            time=now,
            job_key=job.key,
            replica_type=pol.replica_type,
            mode=pol.mode,
            direction="up" if target > current else "down",
            from_replicas=current,
            to_replicas=target,
            reason=reason,
            reshard=reshard,
            signals=dict(st.signals),
        )
        with self._lock:
            st.desired = target
            st.last_scale = now
            st.last_decision = d
            if reshard:
                st.reshard_pending = True
            self._decisions.append(d)
        self.metrics.inc("autoscaler_decisions_total", direction=d.direction)
        self.metrics.set(
            "autoscaler_desired_replicas",
            float(target),
            job=job.key,
            replicaType=pol.replica_type.value,
        )
        self._log.info(
            "autoscaler %s/%s: %s %d -> %d (%s)",
            job.key, pol.replica_type.value, d.direction,
            current, target, reason,
        )
        return d

    # -- signal measurement -------------------------------------------------

    def _measure_signals(
        self, pol: AutoscalingPolicy, st: _PolicyState
    ) -> Tuple[bool, Dict[str, Any]]:
        """(any_breaching, {signal name: measured}) — gauge signals
        carry the hysteresis latch in ``st.latched``."""

        any_breach = False
        values: Dict[str, Any] = {}
        for sig in pol.signals:
            if sig.kind == "alert":
                breach, meas = self._measure_alert(sig)
            else:
                breach, meas = self._measure_gauge(sig, pol, st)
            values[self._signal_key(sig)] = {**meas, "breaching": breach}
            any_breach = any_breach or breach
        return any_breach, values

    @staticmethod
    def _signal_key(sig: SignalBinding) -> str:
        """The binding's identity in signal maps AND the hysteresis
        latch (ISSUE 13): label-filtered gauge bindings (the
        disaggregated policies slice one family by {role=}) carry the
        filter — ``kv_blocks_pressure{role=prefill}`` — so the
        decision reason and /autoscaler name WHICH slice breached,
        and two filtered bindings on one family in one policy can
        never collide in the values map or share a latch."""

        if sig.kind == "alert" or not sig.labels:
            return sig.name
        return sig.name + "{" + ",".join(
            f"{k}={v}" for k, v in sorted(sig.labels.items())
        ) + "}"

    def _cost_plane_veto(self) -> Optional[str]:
        """The name of a firing COST_PLANE_VETO_RULES alert, or None.
        No engine attached = no veto (a metrics-only autoscaler keeps
        its legacy behavior; the stock operator wiring always attaches
        one)."""

        if self.alerts is None:
            return None
        for name in COST_PLANE_VETO_RULES:
            alert = self.alerts.alert(name)
            if alert is not None and alert.state == "firing":
                return name
        return None

    def _measure_alert(self, sig: SignalBinding) -> Tuple[bool, Dict[str, Any]]:
        if self.alerts is None:
            return False, {"kind": "alert", "unknown": True}
        alert = self.alerts.alert(sig.name)
        if alert is None:
            # bound to a rule the engine does not run: never breaches,
            # but the snapshot says so instead of looking healthy —
            # the runtime twin of the static lint gate
            return False, {"kind": "alert", "unknown": True}
        return alert.state == "firing", {"kind": "alert", "state": alert.state}

    def _measure_gauge(
        self, sig: SignalBinding, pol: AutoscalingPolicy, st: _PolicyState
    ) -> Tuple[bool, Dict[str, Any]]:
        series = self.metrics.gauge_series(sig.name)
        level = 0.0
        for labels, v in series.items():
            d = dict(labels)
            if all(d.get(k) == str(val) for k, val in sig.labels.items()):
                level = max(level, v)
        key = self._signal_key(sig)
        latched = st.latched.get(key, False)
        if level > sig.threshold:
            latched = True
        elif level <= sig.threshold * pol.hysteresis_ratio:
            latched = False
        # between the release level and the threshold: hold the latch
        st.latched[key] = latched
        return latched, {
            "kind": "gauge",
            "level": round(level, 3),
            "threshold": sig.threshold,
        }

    # -- evaluator thread ---------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Autoscaler":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="autoscaler"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.evaluate_once()
            except Exception as e:  # noqa: BLE001 - must outlive bugs
                self._log.error(
                    "autoscaler sweep failed: %s: %s", type(e).__name__, e
                )


#: process-global default (the metrics/tracer/alerts pattern): kubesim's
#: /autoscaler debug route and the operator binary share this instance.
#: NOT started, and inert until a controller attach()es its job cache.
default_autoscaler = Autoscaler()
