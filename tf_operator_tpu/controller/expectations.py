"""Expectations: informer-race bookkeeping.

Parity: ``ControllerExpectations`` from the reference's job-controller
runtime (SURVEY.md §2 "Generic job-controller runtime", §5 "Race
detection") — *the* race-correctness core.  After the controller issues N
creates / M deletes for a job, the informer cache won't reflect them until
watch events arrive; syncing again in that window would double-create.
The controller therefore records "I expect N adds and M deletes for key
K"; observed watch events lower the counters; a sync only trusts the cache
once expectations are satisfied (or expired).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

#: Parity with client-go's ExpectationsTimeout (5 min): after this long an
#: unsatisfied expectation is assumed lost (dropped watch) and the sync
#: proceeds from observed state — the self-healing path.
EXPECTATION_TIMEOUT_S = 300.0


@dataclass
class _Expectation:
    adds: int = 0
    deletes: int = 0
    timestamp: float = field(default_factory=time.monotonic)


class Expectations:
    def __init__(self, timeout_s: float = EXPECTATION_TIMEOUT_S):
        self._lock = threading.Lock()
        self._by_key: dict = {}
        self.timeout_s = timeout_s

    def expect_creations(self, key: str, n: int) -> None:
        with self._lock:
            e = self._by_key.setdefault(key, _Expectation())
            e.adds += n
            e.timestamp = time.monotonic()

    def expect_deletions(self, key: str, n: int) -> None:
        with self._lock:
            e = self._by_key.setdefault(key, _Expectation())
            e.deletes += n
            e.timestamp = time.monotonic()

    def creation_observed(self, key: str) -> None:
        with self._lock:
            e = self._by_key.get(key)
            if e is not None and e.adds > 0:
                e.adds -= 1

    def deletion_observed(self, key: str) -> None:
        with self._lock:
            e = self._by_key.get(key)
            if e is not None and e.deletes > 0:
                e.deletes -= 1

    def satisfied(self, key: str) -> bool:
        """True when the cache can be trusted for this key."""

        with self._lock:
            e = self._by_key.get(key)
            if e is None:
                return True
            if e.adds <= 0 and e.deletes <= 0:
                return True
            if time.monotonic() - e.timestamp > self.timeout_s:
                return True  # expired: assume events lost, resync from state
            return False

    def delete(self, key: str) -> None:
        with self._lock:
            self._by_key.pop(key, None)

    def pending(self, key: str):
        with self._lock:
            e = self._by_key.get(key)
            return (0, 0) if e is None else (e.adds, e.deletes)
