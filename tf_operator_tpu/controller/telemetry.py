"""Operator-side fleet telemetry: scrape pods, federate families,
stitch traces (ISSUE 15, the plane's operator half).

The pod-side exporter (runtime/telemetry.py) makes every
reconciler-launched worker scrapable; this module makes the operator
USE that: a :class:`TelemetryScraper` (own daemon thread, watchdog-
patterned start/stop, synthetic-clock drivable like AlertEngine /
Autoscaler) discovers scrape targets from live pod records (the
``tpujob.dist/telemetry-port`` annotation the reconciler stamps), pulls
each pod's exposition through ``backend/retry.RetryPolicy`` with
bounded timeouts, and merges the samples into FEDERATED families in the
shared registry, decorated ``{job, replica_type, replica_index,
slice}`` (``FEDERATED_LABELS`` — the lint gates pin the tuple):

- **gauges** are instantaneous — last scrape wins (``Metrics.set``);
- **counters** accumulate deltas: the scraper tracks each series'
  previous cumulative value and adds the increase since the last
  scrape (a value DROP is a pod restart and contributes the new
  absolute), keeping the operator counter MONOTONE — equal to the
  pod's cumulative total until a restart, and to the sum of every
  incarnation's contributions after one, which is exactly what the
  ``counter_increase`` alert windows need;
- **histograms** are bucket-summed: per-bucket deltas merge through
  ``Metrics.merge_histogram`` into labeled series the existing
  ``histogram_family_merged`` machinery then collapses into fleet
  quantiles.

Staleness honesty (the satellite contract): every scrape failure
increments ``telemetry_scrape_failures_total{job,replica}``, every
sweep refreshes the per-target ``telemetry_scrape_age_seconds`` gauge,
and a target unreachable (or gone from the pod records) past
``stale_after`` has its federated series SWEPT from the registry
(``clear_gauge``-family forget semantics) instead of exporting frozen
values.  Scraping runs on its own thread against the informer cache's
pod snapshots — it never blocks a reconcile sync.

Trace stitching: each scrape also pulls ``GET /traces`` (JSONL) and
folds unseen spans into the operator TraceStore
(``TraceStore.add_dict``).  Because the harness rooted the pod's train
trace under the reconciler's ``pod.create`` span context (the env
contract in bootstrap/tpu_env.py), ``GET /traces/<id>`` then shows ONE
vertical reconcile→boot→train waterfall.
"""

from __future__ import annotations

import socket
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from tf_operator_tpu.api.types import ANNOTATION_TELEMETRY_PORT
from tf_operator_tpu.backend.retry import RetryPolicy
from tf_operator_tpu.utils.logging import FieldLogger, _root

#: the federated decoration, in exposition order — every series merged
#: from a pod carries exactly these keys on top of its own labels.
#: tests/test_alert_rules_lint.py pins this tuple against the merge
#: call sites, so a renamed key fails tier-1 before it orphans a
#: rule/policy/dashboard binding.
FEDERATED_LABELS = ("job", "replica_type", "replica_index", "slice")

#: per-target cap on remembered span ids (trace-folding dedup ring)
MAX_SEEN_SPANS = 4096


def alloc_telemetry_port(host: str = "127.0.0.1") -> int:
    """One free TCP port, OS-assigned — the reconciler calls this at
    pod create and injects the result as ``TPUJOB_TELEMETRY_PORT``.
    (Tiny race window between close and the pod's bind; acceptable for
    the sim/local backends this repo runs — a real cluster would use
    the pod IP and a FIXED port instead.)"""

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


def _unescape(value: str) -> str:
    out, i = [], 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(raw: str) -> Dict[str, str]:
    """``k="v",k2="v2"`` (text-exposition escaped) -> dict."""

    labels: Dict[str, str] = {}
    i = 0
    while i < len(raw):
        eq = raw.find("=", i)
        if eq < 0:
            break
        key = raw[i:eq].strip().lstrip(",").strip()
        j = eq + 2  # past ="
        val = []
        while j < len(raw):
            c = raw[j]
            if c == "\\" and j + 1 < len(raw):
                val.append(raw[j:j + 2])
                j += 2
                continue
            if c == '"':
                break
            val.append(c)
            j += 1
        labels[key] = _unescape("".join(val))
        i = j + 1
    return labels


#: parsed exposition shape: {(family, labels-tuple): value} per kind,
#: histograms as {(family, labels-tuple): (buckets, counts, sum, count)}
#: with PER-BUCKET (de-cumulated) counts
Parsed = Dict[str, Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Any]]


def parse_exposition(text: str) -> Parsed:
    """Parse the Prometheus text format ``utils/metrics.exposition``
    emits back into structured samples — the scrape-side inverse.
    Unknown/ill-formed lines are skipped (a half-written exposition
    must degrade, not crash the sweep)."""

    kinds: Dict[str, str] = {}
    counters: Dict[Tuple[str, Tuple], float] = {}
    gauges: Dict[Tuple[str, Tuple], float] = {}
    #: (family, labels) -> {"buckets": [(le, cum)], "sum": x, "count": n}
    hist_raw: Dict[Tuple[str, Tuple], Dict[str, Any]] = {}

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                continue
            name = line[:brace]
            labels = _parse_labels(line[brace + 1:close])
            rest = line[close + 1:].strip()
        else:
            bits = line.split()
            if len(bits) != 2:
                continue
            name, rest = bits[0], bits[1]
            labels = {}
        try:
            value = float(rest)
        except ValueError:
            continue
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and kinds.get(name[: -len(suffix)]) == "histogram":
                base = name[: -len(suffix)]
                part = suffix[1:]
                break
        if base is not None:
            le = labels.pop("le", None)
            key = (base, tuple(sorted(labels.items())))
            h = hist_raw.setdefault(
                key, {"buckets": [], "sum": 0.0, "count": 0}
            )
            if part == "bucket":
                if le is not None and le != "+Inf":
                    try:
                        h["buckets"].append((float(le), value))
                    except ValueError:
                        pass
            elif part == "sum":
                h["sum"] = value
            else:
                h["count"] = int(value)
            continue
        kind = kinds.get(name)
        key = (name, tuple(sorted(labels.items())))
        if kind == "counter":
            counters[key] = value
        elif kind == "gauge":
            gauges[key] = value
        # summaries (raw observe()) are not federated: unbounded
        # per-observation lists don't survive a scrape contract

    histograms: Dict[Tuple[str, Tuple], Tuple] = {}
    for key, h in hist_raw.items():
        bounds = [b for b, _ in sorted(h["buckets"])]
        cums = [c for _, c in sorted(h["buckets"])]
        counts: List[int] = []
        prev = 0.0
        for c in cums:
            counts.append(int(c - prev))
            prev = c
        counts.append(int(h["count"] - prev))  # +Inf bucket
        histograms[key] = (tuple(bounds), counts, h["sum"], h["count"])
    return {"counters": counters, "gauges": gauges, "histograms": histograms}


@dataclass
class ScrapeTarget:
    """One discovered pod exporter."""

    job: str  # "<ns>/<name>" — the per-object gauge key convention
    replica_type: str
    replica_index: int
    slice_id: str  # "" outside multi-slice topologies
    url: str

    @property
    def key(self) -> Tuple[str, str, int]:
        return (self.job, self.replica_type, self.replica_index)

    @property
    def replica(self) -> str:
        return f"{self.replica_type}-{self.replica_index}"

    @property
    def labels(self) -> Dict[str, str]:
        return {
            "job": self.job,
            "replica_type": self.replica_type,
            "replica_index": str(self.replica_index),
            "slice": self.slice_id,
        }


class _TargetState:
    __slots__ = (
        "target", "first_seen", "last_ok", "last_counters",
        "last_histograms", "families", "failures", "seen_spans",
        "seen_ring", "swept",
    )

    def __init__(self, target: ScrapeTarget, now: float):
        self.target = target
        self.first_seen = now
        #: unix of the last successful scrape (0 = never reached)
        self.last_ok = 0.0
        #: previous cumulative counter values, for delta federation
        self.last_counters: Dict[Tuple[str, Tuple], float] = {}
        self.last_histograms: Dict[Tuple[str, Tuple], Tuple] = {}
        #: every (kind, family) this target federated — the sweep list
        self.families: Set[Tuple[str, str]] = set()
        self.failures = 0
        self.seen_spans: Set[str] = set()
        self.seen_ring: deque = deque(maxlen=MAX_SEEN_SPANS)
        self.swept = False


def pods_to_targets(pods) -> List[ScrapeTarget]:
    """Scrape targets from live pod records: a RUNNING pod stamped
    with the telemetry-port annotation is scrapable.  The slice label
    comes from the pod's own MEGASCALE_SLICE_ID env (the ISSUE-14
    injection contract) so federated series carry the DCN topology."""

    out: List[ScrapeTarget] = []
    for pod in pods:
        phase = getattr(pod.phase, "value", str(pod.phase))
        if phase != "Running":
            continue
        port = (pod.metadata.annotations or {}).get(ANNOTATION_TELEMETRY_PORT)
        if not port or not str(port).isdigit():
            continue
        rtype = pod.replica_type
        idx = pod.replica_index
        if rtype is None or idx is None:
            continue
        slice_id = ""
        for c in pod.containers:
            slice_id = (c.env or {}).get("MEGASCALE_SLICE_ID", "")
            break
        out.append(
            ScrapeTarget(
                job=f"{pod.metadata.namespace}/{pod.job_name}",
                replica_type=rtype.lower_name,
                replica_index=idx,
                slice_id=slice_id,
                url=f"http://127.0.0.1:{int(port)}",
            )
        )
    return out


class TelemetryScraper:
    """Pull pod expositions, federate them into the shared registry.

    ``scrape_once(now)`` is the whole engine (tests drive it with a
    synthetic clock — the AlertEngine/Autoscaler pattern); ``start()``
    runs it on a daemon thread every ``interval`` seconds.  The
    controller ``attach()``es a pod lister (its informer cache);
    nothing here ever runs inside a reconcile sync.
    """

    def __init__(
        self,
        metrics=None,
        tracer=None,
        interval: float = 2.0,
        timeout: float = 2.0,
        stale_after: float = 30.0,
        retry: Optional[RetryPolicy] = None,
    ):
        if metrics is None:
            from tf_operator_tpu.utils.metrics import default_metrics

            metrics = default_metrics
        if tracer is None:
            from tf_operator_tpu.utils.trace import default_tracer

            tracer = default_tracer
        self.metrics = metrics
        self.tracer = tracer
        self.interval = float(interval)
        self.timeout = float(timeout)
        #: a target silent this long has its federated series swept
        self.stale_after = float(stale_after)
        #: bounded per-scrape budget: ONE quick retry, tight deadline —
        #: a fleet sweep must stay cheap even when half the fleet died
        self.retry = retry or RetryPolicy(
            max_attempts=2, base_delay=0.05, max_delay=0.2, deadline=5.0
        )
        self._lock = threading.Lock()
        self._targets: Dict[Tuple[str, str, int], _TargetState] = {}
        self._list_pods: Optional[Callable[[], list]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._log = FieldLogger(_root, component="telemetry")
        #: every (kind, family) EVER federated — the /federate read set
        self._federated: Set[Tuple[str, str]] = set()

    # -- wiring -------------------------------------------------------------

    def attach(self, list_pods: Callable[[], list]) -> None:
        """Wire the pod source (the controller's informer cache
        snapshot — read-only, never blocks a sync)."""

        with self._lock:
            self._list_pods = list_pods

    def detach(self, list_pods: Optional[Callable[[], list]] = None) -> None:
        with self._lock:
            # == not `is`: bound methods are re-minted per access, so
            # identity would never match the method attach() stored
            if list_pods is None or self._list_pods == list_pods:
                self._list_pods = None

    # -- one sweep ----------------------------------------------------------

    def scrape_once(self, now: Optional[float] = None) -> int:
        """Discover targets, scrape each, federate, sweep staleness.
        Returns the number of successful scrapes this sweep."""

        now = time.time() if now is None else float(now)
        with self._lock:
            lister = self._list_pods
        pods = []
        if lister is not None:
            try:
                pods = list(lister())
            except Exception as e:  # noqa: BLE001 - outlives cache bugs
                self._log.error(
                    "pod lister failed: %s: %s", type(e).__name__, e
                )
        live = {}
        for t in pods_to_targets(pods):
            live[t.key] = t
        replaced: List[_TargetState] = []
        with self._lock:
            for key, t in live.items():
                st = self._targets.get(key)
                if st is None or st.target.url != t.url:
                    # new pod (or the index was recreated on a new
                    # port): fresh state — counter baselines reset.
                    # The OLD state's federated series must be cleared
                    # first, or the recreated pod's counters (re-seeded
                    # at their new absolute) would STACK onto the dead
                    # pod's last-seen values under the same labels.
                    if st is not None and not st.swept:
                        replaced.append(st)
                    self._targets[key] = _TargetState(t, now)
                else:
                    st.target = t
            states = list(self._targets.values())
        for st in replaced:
            self._clear_target(st)

        ok = 0
        for st in states:
            if st.target.key in live:
                if self._scrape_target(st, now):
                    ok += 1
            self._refresh_age(st, now)
        self._sweep_stale(now, live)
        return ok

    def _fetch(self, url: str) -> str:
        timeout = self.timeout

        def _do():
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return resp.read().decode("utf-8", errors="replace")

        return self.retry.call(_do, client="telemetry", metrics=self.metrics)

    def _scrape_target(self, st: _TargetState, now: float) -> bool:
        t = st.target
        try:
            text = self._fetch(t.url + "/metrics")
            parsed = parse_exposition(text)
        except Exception as e:  # noqa: BLE001 - a dead pod is data, not a crash
            st.failures += 1
            # the literal call site the lint collectors pin: scrape
            # failures are first-class observable, per job and replica
            self.metrics.inc(
                "telemetry_scrape_failures_total",
                job=t.job, replica=t.replica,
            )
            self._log.debug(
                "scrape failed for %s %s: %s: %s",
                t.job, t.replica, type(e).__name__, e,
            )
            return False
        self._merge(st, parsed, now)
        # trace stitching is best-effort and separately fallible: a pod
        # whose /traces hangs must not mark its metrics scrape failed —
        # but the miss is counted, never silent
        try:
            self._fold_traces(st, self._fetch(t.url + "/traces"))
        except Exception as e:  # noqa: BLE001 - stitching is optional
            self.metrics.inc(
                "telemetry_trace_fold_failures_total",
                job=t.job, replica=t.replica,
            )
            self._log.debug(
                "trace fold failed for %s %s: %s: %s",
                t.job, t.replica, type(e).__name__, e,
            )
        st.last_ok = now
        st.swept = False
        return True

    # -- federation ---------------------------------------------------------

    def _merge(self, st: _TargetState, parsed: Parsed, now: float) -> None:
        fed = st.target.labels
        for (name, labels), value in parsed["gauges"].items():
            merged = {**dict(labels), **fed}
            self.metrics.set(name, value, **merged)
            st.families.add(("gauge", name))
        for (name, labels), value in parsed["counters"].items():
            prev = st.last_counters.get((name, labels), 0.0)
            delta = value - prev if value >= prev else value  # pod restart
            if delta:
                merged = {**dict(labels), **fed}
                self.metrics.inc(name, delta, **merged)
            st.last_counters[(name, labels)] = value
            st.families.add(("counter", name))
        for (name, labels), (bks, counts, total, n) in parsed[
            "histograms"
        ].items():
            prev = st.last_histograms.get((name, labels))
            if prev is not None and prev[0] == bks and prev[3] <= n:
                d_counts = [a - b for a, b in zip(counts, prev[1])]
                d_sum, d_n = total - prev[2], n - prev[3]
            else:  # first scrape, pod restart, or re-bucketed family
                d_counts, d_sum, d_n = list(counts), total, n
            if d_n:
                merged = {**dict(labels), **fed}
                self.metrics.merge_histogram(
                    name, bks, d_counts, d_sum, d_n, **merged
                )
            st.last_histograms[(name, labels)] = (bks, counts, total, n)
            st.families.add(("histogram", name))
        with self._lock:
            self._federated |= st.families

    def _fold_traces(self, st: _TargetState, jsonl: str) -> None:
        import json

        store = self.tracer.store
        for line in jsonl.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue
            sid = d.get("spanId")
            if not sid or sid in st.seen_spans:
                continue
            if len(st.seen_ring) == st.seen_ring.maxlen:
                st.seen_spans.discard(st.seen_ring[0])
            st.seen_ring.append(sid)
            st.seen_spans.add(sid)
            store.add_dict(d)

    # -- staleness ----------------------------------------------------------

    def _refresh_age(self, st: _TargetState, now: float) -> None:
        t = st.target
        # never-reached targets age from discovery: the gauge is
        # "seconds since this pod last proved it was alive"
        age = now - (st.last_ok or st.first_seen)
        # the literal per-target age call site the lint collectors pin
        self.metrics.set(
            "telemetry_scrape_age_seconds",
            round(max(age, 0.0), 3),
            job=t.job, replica_type=t.replica_type,
            replica_index=str(t.replica_index), slice=t.slice_id,
        )

    def _sweep_stale(self, now: float, live: Dict) -> None:
        """TTL GC: a target unreachable (or no longer backed by a live
        pod record) past ``stale_after`` has every federated series it
        contributed cleared — frozen telemetry is worse than absent
        telemetry."""

        with self._lock:
            states = list(self._targets.items())
        for key, st in states:
            gone = key not in live
            last_sign = st.last_ok or st.first_seen
            silent = now - last_sign > self.stale_after
            if not silent:
                continue
            if not st.swept:
                self._clear_target(st)
                st.swept = True
            if gone:
                with self._lock:
                    self._targets.pop(key, None)

    def _clear_target(self, st: _TargetState) -> None:
        t = st.target
        fed = t.labels
        for kind, name in sorted(st.families):
            if kind == "gauge":
                self.metrics.clear_gauge(name, **fed)
            elif kind == "counter":
                self.metrics.clear_counter(name, **fed)
            else:
                self.metrics.clear_histogram(name, **fed)
        self.metrics.clear_gauge(
            "telemetry_scrape_age_seconds",
            job=t.job, replica_type=t.replica_type,
            replica_index=str(t.replica_index),
        )
        st.last_counters.clear()
        st.last_histograms.clear()
        self._log.info(
            "swept stale federated series for %s %s", t.job, t.replica
        )

    # -- reads --------------------------------------------------------------

    def federate_text(self) -> str:
        """The ``GET /federate`` body: every federated family (plus the
        scrape meta families), rendered by the ONE exposition renderer
        (``Metrics.exposition(families=...)``) restricted to the
        federated name set — the Prometheus federation contract, with
        no second format to drift."""

        with self._lock:
            names = {name for _, name in self._federated}
        names.add("telemetry_scrape_failures_total")
        names.add("telemetry_scrape_age_seconds")
        return self.metrics.exposition(families=names)

    def targets_snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /federate/targets`` JSON body: per-target scrape
        state, STALE-FIRST (the thing needing attention leads — the
        alerts-panel convention), then by age descending."""

        now = time.time() if now is None else float(now)
        with self._lock:
            states = list(self._targets.values())
            fams = sorted(n for _, n in self._federated)
        rows = []
        for st in states:
            t = st.target
            age = round(now - st.last_ok, 3) if st.last_ok else None
            rows.append({
                "job": t.job,
                "replica": t.replica,
                "replicaType": t.replica_type,
                "replicaIndex": t.replica_index,
                "slice": t.slice_id,
                "url": t.url,
                "lastScrapeAgeSeconds": age,
                "failures": st.failures,
                "stale": bool(
                    st.swept
                    or st.last_ok == 0.0
                    or now - st.last_ok > self.stale_after
                ),
            })
        rows.sort(
            key=lambda r: (
                not r["stale"],
                -(r["lastScrapeAgeSeconds"] or float("inf")),
                r["job"], r["replica"],
            )
        )
        return {"targets": rows, "families": fams}

    def job_rows(self, job_key: str, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Per-pod health rows for one job — the reconciler folds these
        into ``observedHealth.pods`` so ``tpujob describe`` shows the
        fleet, not just the operator's own aggregates."""

        now = time.time() if now is None else float(now)
        with self._lock:
            states = [
                st for st in self._targets.values()
                if st.target.job == job_key
            ]
        rows = []
        for st in states:
            t = st.target
            row: Dict[str, Any] = {
                "replica": t.replica,
                "stale": bool(st.swept or st.last_ok == 0.0),
                "failures": st.failures,
            }
            if st.last_ok:
                row["scrapeAgeSeconds"] = round(now - st.last_ok, 1)
            tput = self.metrics.gauge(
                "train_window_steps_per_second", **t.labels
            )
            if tput:
                row["stepsPerSec"] = round(tput, 3)
            rows.append(row)
        rows.sort(key=lambda r: r["replica"])
        return rows

    # -- scraper thread -----------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "TelemetryScraper":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="telemetry-scraper"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scrape_once()
            except Exception as e:  # noqa: BLE001 - must outlive bugs
                self._log.error(
                    "telemetry sweep failed: %s: %s", type(e).__name__, e
                )


#: process-global default (the metrics/tracer/alerts/autoscaler
#: pattern): the operator binary and the API's /federate route share
#: this instance.  NOT started, and inert until a controller
#: attach()es its pod cache.
default_scraper = TelemetryScraper()
