"""Informer caches: local read models fed by watch events.

Parity: client-go SharedInformer caches + the reference's event handlers
(SURVEY.md §2 "Job lifecycle hooks": addTFJob/updateTFJob/enqueueTFJob and
pod/service handlers routed via owner refs).  The reconciler reads ONLY
from these caches (never the backend directly), exactly like the
reference reads listers — which is what makes the Expectations race real
and testable with the fake backend's manual delivery mode.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from tf_operator_tpu.api.types import LABEL_JOB_NAME, TPUJob
from tf_operator_tpu.backend.base import match_selector
from tf_operator_tpu.backend.objects import (
    Pod,
    PodGroup,
    Service,
    WatchEvent,
    WatchEventType,
)
from tf_operator_tpu.controller.expectations import Expectations


class InformerCache:
    """Caches for every kind + enqueue/expectation hooks.

    Wire it to a backend and a job store with ``subscribe``; hand
    ``enqueue`` a callable taking a job key.
    """

    def __init__(
        self,
        enqueue: Callable[[str], None],
        pod_expectations: Expectations,
        service_expectations: Expectations,
    ):
        self._lock = threading.RLock()
        self._enqueue = enqueue
        self._pod_exp = pod_expectations
        self._svc_exp = service_expectations
        #: monotonic count of watch events applied — resync uses it to
        #: detect (and abort on) events that interleaved with its re-list
        self.event_count = 0
        self.pods: Dict[str, Pod] = {}
        self.services: Dict[str, Service] = {}
        self.groups: Dict[str, PodGroup] = {}
        self.jobs: Dict[str, TPUJob] = {}
        # client-go Indexer parity: list_* with the job-label selector is
        # the reconciler's hot read — O(own objects), not O(cluster).
        # key: "<ns>/<job-label>" → {object key, ...}
        self._pods_by_job: Dict[str, set] = {}
        self._svcs_by_job: Dict[str, set] = {}
        # owner index for the orphan pass: owner uid → {pod key, ...}
        self._pods_by_owner: Dict[str, set] = {}

    # -- wiring -------------------------------------------------------------

    def handle_event(self, ev: WatchEvent) -> None:
        handler = {
            "Pod": self._on_pod,
            "Service": self._on_service,
            "PodGroup": self._on_group,
            "TPUJob": self._on_job,
        }.get(ev.kind)
        if handler:
            with self._lock:
                self.event_count += 1
            handler(ev)

    # -- reads (the "listers") ----------------------------------------------

    def get_job(self, key: str) -> Optional[TPUJob]:
        with self._lock:
            job = self.jobs.get(key)
            return job.deepcopy() if job else None

    def list_pods(self, namespace: str, selector: Optional[Dict[str, str]] = None) -> List[Pod]:
        with self._lock:
            keys = self._index_keys(self._pods_by_job, namespace, selector)
            if keys is not None:
                return [
                    p
                    for p in (self.pods.get(k) for k in keys)
                    if p is not None and match_selector(p.metadata.labels, selector)
                ]
            return [
                p
                for p in self.pods.values()
                if p.metadata.namespace == namespace
                and match_selector(p.metadata.labels, selector)
            ]

    def list_services(
        self, namespace: str, selector: Optional[Dict[str, str]] = None
    ) -> List[Service]:
        with self._lock:
            keys = self._index_keys(self._svcs_by_job, namespace, selector)
            if keys is not None:
                return [
                    s
                    for s in (self.services.get(k) for k in keys)
                    if s is not None and match_selector(s.metadata.labels, selector)
                ]
            return [
                s
                for s in self.services.values()
                if s.metadata.namespace == namespace
                and match_selector(s.metadata.labels, selector)
            ]

    @staticmethod
    def _index_keys(index, namespace, selector):
        """Index bucket for a job-label selector; None = full scan."""

        if not selector or LABEL_JOB_NAME not in selector:
            return None
        return index.get(f"{namespace}/{selector[LABEL_JOB_NAME]}", ())

    def list_pods_owned(self, owner_uid: str) -> List[Pod]:
        """Pods whose controller owner is ``owner_uid`` (owner index)."""

        with self._lock:
            keys = self._pods_by_owner.get(owner_uid, ())
            return [p for p in (self.pods.get(k) for k in keys) if p is not None]

    def get_group(self, key: str) -> Optional[PodGroup]:
        with self._lock:
            return self.groups.get(key)

    # -- resync -------------------------------------------------------------

    def resync(self, jobs, pods, services, groups, expected_event_count=None) -> set:
        """Full state replacement (SharedInformer resync parity,
        SURVEY.md §5): swap in authoritative listings, rebuild the
        indexes, enqueue every job that exists now OR existed before OR
        is referenced by an object's label — lost watch events (adds,
        deletes, phase changes) are healed on the next sync.

        ``expected_event_count``: the caller's ``event_count`` read
        BEFORE taking the listings.  If any watch event landed since,
        the listings may be older than the cache — the swap is aborted
        (returns an empty set) and the next periodic resync tries again;
        resyncs matter precisely when events are NOT flowing, so an
        abort under churn costs nothing.

        Expectations are deliberately untouched (reference semantics:
        resync re-delivers state, expectation imbalances heal via their
        own timeout)."""

        with self._lock:
            if (
                expected_event_count is not None
                and self.event_count != expected_event_count
            ):
                return set()
            affected = set(self.jobs)
            self.jobs = {j.key: j for j in jobs}
            self.pods = {p.key: p for p in pods}
            self.services = {s.key: s for s in services}
            self.groups = {g.key: g for g in groups}
            self._pods_by_job = {}
            self._svcs_by_job = {}
            self._pods_by_owner = {}
            for p in pods:
                jk = self._job_key_for(p)
                if jk:
                    self._pods_by_job.setdefault(jk, set()).add(p.key)
                    affected.add(jk)
                if p.metadata.owner_uid:
                    self._pods_by_owner.setdefault(
                        p.metadata.owner_uid, set()
                    ).add(p.key)
            for s in services:
                jk = self._job_key_for(s)
                if jk:
                    self._svcs_by_job.setdefault(jk, set()).add(s.key)
                    affected.add(jk)
            affected |= set(self.jobs)
        for key in affected:
            self._enqueue(key)
        return affected

    # -- handlers -----------------------------------------------------------

    def _job_key_for(self, obj) -> Optional[str]:
        jname = obj.metadata.labels.get(LABEL_JOB_NAME)
        if not jname:
            return None
        return f"{obj.metadata.namespace}/{jname}"

    @staticmethod
    def _index_update(index, obj, job_key: Optional[str], old_key: Optional[str], deleted: bool):
        # requires self._lock held
        if old_key is not None and (deleted or old_key != job_key):
            bucket = index.get(old_key)
            if bucket is not None:
                bucket.discard(obj.key)
                if not bucket:
                    del index[old_key]
        if not deleted and job_key is not None:
            index.setdefault(job_key, set()).add(obj.key)

    def _on_pod(self, ev: WatchEvent) -> None:
        pod: Pod = ev.obj
        old_key: Optional[str] = None
        deleted = ev.type is WatchEventType.DELETED
        key = self._job_key_for(pod)
        with self._lock:
            prev = self.pods.get(pod.key)
            if prev is not None:
                old_key = self._job_key_for(prev)
            if deleted:
                self.pods.pop(pod.key, None)
            else:
                self.pods[pod.key] = pod
            self._index_update(
                self._pods_by_job, pod, key, old_key if prev else None, deleted
            )
            self._index_update(
                self._pods_by_owner,
                pod,
                pod.metadata.owner_uid or None,
                (prev.metadata.owner_uid or None) if prev is not None else None,
                deleted,
            )
        if old_key and old_key != key:
            # label change moved the pod to another controller: the old
            # one must re-sync to release/recreate (reference updatePod
            # parity — both old and new owners are enqueued)
            self._enqueue(old_key)
        if key:
            if ev.type is WatchEventType.ADDED:
                self._pod_exp.creation_observed(key)
            elif ev.type is WatchEventType.DELETED:
                self._pod_exp.deletion_observed(key)
            self._enqueue(key)

    def _on_service(self, ev: WatchEvent) -> None:
        svc: Service = ev.obj
        deleted = ev.type is WatchEventType.DELETED
        key = self._job_key_for(svc)
        with self._lock:
            prev = self.services.get(svc.key)
            if deleted:
                self.services.pop(svc.key, None)
            else:
                self.services[svc.key] = svc
            self._index_update(
                self._svcs_by_job,
                svc,
                key,
                self._job_key_for(prev) if prev is not None else None,
                deleted,
            )
        if key:
            if ev.type is WatchEventType.ADDED:
                self._svc_exp.creation_observed(key)
            elif ev.type is WatchEventType.DELETED:
                self._svc_exp.deletion_observed(key)
            self._enqueue(key)

    def _on_group(self, ev: WatchEvent) -> None:
        group: PodGroup = ev.obj
        with self._lock:
            if ev.type is WatchEventType.DELETED:
                self.groups.pop(group.key, None)
            else:
                self.groups[group.key] = group
        key = self._job_key_for(group)
        if key:
            self._enqueue(key)

    def _on_job(self, ev: WatchEvent) -> None:
        job: TPUJob = ev.obj
        with self._lock:
            if ev.type is WatchEventType.DELETED:
                self.jobs.pop(job.key, None)
            else:
                self.jobs[job.key] = job
        self._enqueue(job.key)
