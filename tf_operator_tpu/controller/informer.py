"""Informer caches: local read models fed by watch events.

Parity: client-go SharedInformer caches + the reference's event handlers
(SURVEY.md §2 "Job lifecycle hooks": addTFJob/updateTFJob/enqueueTFJob and
pod/service handlers routed via owner refs).  The reconciler reads ONLY
from these caches (never the backend directly), exactly like the
reference reads listers — which is what makes the Expectations race real
and testable with the fake backend's manual delivery mode.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from tf_operator_tpu.api.types import LABEL_JOB_NAME, TPUJob
from tf_operator_tpu.backend.base import match_selector
from tf_operator_tpu.backend.objects import (
    Pod,
    PodGroup,
    Service,
    WatchEvent,
    WatchEventType,
)
from tf_operator_tpu.controller.expectations import Expectations


class InformerCache:
    """Caches for every kind + enqueue/expectation hooks.

    Wire it to a backend and a job store with ``subscribe``; hand
    ``enqueue`` a callable taking a job key.
    """

    def __init__(
        self,
        enqueue: Callable[[str], None],
        pod_expectations: Expectations,
        service_expectations: Expectations,
    ):
        self._lock = threading.RLock()
        self._enqueue = enqueue
        self._pod_exp = pod_expectations
        self._svc_exp = service_expectations
        self.pods: Dict[str, Pod] = {}
        self.services: Dict[str, Service] = {}
        self.groups: Dict[str, PodGroup] = {}
        self.jobs: Dict[str, TPUJob] = {}

    # -- wiring -------------------------------------------------------------

    def handle_event(self, ev: WatchEvent) -> None:
        handler = {
            "Pod": self._on_pod,
            "Service": self._on_service,
            "PodGroup": self._on_group,
            "TPUJob": self._on_job,
        }.get(ev.kind)
        if handler:
            handler(ev)

    # -- reads (the "listers") ----------------------------------------------

    def get_job(self, key: str) -> Optional[TPUJob]:
        with self._lock:
            job = self.jobs.get(key)
            return job.deepcopy() if job else None

    def list_pods(self, namespace: str, selector: Optional[Dict[str, str]] = None) -> List[Pod]:
        with self._lock:
            return [
                p
                for p in self.pods.values()
                if p.metadata.namespace == namespace
                and match_selector(p.metadata.labels, selector)
            ]

    def list_services(
        self, namespace: str, selector: Optional[Dict[str, str]] = None
    ) -> List[Service]:
        with self._lock:
            return [
                s
                for s in self.services.values()
                if s.metadata.namespace == namespace
                and match_selector(s.metadata.labels, selector)
            ]

    def get_group(self, key: str) -> Optional[PodGroup]:
        with self._lock:
            return self.groups.get(key)

    # -- handlers -----------------------------------------------------------

    def _job_key_for(self, obj) -> Optional[str]:
        jname = obj.metadata.labels.get(LABEL_JOB_NAME)
        if not jname:
            return None
        return f"{obj.metadata.namespace}/{jname}"

    def _on_pod(self, ev: WatchEvent) -> None:
        pod: Pod = ev.obj
        old_key: Optional[str] = None
        with self._lock:
            prev = self.pods.get(pod.key)
            if prev is not None:
                old_key = self._job_key_for(prev)
            if ev.type is WatchEventType.DELETED:
                self.pods.pop(pod.key, None)
            else:
                self.pods[pod.key] = pod
        key = self._job_key_for(pod)
        if old_key and old_key != key:
            # label change moved the pod to another controller: the old
            # one must re-sync to release/recreate (reference updatePod
            # parity — both old and new owners are enqueued)
            self._enqueue(old_key)
        if key:
            if ev.type is WatchEventType.ADDED:
                self._pod_exp.creation_observed(key)
            elif ev.type is WatchEventType.DELETED:
                self._pod_exp.deletion_observed(key)
            self._enqueue(key)

    def _on_service(self, ev: WatchEvent) -> None:
        svc: Service = ev.obj
        with self._lock:
            if ev.type is WatchEventType.DELETED:
                self.services.pop(svc.key, None)
            else:
                self.services[svc.key] = svc
        key = self._job_key_for(svc)
        if key:
            if ev.type is WatchEventType.ADDED:
                self._svc_exp.creation_observed(key)
            elif ev.type is WatchEventType.DELETED:
                self._svc_exp.deletion_observed(key)
            self._enqueue(key)

    def _on_group(self, ev: WatchEvent) -> None:
        group: PodGroup = ev.obj
        with self._lock:
            if ev.type is WatchEventType.DELETED:
                self.groups.pop(group.key, None)
            else:
                self.groups[group.key] = group
        key = self._job_key_for(group)
        if key:
            self._enqueue(key)

    def _on_job(self, ev: WatchEvent) -> None:
        job: TPUJob = ev.obj
        with self._lock:
            if ev.type is WatchEventType.DELETED:
                self.jobs.pop(job.key, None)
            else:
                self.jobs[job.key] = job
        self._enqueue(job.key)
