"""The reconcile decision core as pure functions, with native dispatch.

Parity: the decision half of the reference's pod reconciler and status
engine (SURVEY.md §2 "Pod reconciler", "Status engine") — given observed
pod state, decide creates / scale-in deletes / restarts (with restart
budget) / fatals, and evaluate the success-policy truth table.  The
reconciler executes these decisions against the backend.

Two implementations behind one interface: this Python twin and the
native C++ core (native/src/planner.cc), which is used whenever the
native library loads (SURVEY.md §2a item 1 — the reference's hot path
is native).  tests/test_plan.py property-tests their equivalence.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import (
    CHIEF_LIKE,
    PodPhase,
    ReplicaType,
    RestartPolicy,
    SuccessPolicy,
    TPUJob,
)
from tf_operator_tpu.backend.objects import Pod
from tf_operator_tpu.utils.train_util import is_retryable_exit_code

#: observation of one pod: (replica_index, phase, exit_code or None)
PodObs = Tuple[int, PodPhase, Optional[int]]

_PHASE_CHAR = {
    PodPhase.PENDING: "P",
    PodPhase.RUNNING: "R",
    PodPhase.SUCCEEDED: "S",
    PodPhase.FAILED: "F",
    PodPhase.UNKNOWN: "U",
}


@dataclass
class ReplicaPlan:
    """Decisions for one replica type, one sync."""

    create: List[int] = field(default_factory=list)
    scale_in: List[int] = field(default_factory=list)
    #: (index, exit_code): delete the pod, count one restart
    restart: List[Tuple[int, int]] = field(default_factory=list)
    #: (index, exit_code): permanent failure
    fatal: List[Tuple[int, int]] = field(default_factory=list)
    backoff_exceeded: bool = False


def plan_replica_py(
    want: int,
    policy: RestartPolicy,
    backoff_limit: Optional[int],
    restart_count: int,
    observed: List[PodObs],
) -> ReplicaPlan:
    """Pure-Python twin of tpuop_plan_replica."""

    plan = ReplicaPlan()
    by_index: Dict[int, PodObs] = {}
    for obs in observed:
        idx = obs[0]
        if idx >= want:
            # duplicates appended as observed — matching the C++ twin;
            # the reconciler dedups with sorted(set(...)) before acting
            plan.scale_in.append(idx)
        elif idx not in by_index:
            by_index[idx] = obs  # first pod per index wins (slot[0])

    count = restart_count
    for idx in range(want):
        obs = by_index.get(idx)
        if obs is None:
            plan.create.append(idx)
            continue
        _, phase, exit_code = obs
        if phase is not PodPhase.FAILED:
            continue
        code = exit_code if exit_code is not None else 1
        should_restart = policy in (
            RestartPolicy.ALWAYS,
            RestartPolicy.ON_FAILURE,
        ) or (policy is RestartPolicy.EXIT_CODE and is_retryable_exit_code(code))
        if not should_restart:
            plan.fatal.append((idx, code))
            continue
        if backoff_limit is not None and count >= backoff_limit:
            # budget exhausted: abort the remaining indices (reference
            # parity — the job fails before touching later replicas)
            plan.backoff_exceeded = True
            break
        count += 1
        plan.restart.append((idx, code))
    return plan


def plan_replica(
    want: int,
    policy: RestartPolicy,
    backoff_limit: Optional[int],
    restart_count: int,
    observed: List[PodObs],
) -> ReplicaPlan:
    """Native core when available; Python twin otherwise."""

    native = _native()
    if native is None:
        return plan_replica_py(want, policy, backoff_limit, restart_count, observed)
    desc = (
        f"want={want};policy={policy.value};"
        f"limit={'-' if backoff_limit is None else backoff_limit};"
        f"restarts={restart_count};pods="
        + ",".join(
            f"{idx}:{_PHASE_CHAR[phase]}:{'-' if code is None else code}"
            for idx, phase, code in observed
        )
    )
    return _parse_plan(native.plan_replica(desc))


def _parse_plan(out: str) -> ReplicaPlan:
    fields = dict(item.split("=", 1) for item in out.split(";"))
    plan = ReplicaPlan()
    if fields.get("create"):
        plan.create = [int(x) for x in fields["create"].split(",")]
    if fields.get("scalein"):
        plan.scale_in = [int(x) for x in fields["scalein"].split(",")]
    for key, dest in (("restart", plan.restart), ("fatal", plan.fatal)):
        if fields.get(key):
            for item in fields[key].split(","):
                idx, _, code = item.partition(":")
                dest.append((int(idx), int(code)))
    plan.backoff_exceeded = fields.get("backoff") == "1"
    return plan


# ---------------------------------------------------------------- success


def evaluate_success_py(
    job: TPUJob, pods_by_type: Dict[ReplicaType, List[Pod]]
) -> Tuple[bool, str]:
    """Pure-Python twin — delegates to the existing status-engine
    implementation (the original source of truth)."""

    from tf_operator_tpu.controller import status

    return status._evaluate_success_py(job, pods_by_type)


def evaluate_success(
    job: TPUJob, pods_by_type: Dict[ReplicaType, List[Pod]]
) -> Tuple[bool, str]:
    """Native success-policy truth table when available."""

    native = _native()
    if native is None:
        return evaluate_success_py(job, pods_by_type)
    parts = []
    for rtype, spec in job.spec.replica_specs.items():
        pods = pods_by_type.get(rtype, [])
        nsucc = sum(1 for p in pods if p.phase is PodPhase.SUCCEEDED)
        pod0 = next((p for p in pods if p.replica_index == 0), None)
        p0s = 1 if pod0 is not None and pod0.phase is PodPhase.SUCCEEDED else 0
        parts.append(
            f"{rtype.value}:{job.spec.pod_count(rtype)}:{len(pods)}:{nsucc}:{p0s}"
        )
    desc = (
        f"policy={job.spec.success_policy.value or 'Default'};types="
        + ",".join(parts)
    )
    out = native.eval_success(desc)
    flag, _, reason = out.partition(":")
    return flag == "1", reason


# ---------------------------------------------------------------- native


class _NativePlanner:
    def __init__(self, lib):
        import ctypes

        self._lib = lib
        self._ctypes = ctypes
        lib.tpuop_plan_replica.restype = ctypes.c_int
        lib.tpuop_plan_replica.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.tpuop_eval_success.restype = ctypes.c_int
        lib.tpuop_eval_success.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]

    def _call(self, fn, desc: str) -> str:
        buf = self._ctypes.create_string_buffer(max(4096, 32 * len(desc)))
        n = fn(desc.encode(), buf, len(buf))
        if n < 0:
            raise ValueError(f"native planner rejected {desc!r}")
        return buf.value.decode()

    def plan_replica(self, desc: str) -> str:
        return self._call(self._lib.tpuop_plan_replica, desc)

    def eval_success(self, desc: str) -> str:
        return self._call(self._lib.tpuop_eval_success, desc)


_planner: Optional[_NativePlanner] = None
_planner_checked = False


def _native() -> Optional[_NativePlanner]:
    global _planner, _planner_checked
    if not _planner_checked:
        _planner_checked = True
        try:
            from tf_operator_tpu import native

            if native.available():
                _planner = _NativePlanner(native._load())
        except Exception:  # noqa: BLE001 - fall back to Python twin
            _planner = None
    return _planner
