"""The reconcile decision core as pure functions, with native dispatch.

Parity: the decision half of the reference's pod reconciler and status
engine (SURVEY.md §2 "Pod reconciler", "Status engine") — given observed
pod state, decide creates / scale-in deletes / restarts (with restart
budget) / fatals, and evaluate the success-policy truth table.  The
reconciler executes these decisions against the backend.

Two implementations behind one interface: this Python twin and the
native C++ core (native/src/planner.cc), which is used whenever the
native library loads (SURVEY.md §2a item 1 — the reference's hot path
is native).  tests/test_plan.py property-tests their equivalence.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import (
    CHIEF_LIKE,
    PodPhase,
    ReplicaType,
    RestartPolicy,
    SuccessPolicy,
    TPUJob,
)
from tf_operator_tpu.backend.objects import Pod
from tf_operator_tpu.utils.train_util import is_retryable_exit_code

#: observation of one pod: (replica_index, phase, exit_code or None)
PodObs = Tuple[int, PodPhase, Optional[int]]

_PHASE_CHAR = {
    PodPhase.PENDING: "P",
    PodPhase.RUNNING: "R",
    PodPhase.SUCCEEDED: "S",
    PodPhase.FAILED: "F",
    PodPhase.UNKNOWN: "U",
}


@dataclass
class ReplicaPlan:
    """Decisions for one replica type, one sync."""

    create: List[int] = field(default_factory=list)
    scale_in: List[int] = field(default_factory=list)
    #: (index, exit_code): delete the pod, count one restart
    restart: List[Tuple[int, int]] = field(default_factory=list)
    #: (index, exit_code): permanent failure
    fatal: List[Tuple[int, int]] = field(default_factory=list)
    backoff_exceeded: bool = False


def plan_replica_py(
    want: int,
    policy: RestartPolicy,
    backoff_limit: Optional[int],
    restart_count: int,
    observed: List[PodObs],
) -> ReplicaPlan:
    """Pure-Python twin of tpuop_plan_replica."""

    plan = ReplicaPlan()
    by_index: Dict[int, PodObs] = {}
    for obs in observed:
        idx = obs[0]
        if idx >= want:
            # duplicates appended as observed — matching the C++ twin;
            # the reconciler dedups with sorted(set(...)) before acting
            plan.scale_in.append(idx)
        elif idx not in by_index:
            by_index[idx] = obs  # first pod per index wins (slot[0])

    count = restart_count
    for idx in range(want):
        obs = by_index.get(idx)
        if obs is None:
            plan.create.append(idx)
            continue
        _, phase, exit_code = obs
        if phase is not PodPhase.FAILED:
            continue
        code = exit_code if exit_code is not None else 1
        should_restart = policy in (
            RestartPolicy.ALWAYS,
            RestartPolicy.ON_FAILURE,
        ) or (policy is RestartPolicy.EXIT_CODE and is_retryable_exit_code(code))
        if not should_restart:
            plan.fatal.append((idx, code))
            continue
        if backoff_limit is not None and count >= backoff_limit:
            # budget exhausted: abort the remaining indices (reference
            # parity — the job fails before touching later replicas)
            plan.backoff_exceeded = True
            break
        count += 1
        plan.restart.append((idx, code))
    return plan


def plan_replica(
    want: int,
    policy: RestartPolicy,
    backoff_limit: Optional[int],
    restart_count: int,
    observed: List[PodObs],
) -> ReplicaPlan:
    """Native core when available; Python twin otherwise."""

    native = _native()
    if native is None:
        return plan_replica_py(want, policy, backoff_limit, restart_count, observed)
    desc = (
        f"want={want};policy={policy.value};"
        f"limit={'-' if backoff_limit is None else backoff_limit};"
        f"restarts={restart_count};pods="
        + ",".join(
            f"{idx}:{_PHASE_CHAR[phase]}:{'-' if code is None else code}"
            for idx, phase, code in observed
        )
    )
    return _parse_plan(native.plan_replica(desc))


def _parse_plan(out: str) -> ReplicaPlan:
    fields = dict(item.split("=", 1) for item in out.split(";"))
    plan = ReplicaPlan()
    if fields.get("create"):
        plan.create = [int(x) for x in fields["create"].split(",")]
    if fields.get("scalein"):
        plan.scale_in = [int(x) for x in fields["scalein"].split(",")]
    for key, dest in (("restart", plan.restart), ("fatal", plan.fatal)):
        if fields.get(key):
            for item in fields[key].split(","):
                idx, _, code = item.partition(":")
                dest.append((int(idx), int(code)))
    plan.backoff_exceeded = fields.get("backoff") == "1"
    return plan


# ------------------------------------------------------------- batch sync

_TYPE_ID = {
    ReplicaType.CHIEF: 0,
    ReplicaType.MASTER: 1,
    ReplicaType.PS: 2,
    ReplicaType.WORKER: 3,
    ReplicaType.EVALUATOR: 4,
    ReplicaType.TPU_SLICE: 5,
}
_TYPE_FROM_ID = {v: k for k, v in _TYPE_ID.items()}
_PHASE_ID = {
    PodPhase.PENDING: 0,
    PodPhase.RUNNING: 1,
    PodPhase.SUCCEEDED: 2,
    PodPhase.FAILED: 3,
    PodPhase.UNKNOWN: 4,
}
_POLICY_ID = {
    RestartPolicy.NEVER: 0,
    RestartPolicy.ALWAYS: 1,
    RestartPolicy.ON_FAILURE: 2,
    RestartPolicy.EXIT_CODE: 3,
}
#: Reason-code → string table (tpuop::Reason in plan_core.h)
_REASON_TEXT = (
    "",
    "Chief replica succeeded",
    "Master replica succeeded",
    "all replicas succeeded",
    "all workers succeeded",
    "all slice members succeeded",
    "all slice members and worker 0 succeeded",
    "worker 0 succeeded",
)


@dataclass
class SyncDecision:
    """Everything one reconcile sync decides, computed in one shot."""

    succeeded: bool
    reason: str
    plans: Dict[ReplicaType, ReplicaPlan]


def sync_decide_py(job: TPUJob, pods_by_type: Dict[ReplicaType, "list"]) -> SyncDecision:
    """Pure-Python twin of tpuop_sync_decide: success evaluation plus
    per-type plans with the job-global restart budget threaded across
    types in spec order (matching the executor's sequential behavior)."""

    succeeded, reason = evaluate_success_py(job, pods_by_type)
    limit = job.spec.run_policy.backoff_limit
    count = job.status.restart_count
    plans: Dict[ReplicaType, ReplicaPlan] = {}
    for rtype in job.spec.ordered_types():
        spec = job.spec.replica_specs[rtype]
        pods = pods_by_type.get(rtype, [])
        observed = [
            (p.replica_index, p.phase, p.exit_code)
            for p in pods
            if p.replica_index is not None
        ]
        policy = spec.restart_policy or RestartPolicy.NEVER
        plan = plan_replica_py(job.spec.pod_count(rtype), policy, limit, count, observed)
        count += len(plan.restart)
        plans[rtype] = plan
    return SyncDecision(succeeded, reason, plans)


def sync_decide(
    job: TPUJob,
    pods_by_type: Dict[ReplicaType, "list"],
    use_native: Optional[bool] = None,
) -> SyncDecision:
    """ONE native call per sync (packed int32, syncdecide.cc) when the
    native runtime is available; Python twin otherwise.  ``use_native``
    forces one implementation (False = Python twin even when the native
    library loads — the controller's use_native flag threads through
    here so a python-runtime controller is python end to end)."""

    native = _native() if use_native in (None, True) else None
    if native is None:
        if use_native is True:
            raise RuntimeError(
                "use_native=True but the native planner is unavailable"
            )
        return sync_decide_py(job, pods_by_type)

    limit = job.spec.run_policy.backoff_limit
    ordered = job.spec.ordered_types()
    arr = [
        1,
        1 if job.spec.success_policy is SuccessPolicy.ALL_WORKERS else 0,
        job.status.restart_count,
        0 if limit is None else 1,
        0 if limit is None else limit,
        len(ordered),
    ]
    out_cap = 3
    for rtype in ordered:
        spec = job.spec.replica_specs[rtype]
        pods = pods_by_type.get(rtype, [])
        want = job.spec.pod_count(rtype)
        policy = spec.restart_policy or RestartPolicy.NEVER
        arr += (_TYPE_ID[rtype], want, _POLICY_ID[policy], len(pods))
        for p in pods:
            idx = p.replica_index
            code = p.exit_code
            arr += (
                -1 if idx is None else idx,
                _PHASE_ID[p.phase],
                -1 if code is None else code,
            )
        out_cap += 6 + 3 * want + 3 * len(pods)
    out = native.sync_decide(arr, out_cap)

    succeeded = bool(out[0])
    reason = _REASON_TEXT[out[1]]
    plans: Dict[ReplicaType, ReplicaPlan] = {}
    pos = 3
    for _ in range(out[2]):
        tid, backoff, nc, ns, nr, nf = out[pos : pos + 6]
        pos += 6
        plan = ReplicaPlan()
        plan.create = list(out[pos : pos + nc])
        pos += nc
        plan.scale_in = list(out[pos : pos + ns])
        pos += ns
        plan.restart = [(out[pos + 2 * i], out[pos + 2 * i + 1]) for i in range(nr)]
        pos += 2 * nr
        plan.fatal = [(out[pos + 2 * i], out[pos + 2 * i + 1]) for i in range(nf)]
        pos += 2 * nf
        plan.backoff_exceeded = bool(backoff)
        plans[_TYPE_FROM_ID[tid]] = plan
    return SyncDecision(succeeded, reason, plans)


# ---------------------------------------------------------------- success


def evaluate_success_py(
    job: TPUJob, pods_by_type: Dict[ReplicaType, List[Pod]]
) -> Tuple[bool, str]:
    """Pure-Python twin — delegates to the existing status-engine
    implementation (the original source of truth)."""

    from tf_operator_tpu.controller import status

    return status._evaluate_success_py(job, pods_by_type)


def evaluate_success(
    job: TPUJob, pods_by_type: Dict[ReplicaType, List[Pod]]
) -> Tuple[bool, str]:
    """Native success-policy truth table when available."""

    native = _native()
    if native is None:
        return evaluate_success_py(job, pods_by_type)
    parts = []
    for rtype, spec in job.spec.replica_specs.items():
        pods = pods_by_type.get(rtype, [])
        nsucc = sum(1 for p in pods if p.phase is PodPhase.SUCCEEDED)
        pod0 = next((p for p in pods if p.replica_index == 0), None)
        p0s = 1 if pod0 is not None and pod0.phase is PodPhase.SUCCEEDED else 0
        parts.append(
            f"{rtype.value}:{job.spec.pod_count(rtype)}:{len(pods)}:{nsucc}:{p0s}"
        )
    desc = (
        f"policy={job.spec.success_policy.value or 'Default'};types="
        + ",".join(parts)
    )
    out = native.eval_success(desc)
    flag, _, reason = out.partition(":")
    return flag == "1", reason


# ---------------------------------------------------------------- native


class _NativePlanner:
    def __init__(self, lib):
        import ctypes

        self._lib = lib
        self._ctypes = ctypes
        lib.tpuop_plan_replica.restype = ctypes.c_int
        lib.tpuop_plan_replica.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.tpuop_eval_success.restype = ctypes.c_int
        lib.tpuop_eval_success.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.tpuop_sync_decide.restype = ctypes.c_int
        lib.tpuop_sync_decide.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
        ]

    def _call(self, fn, desc: str) -> str:
        buf = self._ctypes.create_string_buffer(max(4096, 32 * len(desc)))
        n = fn(desc.encode(), buf, len(buf))
        if n < 0:
            raise ValueError(f"native planner rejected {desc!r}")
        return buf.value.decode()

    def plan_replica(self, desc: str) -> str:
        return self._call(self._lib.tpuop_plan_replica, desc)

    def eval_success(self, desc: str) -> str:
        return self._call(self._lib.tpuop_eval_success, desc)

    def sync_decide(self, values: "list", out_cap: int):
        import array

        ct = self._ctypes
        # array('i') ingests the list at C speed; from_buffer avoids the
        # per-element ctypes conversion of (c_int32 * n)(*values)
        buf = array.array("i", values)
        in_arr = (ct.c_int32 * len(buf)).from_buffer(buf)
        out_buf = array.array("i", bytes(4 * out_cap))
        out_arr = (ct.c_int32 * out_cap).from_buffer(out_buf)
        n = self._lib.tpuop_sync_decide(in_arr, len(buf), out_arr, out_cap)
        if n < 0:
            raise ValueError(f"native sync_decide rejected input (rc={n})")
        return out_buf[:n]


_planner: Optional[_NativePlanner] = None
_planner_checked = False


def _native() -> Optional[_NativePlanner]:
    global _planner, _planner_checked
    if not _planner_checked:
        _planner_checked = True
        try:
            from tf_operator_tpu import native

            if native.available():
                _planner = _NativePlanner(native._load())
        except Exception:  # noqa: BLE001 - fall back to Python twin
            _planner = None
    return _planner
