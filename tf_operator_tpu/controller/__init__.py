"""The reconcile engine (SURVEY.md §1 L2/L3): work queue, expectations,
informer caches, pod/service reconcilers, status engine, controller loop."""
