"""Controller assembly + worker loop.

Parity: ``NewTFController`` + ``Controller.Run(threadiness, stopCh)``
(SURVEY.md §2 "TFJob controller core", §3.1): wires informer handlers to
the work queue, spawns N worker threads draining it, applies per-key
rate-limited retries on sync errors.

Deterministic test mode: with a sync-delivery fake backend,
``sync_until_quiet()`` drains the queue inline — no threads — which is
how the tier-1 tests run "multi-node" scenarios as pure data
(SURVEY.md §4).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from tf_operator_tpu.backend.base import ClusterBackend
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.controller.expectations import (
    EXPECTATION_TIMEOUT_S,
    Expectations,
)
from tf_operator_tpu.controller.informer import InformerCache
from tf_operator_tpu.controller.reconciler import Reconciler, ReconcilerConfig
from tf_operator_tpu.controller.workqueue import WorkQueue
from tf_operator_tpu.utils.events import EventRecorder
from tf_operator_tpu.utils.logging import logger_for_job
from tf_operator_tpu.utils.metrics import Metrics, default_metrics
from tf_operator_tpu.utils.trace import Tracer, default_tracer


class TPUJobController:
    def __init__(
        self,
        job_store: JobStore,
        backend: ClusterBackend,
        config: Optional[ReconcilerConfig] = None,
        metrics: Optional[Metrics] = None,
        max_sync_retries: int = 20,
        use_native: Optional[bool] = None,
        resync_period: float = 30.0,
        expectations_timeout: float = EXPECTATION_TIMEOUT_S,
        recorder: Optional[EventRecorder] = None,
        tracer: Optional[Tracer] = None,
        alerts=None,
        autoscaler=None,
        telemetry=None,
        scheduler=None,
    ):
        self.jobs = job_store
        self.backend = backend
        self.tracer = tracer if tracer is not None else default_tracer
        #: key -> (trace_id, parent_span_id, enqueue_monotonic): the
        #: trace context captured at enqueue time, consumed at dequeue
        #: so the queue-latency span and the sync join the trace that
        #: triggered the work (informer event, requeue, resync)
        self._pending_trace: Dict[str, Tuple[Optional[str], Optional[str], float]] = {}
        self._pending_lock = threading.Lock()
        # native (C++) runtime by default when buildable — the reference's
        # queue/expectations tier is native (SURVEY.md §2a); the Python
        # twins back it on boxes without a toolchain.
        if use_native is None:
            from tf_operator_tpu import native

            use_native = native.available()
        self.native = bool(use_native)
        if self.native:
            from tf_operator_tpu.native import NativeExpectations, NativeWorkQueue

            self.queue = NativeWorkQueue()
            self.pod_exp = NativeExpectations(expectations_timeout)
            self.svc_exp = NativeExpectations(expectations_timeout)
        else:
            self.queue = WorkQueue()
            self.pod_exp = Expectations(expectations_timeout)
            self.svc_exp = Expectations(expectations_timeout)
        # injectable: the kube backends post REAL v1 Event objects to
        # the apiserver instead (backend/kubejobs.KubeEventRecorder —
        # same surface, so the describe/API read path is unchanged)
        self.recorder = recorder if recorder is not None else EventRecorder()
        self.metrics = metrics or default_metrics
        if config is None:
            config = ReconcilerConfig(use_native_decisions=self.native)
        elif config.use_native_decisions is None:
            # never mutate the caller's config object — it may be shared
            import dataclasses

            config = dataclasses.replace(config, use_native_decisions=self.native)
        self.cache = InformerCache(self._enqueue, self.pod_exp, self.svc_exp)
        #: utils/alerts.AlertEngine (optional): the reconciler rolls its
        #: firing set into TPUJob.status; every alert transition
        #: re-enqueues all known jobs so Degraded lands/clears without
        #: waiting for the next watch event or resync
        self.alerts = alerts
        if alerts is not None:
            alerts.subscribe(self._on_alert_transition)
        #: controller/autoscaler.Autoscaler (optional): we feed it the
        #: informer cache as its job source; each decision emits a
        #: ScaledUp/ScaledDown Normal event and re-enqueues the job so
        #: the reconciler applies the new desired count promptly
        self.autoscaler = autoscaler
        if autoscaler is not None:
            autoscaler.attach(self._list_cached_jobs, self._on_scale_decision)
        #: controller/telemetry.TelemetryScraper (optional): we feed it
        #: the informer cache's pod snapshot as its target source — it
        #: scrapes on its OWN thread (a reconcile sync never waits on a
        #: pod's HTTP server) and federates pod-scope families into the
        #: shared registry the alert engine / autoscaler / rollup read
        self.telemetry = telemetry
        if telemetry is not None:
            telemetry.attach(self._list_cached_pods)
        #: controller/scheduler.Scheduler (optional): we feed it the
        #: informer cache as its job source and the backend's chip pool
        #: as its capacity probe; each decision emits an event and
        #: re-enqueues the job, and capacity-shrink revocation in the
        #: backend routes through its victim policy instead of LIFO
        self.scheduler = scheduler
        if scheduler is not None:
            scheduler.attach(
                self._list_cached_jobs,
                self._on_sched_decision,
                capacity=lambda: getattr(backend, "total_chips", None),
            )
            if hasattr(backend, "attach_scheduler"):
                backend.attach_scheduler(scheduler, recorder=self.recorder)
        self.reconciler = Reconciler(
            job_store,
            backend,
            self.cache,
            self.pod_exp,
            self.svc_exp,
            recorder=self.recorder,
            metrics=self.metrics,
            config=config,
            requeue_after=self._requeue_after,
            tracer=self.tracer,
            alerts=alerts,
            autoscaler=autoscaler,
            telemetry=telemetry,
            scheduler=scheduler,
        )
        self.max_sync_retries = max_sync_retries
        self.resync_period = resync_period
        self._threads: list = []
        self._stop = threading.Event()
        backend.subscribe(self._handle_event)
        job_store.subscribe(self._handle_event)

    # ------------------------------------------------------------- tracing

    def _handle_event(self, ev) -> None:
        """Informer event delivery under a span: on a watch thread this
        starts the trace that the enqueue → queue-wait → sync chain
        joins; under a sync-delivery backend it nests inside the sync
        that caused the event (the re-entrancy becomes visible)."""

        etype = getattr(ev.type, "value", str(ev.type))
        with self.tracer.span(
            f"informer {ev.kind} {etype}",
            attributes={"kind": ev.kind, "eventType": etype},
        ):
            self.cache.handle_event(ev)

    def _capture_trace(self, key: str, offset: float = 0.0) -> None:
        span = self.tracer.current_span()
        with self._pending_lock:
            # first unprocessed add wins (client-go workqueue
            # semantics): the queue dedups re-adds, so overwriting here
            # would reset the enqueue timestamp on every re-add and
            # under-report queue latency exactly when the queue is
            # backlogged — the condition the histogram exists to show
            self._pending_trace.setdefault(key, (
                span.trace_id if span is not None else None,
                span.span_id if span is not None else None,
                time.monotonic() + offset,
            ))

    def _list_cached_jobs(self):
        """The autoscaler's job source: a snapshot of the informer
        cache's job objects (read-only — watch events REPLACE cached
        objects, never mutate them, so holding references is safe)."""

        with self.cache._lock:
            return list(self.cache.jobs.values())

    def _list_cached_pods(self):
        """The telemetry scraper's target source: a snapshot of the
        informer cache's pod objects (read-only, same contract as
        ``_list_cached_jobs``)."""

        with self.cache._lock:
            return list(self.cache.pods.values())

    def _on_scale_decision(self, decision) -> None:
        """Autoscaler decision callback (runs on its evaluator thread):
        one Normal event per decision — the acceptance contract's
        event leg — plus a prompt re-enqueue so the reconciler applies
        the new desired count without waiting for a watch event."""

        self.recorder.event(
            decision.job_key,
            "Normal",
            decision.event_reason,
            f"{decision.replica_type.value} replicas "
            f"{decision.from_replicas} -> {decision.to_replicas}: "
            f"{decision.reason}",
        )
        self._enqueue(decision.job_key)

    def _on_sched_decision(self, decision) -> None:
        """Fleet-scheduler decision callback (runs on its evaluator
        thread): one event per decision — Normal for queue/admit,
        Warning for shed/revoke, so a preempted job's audit trail names
        who took its chips — plus a prompt re-enqueue so the reconciler
        acts on the new fleet phase without waiting for a watch event."""

        self.recorder.event(
            decision.job_key,
            decision.event_type,
            decision.event_reason,
            f"fleet scheduler: {decision.action} — {decision.reason}",
        )
        self._enqueue(decision.job_key)

    def _on_alert_transition(self, alert, old: str, new: str) -> None:
        """Alert-engine subscriber (runs on the evaluator thread):
        re-enqueue every cached job so the reconciler's health rollup
        republishes promptly.  Only transitions entering or leaving
        ``firing`` can change the Degraded condition or observedHealth
        (the rollup reads ``alerts.firing()``), so pending flaps and
        the resolved→inactive decay skip the full-cache sweep."""

        if old != "firing" and new != "firing":
            return
        with self.cache._lock:
            keys = list(self.cache.jobs)
        for key in keys:
            self._enqueue(key)

    def _enqueue(self, key: str) -> None:
        self._capture_trace(key)
        self.queue.add(key)
        self.metrics.set("workqueue_depth", float(len(self.queue)))

    def _requeue_after(self, key: str, delay: float) -> None:
        # the intentional delay is not queue latency: measure the wait
        # from the moment the key becomes due
        self._capture_trace(key, offset=delay)
        self.queue.add_after(key, delay)
        self.metrics.set("workqueue_depth", float(len(self.queue)))

    def resync(self) -> int:
        """One full informer resync: authoritative re-list of jobs from
        the store and pods/services/groups from the backend, cache
        replacement, and an enqueue of every affected job (SURVEY.md §5
        "informer resync (periodic full re-list heals missed events)").
        Returns the number of jobs enqueued."""

        with self.tracer.span("informer.resync") as sp:
            before = self.cache.event_count
            jobs = self.jobs.list(None)
            snap = self.backend.snapshot()
            if snap is None:
                # backend can't re-list: no cache swap, just re-enqueue
                # every known job so level-triggered syncs re-examine them
                with self.cache._lock:
                    keys = set(self.cache.jobs) | {j.key for j in jobs}
                for key in keys:
                    self._enqueue(key)
                self.metrics.inc("tpujob_resyncs_total")
                sp.set_attribute("enqueued", len(keys))
                return len(keys)
            pods, services, groups = snap
            affected = self.cache.resync(
                jobs, pods, services, groups, expected_event_count=before
            )
            self.metrics.inc("tpujob_resyncs_total")
            sp.set_attribute("enqueued", len(affected))
            return len(affected)

    # ---------------------------------------------------------------- loops

    def process_next(self, timeout: Optional[float] = 0.0) -> bool:
        """One queue item; returns False when nothing was processed.

        Traced: the sync joins the trace captured at enqueue time (or
        roots a fresh one), with a ``queue.wait`` span spanning
        enqueue→dequeue — the queue-latency leg of the waterfall, also
        observed into ``workqueue_queue_latency_seconds``.
        """

        key = self.queue.get(timeout=timeout)
        if key is None:
            return False
        now = time.monotonic()
        with self._pending_lock:
            pending = self._pending_trace.pop(key, None)
        self.metrics.set("workqueue_depth", float(len(self.queue)))
        tid, parent, enq_ts = pending if pending else (None, None, None)
        if tid is not None:
            root = self.tracer.start_span(
                f"sync {key}", trace_id=tid, parent_id=parent
            )
        else:
            root = self.tracer.start_span(f"sync {key}", root=True)
        with root:
            if enq_ts is not None:
                wait = max(0.0, now - enq_ts)
                self.metrics.observe_histogram(
                    "workqueue_queue_latency_seconds", wait
                )
                self.tracer.start_span(
                    "queue.wait", start_mono=now - wait
                ).end(end_mono=now)
            try:
                self.reconciler.sync(key)
            except Exception as e:  # noqa: BLE001 - retry-with-backoff path
                ns, _, name = key.partition("/")
                logger_for_job(ns, name).error(
                    "sync error: %s [trace=%s]", e, root.trace_id
                )
                root.set_error(f"{type(e).__name__}: {e}")
                self.metrics.inc(
                    "tpujob_sync_errors_total", exemplar=root.trace_id
                )
                if self.queue.num_requeues(key) < self.max_sync_retries:
                    self.queue.add_rate_limited(key)
                else:
                    self.queue.forget(key)
            else:
                self.queue.forget(key)
            finally:
                self.queue.done(key)
        return True

    def sync_until_quiet(self, max_iters: int = 10_000) -> int:
        """Drain the queue inline until empty; returns syncs performed."""

        n = 0
        while n < max_iters and self.process_next(timeout=0.0):
            n += 1
        return n

    def run(self, threadiness: int = 1) -> None:
        """Spawn worker threads (Controller.Run parity) plus the
        periodic resync loop (resync_period <= 0 disables)."""

        self._stop.clear()
        for _ in range(threadiness):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
            self._threads.append(t)
        if self.resync_period > 0:
            t = threading.Thread(target=self._resync_loop, daemon=True)
            t.start()
            self._threads.append(t)

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period):
            try:
                self.resync()
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                logger_for_job("-", "resync").error("resync failed: %s", e)

    def _worker(self) -> None:
        # each reconcile worker heartbeats the process watchdog: a sync
        # wedged on a dead backend stops beating, and past the deadline
        # the watchdog dumps every thread's stack + the flight recorder
        # (utils/watchdog.py; monitoring is opt-in, registration free)
        from tf_operator_tpu.utils.watchdog import default_watchdog

        hb = default_watchdog.register(
            f"controller.{threading.current_thread().name}"
        )
        try:
            while not self._stop.is_set():
                hb.beat()
                self.process_next(timeout=0.2)
        finally:
            default_watchdog.unregister(hb.name)

    def stop(self) -> None:
        self._stop.set()
        if self.telemetry is not None:
            # same contract as the autoscaler/engine below: the
            # (possibly process-global) scraper outlives this
            # controller and must not pin its dead cache as a source
            self.telemetry.detach(self._list_cached_pods)
        if self.autoscaler is not None:
            # same contract as the alert engine below: the (possibly
            # process-global) autoscaler outlives this controller
            self.autoscaler.detach(
                self._list_cached_jobs, self._on_scale_decision
            )
        if self.scheduler is not None:
            # same contract: the (possibly process-global) scheduler
            # outlives this controller and must drop its dead sources
            self.scheduler.detach(self._list_cached_jobs)
            if hasattr(self.backend, "detach_scheduler"):
                self.backend.detach_scheduler(self.scheduler)
        if self.alerts is not None:
            # detach from the (possibly process-global) engine — it
            # outlives this controller and would otherwise pin it and
            # keep invoking the callback forever
            self.alerts.unsubscribe(self._on_alert_transition)
        self.queue.shutdown()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()
