"""Fleet scheduler — priority quota queues + cross-job gang preemption.

The cluster-level half of gang scheduling (ROADMAP item 4, the
Kueue/Volcano shape; SURVEY.md's gang/pod-group inventory is the
grounding reference).  Single-job admission (backend/fake.py's
PodGroup grant, kubesim's scheduler sim) answers "do these chips
exist"; this subsystem answers "who deserves them":

- **queue** — TPUJobs that declare ``spec.scheduling`` enter a fleet
  queue and are admitted WHOLE-GANG in priority × age order: effective
  rank = class rank + ``wait // age_boost_seconds``, so a starved
  low-priority gang eventually outranks fresh high-priority arrivals
  (anti-starvation; the ``gang-queue-stall`` alert rule watches the
  same ``scheduler_queued_since_unix`` stamp).
- **quota** — admitted chips are accounted per ``<namespace>/<group>``
  key; a group at its registered limit queues with reason
  ``QuotaExceeded`` and is NEVER helped by preemption (quota is a hard
  cap, not a priority).
- **preemption** — when a queued gang outranks the running fleet but
  no free chips remain, the scheduler picks victims (lowest class →
  youngest grant → smallest checkpoint debt) and reclaims just enough:
  a multi-slice victim SHEDS whole slices (the reconciler routes the
  resize through the same checkpoint-freshness-gated bounce as PR 14's
  autoscaler resharding, so ``dp``-only-over-DCN survives), a
  single-slice victim is REVOKED back to the queue whole.  Elective
  preemption is gated on victim checkpoint freshness — a victim whose
  latest async checkpoint is unknown or stale is skipped
  (``scheduler_skipped_total{reason="checkpoint_stale"}``) rather than
  robbed of unbounded work.  Capacity-shrink reclaim (the pool itself
  shrank underneath admitted demand) bypasses the gate: those chips
  are already gone, holding the grant would just wedge the queue.

Autoscaler coexistence (PR 7): the scheduler only ever LOWERS a
TPU_SLICE replica count via the same working-clone overlay mechanism
(``apply``), applied after the autoscaler's, and never touches jobs
without ``spec.scheduling`` — the two subsystems converge because both
express desires as overlays the reconciler resolves on every sync, and
a shed ceiling simply clamps whatever the autoscaler wants.

Deliberately NOT here: pod placement (the backends own bin-packing;
slice alignment is preserved because the unit of everything above is a
whole slice) and replica surgery (the reconciler owns pods — this
class only publishes decisions and overlays).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from tf_operator_tpu.api.types import (
    DEFAULT_PRIORITY_CLASS,
    ReplicaType,
    TPUJob,
    priority_rank,
)
from tf_operator_tpu.api.validation import parse_tpu_topology
from tf_operator_tpu.controller.autoscaler import job_checkpoint_age
from tf_operator_tpu.utils.logging import logger_for_job

#: decision-log ring size (mirrors controller/autoscaler.py)
MAX_DECISIONS = 256

#: seconds of queue wait per +1 effective priority rank — the
#: anti-starvation age boost.  At the default, a "low" gang that has
#: waited 3 × 300 s ranks even with a fresh "critical" arrival.
AGE_BOOST_SECONDS = 300.0

#: floor between preemptions touching the same victim, and the grace a
#: fresh admission enjoys before it may be victimised — half of the
#: zero-decision-flapping story (the other half: decisions are only
#: emitted on state TRANSITIONS, never re-emitted per sweep)
PREEMPTION_COOLDOWN_SECONDS = 30.0

#: elective-preemption checkpoint gate: a victim's newest async
#: checkpoint must be at most this old, else it is skipped (mirrors
#: the autoscaler's max_checkpoint_age_seconds resize gate)
MAX_VICTIM_CHECKPOINT_AGE_SECONDS = 900.0

#: how long a gang may be ABSENT from the lister snapshot before its
#: state is dropped.  The lister is an informer cache: a broken watch
#: re-listing under apiserver faults can briefly return a snapshot
#: missing live jobs, and forgetting on one blip would reset queue age,
#: shed ceilings, and cooldowns — then double-count the re-admission
#: (the contention soak caught exactly this flap).  Jobs OBSERVED
#: terminal/unmanaged, and explicit forget() from the reconciler's
#: deletion path, still drop state immediately.
MISSING_GRACE_SECONDS = 10.0


def gang_demand(job: TPUJob) -> int:
    """Chips this job's gang occupies when fully placed: Σ over
    TPU_SLICE replica sets of replicas × slice topology chips.  Jobs
    with no TPU_SLICE replicas demand 0 chips — they queue (and rank,
    and count in the decision log) but never contend for the pool,
    exactly like a CPU-only gang on an accelerator cluster."""

    chips = 0
    for rtype, rspec in job.spec.replica_specs.items():
        if rtype is not ReplicaType.TPU_SLICE:
            continue
        try:
            per_slice = parse_tpu_topology(rspec.tpu_topology)
        except ValueError:
            continue  # validation rejects this at admission
        chips += int(rspec.replicas or 0) * per_slice
    return chips


def slice_chips(job: TPUJob) -> int:
    """Chips of ONE slice replica (0 when the job has none)."""

    rspec = job.spec.replica_specs.get(ReplicaType.TPU_SLICE)
    if rspec is None:
        return 0
    try:
        return parse_tpu_topology(rspec.tpu_topology)
    except ValueError:
        return 0


@dataclass
class SchedulerDecision:
    """One scheduling decision — what the event, the ``GET /scheduler``
    log entry, and the observedHealth block all describe."""

    time: float
    job_key: str
    #: "queue" | "admit" | "shed" | "revoke"
    action: str
    priority_class: str
    quota_group: str
    reason: str
    details: Dict[str, Any] = field(default_factory=dict)

    @property
    def event_reason(self) -> str:
        return {
            "queue": "Queued",
            "admit": "Admitted",
            "shed": "Preempted",
            "revoke": "Preempted",
        }.get(self.action, "Scheduled")

    @property
    def event_type(self) -> str:
        return "Warning" if self.action in ("shed", "revoke") else "Normal"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "time": round(self.time, 3),
            "job": self.job_key,
            "action": self.action,
            "priorityClass": self.priority_class,
            "quotaGroup": self.quota_group,
            "reason": self.reason,
            "details": dict(self.details),
        }


class _GangState:
    """Runtime state of one fleet-managed job."""

    __slots__ = (
        "job", "phase", "priority_class", "rank", "quota_key", "demand",
        "queued_since", "queue_reason", "position", "admitted_at",
        "shed_target", "preempt_pending", "revoke_pending",
        "preempted_at", "preemptions", "was_preempted", "resume_pending",
        "last_preemption", "missing_since",
    )

    def __init__(self, job: TPUJob, now: float):
        self.job = job
        self.phase = "queued"
        sched = job.spec.scheduling
        self.priority_class = (
            sched.effective_priority_class() if sched else DEFAULT_PRIORITY_CLASS
        )
        self.rank = priority_rank(self.priority_class)
        group = (sched.quota_group if sched else "") or "default"
        self.quota_key = f"{job.metadata.namespace}/{group}"
        self.demand = gang_demand(job)
        self.queued_since = now
        self.queue_reason = "WaitingForCapacity"
        self.position = 0
        self.admitted_at = 0.0
        #: admitted-but-shed ceiling on TPU_SLICE replicas (overlay)
        self.shed_target: Optional[int] = None
        #: shed handshake — the reconciler bounces the slice set once
        self.preempt_pending = False
        #: revoke handshake — the reconciler stamps Preempted once
        self.revoke_pending = False
        self.preempted_at = 0.0
        self.preemptions = 0
        self.was_preempted = False
        #: set at re-admission of a preempted gang; the reconciler
        #: consumes it into the Resumed condition once Running again
        self.resume_pending = False
        self.last_preemption: Optional[Dict[str, Any]] = None
        #: first sweep the job was ABSENT from the lister snapshot (0 =
        #: currently listed); see the forget-grace note in _evaluate_locked
        self.missing_since = 0.0


class Scheduler:
    """The fleet queue controller.  Sharing model mirrors
    controller/autoscaler.Autoscaler: one instance per operator
    process (``default_scheduler``), attached to a controller's cached
    job lister + event callback + backend capacity probe, evaluated
    either by its own ticker thread or explicitly (tests, soaks)."""

    def __init__(
        self,
        metrics=None,
        interval: float = 5.0,
        max_decisions: int = MAX_DECISIONS,
        age_boost_seconds: float = AGE_BOOST_SECONDS,
        preemption_cooldown_seconds: float = PREEMPTION_COOLDOWN_SECONDS,
        max_victim_checkpoint_age_seconds: float = (
            MAX_VICTIM_CHECKPOINT_AGE_SECONDS
        ),
        missing_grace_seconds: float = MISSING_GRACE_SECONDS,
    ):
        if metrics is None:
            from tf_operator_tpu.utils.metrics import default_metrics

            metrics = default_metrics
        self.metrics = metrics
        self.interval = interval
        self.age_boost_seconds = age_boost_seconds
        self.preemption_cooldown_seconds = preemption_cooldown_seconds
        self.max_victim_checkpoint_age_seconds = max_victim_checkpoint_age_seconds
        self.missing_grace_seconds = missing_grace_seconds
        self._lock = threading.Lock()
        self._states: Dict[str, _GangState] = {}
        #: job key -> uid of an incarnation OBSERVED terminal.  Terminal
        #: is forever for one uid: a stale informer re-list can hand a
        #: sweep an old copy of a finished job without its Succeeded
        #: condition, and re-registering it would re-admit (and
        #: double-count) a job that already completed.  A recreated job
        #: (same name, new uid) registers normally.
        self._terminal_uids: Dict[str, str] = {}
        self._decisions: deque = deque(maxlen=max_decisions)
        self._quotas: Dict[str, float] = {}
        self._quota_gauge_keys: set = set()
        self._list_jobs: Optional[Callable[[], List[TPUJob]]] = None
        self._on_decision: Optional[Callable[[SchedulerDecision], None]] = None
        self._capacity: Optional[Callable[[], Optional[int]]] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- wiring -------------------------------------------------------------

    def attach(
        self,
        list_jobs: Callable[[], List[TPUJob]],
        on_decision: Optional[Callable[[SchedulerDecision], None]] = None,
        capacity: Optional[Callable[[], Optional[int]]] = None,
    ) -> None:
        with self._lock:
            self._list_jobs = list_jobs
            self._on_decision = on_decision
            self._capacity = capacity

    def detach(self, list_jobs: Callable[[], List[TPUJob]]) -> None:
        with self._lock:
            if self._list_jobs is list_jobs:
                self._list_jobs = None
                self._on_decision = None
                self._capacity = None

    def set_quota(
        self, namespace: str, group: str, chips: Optional[float]
    ) -> None:
        """Register (chips) or delete (None) the limit for one
        ``<namespace>/<group>`` quota key — cluster-operator config,
        deliberately NOT part of the job manifest."""

        key = f"{namespace}/{group or 'default'}"
        with self._lock:
            if chips is None:
                self._quotas.pop(key, None)
                self.metrics.clear_gauge(
                    "scheduler_quota_limit_chips", quota=key
                )
            else:
                self._quotas[key] = float(chips)
                self.metrics.set(
                    "scheduler_quota_limit_chips", float(chips), quota=key
                )

    # -- reconciler surface -------------------------------------------------

    def manages(self, job: TPUJob) -> bool:
        return job.spec.scheduling is not None

    def admission(self, job: TPUJob) -> str:
        """Register the job on first sight and return its fleet phase
        ("queued" | "admitted").  Registration is silent — the next
        ``evaluate_once`` sweep emits the queue/admit decision — but
        the queue-wait stamp starts NOW, so the stall rule and the age
        boost measure from arrival, not from the first sweep."""

        with self._lock:
            st = self._ensure_locked(job, time.time())
            return st.phase

    def apply(self, job: TPUJob) -> None:
        """Overlay the shed ceiling onto a WORKING CLONE of the job
        (the reconciler's in-sync copy — never the cached object),
        after the autoscaler's overlay: the scheduler's ceiling clamps
        whatever the autoscaler wanted, so the two cannot flap."""

        with self._lock:
            st = self._states.get(job.key)
            if st is None or st.phase != "admitted" or st.shed_target is None:
                return
            rspec = job.spec.replica_specs.get(ReplicaType.TPU_SLICE)
            if rspec is None:
                return
            current = int(rspec.replicas or 0)
            if current > st.shed_target:
                rspec.replicas = st.shed_target

    def take_preemption(self, job_key: str) -> Optional[int]:
        """Peek the pending shed bounce for this job: the TPU_SLICE
        replica target, or None.  Mirrors Autoscaler.take_reshard —
        peek here, act, then ``consume_preemption`` only after the
        pods are actually gone, so a crash between the two replays the
        bounce instead of losing it."""

        with self._lock:
            st = self._states.get(job_key)
            if st is None or not st.preempt_pending:
                return None
            return st.shed_target

    def consume_preemption(self, job_key: str) -> None:
        with self._lock:
            st = self._states.get(job_key)
            if st is not None:
                st.preempt_pending = False

    def take_revocation(self, job_key: str) -> Optional[Dict[str, Any]]:
        """Peek the pending whole-gang revocation (the reconciler
        stamps the Preempted condition + event from it, deletes the
        pods, then ``consume_revocation``)."""

        with self._lock:
            st = self._states.get(job_key)
            if st is None or not st.revoke_pending:
                return None
            return dict(st.last_preemption or {"mode": "revoke"})

    def consume_revocation(self, job_key: str) -> None:
        with self._lock:
            st = self._states.get(job_key)
            if st is not None:
                st.revoke_pending = False

    def take_resume(self, job_key: str) -> bool:
        with self._lock:
            st = self._states.get(job_key)
            return bool(st is not None and st.resume_pending)

    def consume_resume(self, job_key: str) -> None:
        with self._lock:
            st = self._states.get(job_key)
            if st is not None:
                st.resume_pending = False

    def queue_reason(self, job_key: str) -> str:
        with self._lock:
            st = self._states.get(job_key)
            return st.queue_reason if st is not None else "WaitingForCapacity"

    def health_block(self, job: TPUJob) -> Optional[Dict[str, Any]]:
        """The ``observedHealth["scheduler"]`` sub-block (camelCase,
        like the autoscaler's)."""

        with self._lock:
            st = self._states.get(job.key)
            if st is None:
                return None
            block: Dict[str, Any] = {
                "phase": st.phase,
                "priorityClass": st.priority_class,
                "quotaGroup": st.quota_key,
            }
            if st.phase == "queued":
                block["queuePosition"] = st.position
                # the STABLE stamp, not a wait age: this block is
                # compared by the health rollup's write throttle, and
                # an ever-changing age would turn every sync into a
                # status write (readers derive the age)
                block["queuedSinceUnix"] = round(st.queued_since, 3)
                block["reason"] = st.queue_reason
            if st.shed_target is not None:
                block["shedTo"] = st.shed_target
            if st.preemptions:
                block["preemptions"] = st.preemptions
            if st.last_preemption is not None:
                block["lastPreemption"] = dict(st.last_preemption)
            return block

    def forget(self, job_key: str) -> None:
        """Mark a deleted/terminal job for removal.

        Soft on purpose: the reconciler calls this when the job is gone
        from ITS informer cache, and under apiserver faults a broken
        watch's re-list can make a live job vanish for one sync.  The
        mark starts the same missing-grace clock the evaluator uses —
        a real deletion stays absent from the lister and is dropped
        when the grace expires, a cache blip re-lists the job and the
        next sweep clears the mark; a job observed terminal is dropped
        (and tombstoned) by the sweep itself, immediately."""

        with self._lock:
            st = self._states.get(job_key)
            if st is None:
                return
            if self.missing_grace_seconds <= 0:
                self._forget_locked(job_key)
            elif st.missing_since == 0.0:
                st.missing_since = time.time()

    def _forget_locked(self, job_key: str) -> None:
        self._states.pop(job_key, None)
        self.metrics.clear_gauge("scheduler_queue_position", job=job_key)
        self.metrics.clear_gauge("scheduler_queued_since_unix", job=job_key)

    # -- backend victim routing (satellite: no more blind LIFO) -------------

    def choose_victims(self, candidates: List[Dict[str, Any]]) -> List[str]:
        """Order revocation candidates for a backend capacity shrink.

        ``candidates`` are granted gangs in GRANT ORDER, each
        ``{"key": "<ns>/<name>", "chips": int}``.  Returns ALL
        candidate keys in victim order (the backend revokes a prefix
        until the rest fit): lowest priority class first, then
        latest-granted first within a class.  Gangs the fleet queue
        does not manage rank as the default class — so a fleet "low"
        job is sacrificed before unmanaged work, and unmanaged work
        before fleet "high", keeping one coherent policy across both
        admission paths."""

        default_rank = priority_rank(DEFAULT_PRIORITY_CLASS)
        with self._lock:

            def key(item):
                idx, cand = item
                st = self._states.get(cand.get("key", ""))
                rank = st.rank if st is not None else default_rank
                return (rank, -idx)

            ordered = sorted(enumerate(candidates), key=key)
        return [cand.get("key", "") for _, cand in ordered]

    def note_revoked(self, job_key: str, by: str = "capacity-shrink") -> None:
        """Backend-side revocation report: the backend already pulled
        the grant (capacity shrank underneath it) and killed the pods —
        park the gang NOW, synchronously, so a reconciler sync that
        lands between the backend's kill and the next scheduler sweep
        reads "queued" and tears down gracefully instead of reading the
        exit-137 corpses as replica failures and failing the job.  The
        demand==need call forces the revoke branch (the whole grant is
        gone; there is nothing left to shed)."""

        now = time.time()
        emitted: List[SchedulerDecision] = []
        with self._lock:
            st = self._states.get(job_key)
            if st is None or st.phase != "admitted":
                return

            def decide(stx, action, reason, **details):
                d = SchedulerDecision(
                    time=now,
                    job_key=stx.job.key,
                    action=action,
                    priority_class=stx.priority_class,
                    quota_group=stx.quota_key,
                    reason=reason,
                    details=details,
                )
                self._decisions.append(d)
                emitted.append(d)

            self._preempt_locked(
                st, need=max(1, st.demand), now=now, by=by,
                reason_label="capacity", decide=decide,
            )
            cb = self._on_decision
        for d in emitted:
            if cb is not None:
                try:
                    cb(d)
                except Exception as e:  # noqa: BLE001 - observer must not wedge
                    logger_for_job("-", "scheduler").warning(
                        "decision observer failed: %s", e
                    )

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """``GET /scheduler``: pending queue (priority-then-age order
        with positions), admitted set, quota accounting, and the
        decision log newest-first."""

        now = time.time()
        with self._lock:
            pending = [
                st for st in self._states.values() if st.phase == "queued"
            ]
            pending.sort(key=lambda st: self._queue_sort_key(st, now))
            queue = [
                {
                    "job": st.job.key,
                    "priorityClass": st.priority_class,
                    "quotaGroup": st.quota_key,
                    "position": i + 1,
                    "waitSeconds": round(max(0.0, now - st.queued_since), 1),
                    "demandChips": st.demand,
                    "reason": st.queue_reason,
                }
                for i, st in enumerate(pending)
            ]
            admitted = [
                {
                    "job": st.job.key,
                    "priorityClass": st.priority_class,
                    "quotaGroup": st.quota_key,
                    "demandChips": st.demand,
                    "admittedAt": round(st.admitted_at, 3),
                    **(
                        {"shedTo": st.shed_target}
                        if st.shed_target is not None
                        else {}
                    ),
                }
                for st in self._states.values()
                if st.phase == "admitted"
            ]
            admitted.sort(key=lambda a: a["job"])
            used: Dict[str, float] = {}
            for st in self._states.values():
                if st.phase == "admitted":
                    used[st.quota_key] = used.get(st.quota_key, 0.0) + st.demand
            quotas = {
                k: {
                    "limitChips": self._quotas.get(k),
                    "usedChips": used.get(k, 0.0),
                }
                for k in sorted(set(self._quotas) | set(used))
            }
            decisions = [d.to_dict() for d in reversed(self._decisions)]
        return {
            "queue": queue,
            "admitted": admitted,
            "quotas": quotas,
            "decisions": decisions,
        }

    # -- evaluation ---------------------------------------------------------

    def evaluate_once(self, now: Optional[float] = None) -> None:
        """One scheduling sweep.  Decision callbacks run OUTSIDE the
        lock (they enqueue reconciler syncs / record events)."""

        if now is None:
            now = time.time()
        with self._lock:
            lister = self._list_jobs
        if lister is None:
            return
        try:
            jobs = list(lister())
        except Exception:  # noqa: BLE001 - lister races job deletion
            return
        with self._lock:
            decisions = self._evaluate_locked(jobs, now)
            cb = self._on_decision
        self.metrics.inc("scheduler_evaluations_total")
        for d in decisions:
            if cb is not None:
                try:
                    cb(d)
                except Exception as e:  # noqa: BLE001 - observer must not wedge
                    logger_for_job("-", "scheduler").warning(
                        "decision observer failed: %s", e
                    )

    def _ensure_locked(self, job: TPUJob, now: float) -> _GangState:
        st = self._states.get(job.key)
        if st is not None and (
            (job.metadata.uid or "") != (st.job.metadata.uid or "")
        ):
            # same name, new incarnation (deleted + recreated inside
            # the forget grace): the old grant must not carry over
            self._forget_locked(job.key)
            st = None
        if st is None:
            st = _GangState(job, now)
            self._states[job.key] = st
            self.metrics.set(
                "scheduler_queued_since_unix", st.queued_since, job=job.key
            )
        else:
            st.job = job
        return st

    def _queue_sort_key(self, st: _GangState, now: float):
        boost = 0
        if self.age_boost_seconds > 0:
            boost = int(max(0.0, now - st.queued_since) // self.age_boost_seconds)
        return (-(st.rank + boost), st.queued_since, st.job.key)

    def _victim_sort_key(self, st: _GangState, now: float):
        age = job_checkpoint_age(st.job, now, self.metrics)
        return (
            st.rank,
            -st.admitted_at,
            age if age is not None else float("inf"),
        )

    def _evaluate_locked(
        self, jobs: List[TPUJob], now: float
    ) -> List[SchedulerDecision]:
        decisions: List[SchedulerDecision] = []

        def decide(
            st: _GangState, action: str, reason: str, **details
        ) -> None:
            d = SchedulerDecision(
                time=now,
                job_key=st.job.key,
                action=action,
                priority_class=st.priority_class,
                quota_group=st.quota_key,
                reason=reason,
                details=details,
            )
            self._decisions.append(d)
            decisions.append(d)

        # 1. refresh the managed set from the lister snapshot.  Jobs
        # OBSERVED terminal/unmanaged drop immediately; jobs merely
        # ABSENT get a grace window before their state is forgotten —
        # the lister is an informer cache, and a watch re-list under
        # apiserver faults can briefly hand us a snapshot missing live
        # jobs (see MISSING_GRACE_SECONDS)
        live: Dict[str, _GangState] = {}
        dropped: set = set()
        for job in jobs:
            uid = job.metadata.uid or ""
            if (
                not self.manages(job)
                or job.invalid_reason is not None
                or job.is_terminal()
            ):
                if self.manages(job) and job.is_terminal():
                    self._terminal_uids[job.key] = uid
                    while len(self._terminal_uids) > 1024:
                        self._terminal_uids.pop(
                            next(iter(self._terminal_uids))
                        )
                dropped.add(job.key)
                continue
            if self._terminal_uids.get(job.key) == uid:
                # stale re-list resurrecting a finished incarnation
                dropped.add(job.key)
                continue
            st = self._ensure_locked(job, now)
            st.missing_since = 0.0
            # spec may have changed underneath us (user edit); demand
            # follows the spec, clamped by any standing shed ceiling
            st.demand = self._effective_demand(st)
            live[job.key] = st
        for key in [k for k in self._states if k not in live]:
            st = self._states[key]
            if key not in dropped:
                if st.missing_since == 0.0:
                    st.missing_since = now
                if now - st.missing_since < self.missing_grace_seconds:
                    # lister blip: keep the gang (cached job object)
                    # so queue age, grants, and cooldowns survive
                    live[key] = st
                    continue
            self._forget_locked(key)

        # 2. capacity + usage
        capacity: Optional[int] = None
        if self._capacity is not None:
            try:
                capacity = self._capacity()
            except Exception:  # noqa: BLE001 - backend probe is advisory
                capacity = None
        used = sum(
            st.demand for st in live.values() if st.phase == "admitted"
        )

        # 3. capacity-shrink reclaim: the pool shrank beneath admitted
        # demand — reclaim by victim policy, NO checkpoint gate (the
        # chips are already gone; see module docstring)
        if capacity is not None and used > capacity:
            victims = sorted(
                (st for st in live.values() if st.phase == "admitted"),
                key=lambda st: self._victim_sort_key(st, now),
            )
            for v in victims:
                if used <= capacity:
                    break
                used -= self._preempt_locked(
                    v, need=used - capacity, now=now, by="capacity-shrink",
                    reason_label="capacity", decide=decide,
                )

        # 4. queue pass: admit in priority × age order, electively
        # preempting strictly-lower classes when the pool is full
        pending = sorted(
            (st for st in live.values() if st.phase == "queued"),
            key=lambda st: self._queue_sort_key(st, now),
        )
        for st in pending:
            limit = self._quotas.get(st.quota_key)
            if limit is not None:
                quota_used = sum(
                    o.demand
                    for o in live.values()
                    if o.phase == "admitted" and o.quota_key == st.quota_key
                )
                if quota_used + st.demand > limit:
                    if st.queue_reason != "QuotaExceeded":
                        st.queue_reason = "QuotaExceeded"
                        decide(
                            st, "queue",
                            f"quota {st.quota_key} at "
                            f"{quota_used:g}/{limit:g} chips",
                            demandChips=st.demand,
                        )
                    continue
            if capacity is not None and used + st.demand > capacity:
                freed = self._elective_preemption_locked(
                    st, need=used + st.demand - capacity, now=now,
                    live=live, decide=decide,
                )
                used -= freed
                if used + st.demand > capacity:
                    if st.queue_reason != "WaitingForCapacity":
                        st.queue_reason = "WaitingForCapacity"
                    if not any(
                        d.job_key == st.job.key and d.action == "queue"
                        for d in self._decisions
                    ):
                        decide(
                            st, "queue",
                            f"needs {st.demand} chips, "
                            f"{max(0, (capacity or 0) - used)} free",
                            demandChips=st.demand,
                        )
                    continue
            # admit
            st.phase = "admitted"
            st.admitted_at = now
            wait = max(0.0, now - st.queued_since)
            st.position = 0
            self.metrics.clear_gauge(
                "scheduler_queue_position", job=st.job.key
            )
            self.metrics.clear_gauge(
                "scheduler_queued_since_unix", job=st.job.key
            )
            self.metrics.inc("scheduler_admitted_total")
            used += st.demand
            reason = f"rank {st.rank} ({st.priority_class}), waited {wait:.0f}s"
            if st.was_preempted:
                st.resume_pending = True
                reason += "; resuming from checkpoint after preemption"
            decide(st, "admit", reason, demandChips=st.demand,
                   waitSeconds=round(wait, 1))

        # 5. gauges: queue positions + quota usage
        still_pending = [
            st for st in live.values() if st.phase == "queued"
        ]
        still_pending.sort(key=lambda st: self._queue_sort_key(st, now))
        for i, st in enumerate(still_pending):
            st.position = i + 1
            self.metrics.set(
                "scheduler_queue_position", float(i + 1), job=st.job.key
            )
            self.metrics.set(
                "scheduler_queued_since_unix", st.queued_since, job=st.job.key
            )
        quota_used: Dict[str, float] = {k: 0.0 for k in self._quota_gauge_keys}
        for st in live.values():
            if st.phase == "admitted":
                quota_used[st.quota_key] = (
                    quota_used.get(st.quota_key, 0.0) + st.demand
                )
        for k, v in quota_used.items():
            if v <= 0 and k not in self._quotas:
                self._quota_gauge_keys.discard(k)
                self.metrics.clear_gauge("scheduler_quota_used_chips", quota=k)
            else:
                self._quota_gauge_keys.add(k)
                self.metrics.set("scheduler_quota_used_chips", v, quota=k)
        return decisions

    def _effective_demand(self, st: _GangState) -> int:
        demand = gang_demand(st.job)
        if st.phase == "admitted" and st.shed_target is not None:
            per = slice_chips(st.job)
            rspec = st.job.spec.replica_specs.get(ReplicaType.TPU_SLICE)
            declared = int(rspec.replicas or 0) if rspec is not None else 0
            demand -= max(0, declared - st.shed_target) * per
        return max(0, demand)

    def _elective_preemption_locked(
        self, st: _GangState, need: int, now: float,
        live: Dict[str, _GangState], decide,
    ) -> int:
        """Free >= ``need`` chips for ``st`` by preempting admitted
        gangs of STRICTLY lower class rank (true class, never the
        age-boosted rank — a boosted "low" may outrank "high" for
        admission order, but may never evict it).  Returns chips
        actually freed (0 when no eligible victim set covers the
        need — all-or-nothing, a half-preemption helps nobody)."""

        victims = [
            v
            for v in live.values()
            if v.phase == "admitted"
            and v.rank < st.rank
            and now - v.admitted_at >= self.preemption_cooldown_seconds
            and (
                v.preempted_at == 0.0
                or now - v.preempted_at >= self.preemption_cooldown_seconds
            )
        ]
        victims.sort(key=lambda v: self._victim_sort_key(v, now))
        plan: List[_GangState] = []
        plannable = 0
        for v in victims:
            if plannable >= need:
                break
            age = job_checkpoint_age(v.job, now, self.metrics)
            if age is None or age > self.max_victim_checkpoint_age_seconds:
                self.metrics.inc(
                    "scheduler_skipped_total", reason="checkpoint_stale"
                )
                continue
            plan.append(v)
            # counted in full — _preempt_locked sheds only what the
            # need requires and revokes whole otherwise
            plannable += v.demand
        if plannable < need:
            return 0
        freed = 0
        for v in plan:
            if freed >= need:
                break
            freed += self._preempt_locked(
                v, need=need - freed, now=now, by=st.job.key,
                reason_label=st.priority_class, decide=decide,
            )
        return freed

    def _preempt_locked(
        self, v: _GangState, need: int, now: float, by: str,
        reason_label: str, decide,
    ) -> int:
        """Reclaim chips from one admitted victim: SHED whole slices
        when that covers the need and leaves >= 1 slice, else REVOKE
        the gang back to the queue.  Returns chips freed."""

        per = slice_chips(v.job)
        current = v.demand // per if per > 0 else 0
        shed_by = -(-need // per) if per > 0 else 0  # ceil
        if per > 0 and 0 < shed_by < current:
            target = current - shed_by
            v.shed_target = target
            v.preempt_pending = True
            v.demand = target * per
            v.preempted_at = now
            v.preemptions += 1
            v.was_preempted = True
            v.last_preemption = {
                "time": round(now, 3),
                "mode": "shed",
                "by": by,
                "fromSlices": current,
                "toSlices": target,
            }
            self.metrics.inc(
                "scheduler_preemptions_total",
                victim_priority=v.priority_class,
                reason="shed",
            )
            decide(
                v, "shed",
                f"shed {shed_by} slice(s) for {by}",
                by=by, fromSlices=current, toSlices=target,
                freedChips=shed_by * per,
            )
            return shed_by * per
        # whole-gang revoke
        freed = v.demand
        v.phase = "queued"
        v.queued_since = now
        v.queue_reason = "Preempted"
        v.shed_target = None
        v.preempt_pending = False
        v.revoke_pending = True
        v.preempted_at = now
        v.preemptions += 1
        v.was_preempted = True
        v.last_preemption = {
            "time": round(now, 3),
            "mode": "revoke",
            "by": by,
        }
        v.demand = gang_demand(v.job)
        self.metrics.set(
            "scheduler_queued_since_unix", v.queued_since, job=v.job.key
        )
        self.metrics.inc(
            "scheduler_preemptions_total",
            victim_priority=v.priority_class,
            reason="revoke",
        )
        decide(
            v, "revoke", f"gang revoked for {by}", by=by, freedChips=freed,
        )
        return freed

    # -- ticker -------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-scheduler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.evaluate_once()
            except Exception as e:  # noqa: BLE001 - the ticker must survive
                logger_for_job("-", "scheduler").error(
                    "evaluation sweep failed: %s", e
                )


#: process-global instance (the sharing model of default_metrics /
#: default_engine / default_autoscaler): kubesim's debug route and the
#: operator API serve this one unless handed another
default_scheduler = Scheduler()
