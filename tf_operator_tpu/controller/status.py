"""Status engine: pod phases → replica statuses → job conditions.

Parity: ``updateStatusSingle`` / ``updateTFJobConditions`` /
``initializeReplicaStatuses`` / ``updateJobReplicaStatuses``
(SURVEY.md §2 "Status engine", §3.2 tail).  Rules encoded:

- conditions are a list of typed entries; setting a condition appends or
  updates it, and setting Running/Succeeded/Failed/Restarting flips the
  mutually-exclusive peers to False (Created stays True forever once set).
- job Running when the coordinator-bearing replica has an active pod (or,
  with no chief, when any worker runs).
- success policy (SURVEY.md §2 "TFJob API types"): with a chief, chief
  success ends the job; without, DEFAULT = worker-0 success ends it,
  ALL_WORKERS = every worker must succeed.  TPU_SLICE replicas are
  treated as workers for success purposes, except gang semantics make
  ALL members required under DEFAULT too — a slice is whole or nothing.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import (
    CHIEF_LIKE,
    JobCondition,
    JobConditionType,
    PodPhase,
    ReplicaStatus,
    ReplicaType,
    SuccessPolicy,
    TPUJob,
)
from tf_operator_tpu.backend.objects import Pod

#: condition types that are mutually exclusive "current state" markers
_EXCLUSIVE = {
    JobConditionType.RUNNING,
    JobConditionType.RESTARTING,
    JobConditionType.SUCCEEDED,
    JobConditionType.FAILED,
}


def set_condition(job: TPUJob, ctype: JobConditionType, reason: str, message: str) -> bool:
    """Append/update a condition; returns True if anything changed."""

    now = time.time()
    changed = False
    if ctype in _EXCLUSIVE:
        for c in job.status.conditions:
            if c.type in _EXCLUSIVE and c.type is not ctype and c.status:
                c.status = False
                c.last_transition_time = now
                c.last_update_time = now
                changed = True
    existing = job.status.condition(ctype)
    if existing is None:
        job.status.conditions.append(
            JobCondition(
                type=ctype,
                status=True,
                reason=reason,
                message=message,
                last_update_time=now,
                last_transition_time=now,
            )
        )
        return True
    if (
        not existing.status
        or existing.reason != reason
        or existing.message != message
    ):
        # message-only changes matter too: the Degraded condition's
        # message lists the firing alert names, which can change while
        # the reason stays the same (one more rule joins the episode).
        # But lastTransitionTime moves only when the STATUS or reason
        # actually changes (k8s convention) — "degraded for X" must not
        # reset because one more rule joined the same episode
        if not existing.status or existing.reason != reason:
            existing.last_transition_time = now
        existing.status = True
        existing.reason = reason
        existing.message = message
        existing.last_update_time = now
        return True
    return changed


def clear_condition(
    job: TPUJob, ctype: JobConditionType, reason: str, message: str
) -> bool:
    """Flip a condition to status=False (it stays in the list as
    history, k8s-style).  Returns True if it was True — the health
    rollup uses this to event exactly once on Degraded→recovered."""

    c = job.status.condition(ctype)
    if c is None or not c.status:
        return False
    now = time.time()
    c.status = False
    c.reason = reason
    c.message = message
    c.last_update_time = now
    c.last_transition_time = now
    return True


def initialize_replica_statuses(job: TPUJob) -> None:
    for rtype in job.spec.replica_specs:
        job.status.replica_statuses[rtype] = ReplicaStatus()


def update_replica_statuses(job: TPUJob, pods_by_type: Dict[ReplicaType, List[Pod]]) -> None:
    # iterate spec types (not just types with pods) so a type scaled to
    # zero pods gets its counts reset instead of going permanently stale
    for rtype in set(job.spec.replica_specs) | set(pods_by_type):
        pods = pods_by_type.get(rtype, [])
        rs = job.status.replica_statuses.setdefault(rtype, ReplicaStatus())
        rs.active = sum(1 for p in pods if p.phase in (PodPhase.PENDING, PodPhase.RUNNING))
        rs.succeeded = sum(1 for p in pods if p.phase is PodPhase.SUCCEEDED)
        rs.failed = sum(1 for p in pods if p.phase is PodPhase.FAILED)


def _find(pods: List[Pod], index: int) -> Optional[Pod]:
    for p in pods:
        if p.replica_index == index:
            return p
    return None


def chief_type(job: TPUJob) -> Optional[ReplicaType]:
    for rtype in CHIEF_LIKE:
        if rtype in job.spec.replica_specs:
            return rtype
    return None


def _worker_like(job: TPUJob) -> List[ReplicaType]:
    return [
        t
        for t in (ReplicaType.WORKER, ReplicaType.TPU_SLICE)
        if t in job.spec.replica_specs and int(job.spec.replica_specs[t].replicas or 0) > 0
    ]


def evaluate_success(
    job: TPUJob, pods_by_type: Dict[ReplicaType, List[Pod]]
) -> Tuple[bool, str]:
    """(job_succeeded, reason) — dispatches to the native decision core
    when available (controller/plan.py); the Python truth table below
    remains the reference implementation and the fallback."""

    from tf_operator_tpu.controller.plan import evaluate_success as _dispatch

    return _dispatch(job, pods_by_type)


def _evaluate_success_py(
    job: TPUJob, pods_by_type: Dict[ReplicaType, List[Pod]]
) -> Tuple[bool, str]:
    """(job_succeeded, reason).  The success-policy truth table."""

    chief = chief_type(job)
    if chief is not None:
        pods = pods_by_type.get(chief, [])
        pod0 = _find(pods, 0)
        if pod0 is not None and pod0.phase is PodPhase.SUCCEEDED:
            return True, f"{chief.value} replica succeeded"
        return False, ""

    workers = _worker_like(job)
    if not workers:
        # evaluator/ps-only jobs: all replicas succeeding ends the job
        all_pods = [p for ps in pods_by_type.values() for p in ps]
        if all_pods and all(p.phase is PodPhase.SUCCEEDED for p in all_pods):
            return True, "all replicas succeeded"
        return False, ""

    if job.spec.success_policy is SuccessPolicy.ALL_WORKERS:
        for rtype in workers:
            want = job.spec.pod_count(rtype)
            rs = [p for p in pods_by_type.get(rtype, []) if p.phase is PodPhase.SUCCEEDED]
            if len(rs) < want:
                return False, ""
        return True, "all workers succeeded"

    # DEFAULT policy.  TPU_SLICE gangs: every slice member must finish
    # (an atomic slice has no meaningful "member 0 finished early") —
    # including when ordinary workers coexist with slices, where BOTH
    # the slice gang and worker-0 must succeed before the job is done.
    if ReplicaType.TPU_SLICE in workers:
        # every pod of every slice (all hosts) must finish
        want = job.spec.pod_count(ReplicaType.TPU_SLICE)
        done = sum(
            1
            for p in pods_by_type.get(ReplicaType.TPU_SLICE, [])
            if p.phase is PodPhase.SUCCEEDED
        )
        if done < want:
            return False, ""
        if ReplicaType.WORKER not in workers:
            return True, "all slice members succeeded"
        worker0 = _find(pods_by_type.get(ReplicaType.WORKER, []), 0)
        if worker0 is not None and worker0.phase is PodPhase.SUCCEEDED:
            return True, "all slice members and worker 0 succeeded"
        return False, ""

    worker0 = _find(pods_by_type.get(ReplicaType.WORKER, []), 0)
    if worker0 is not None and worker0.phase is PodPhase.SUCCEEDED:
        return True, "worker 0 succeeded"
    return False, ""


def is_running(job: TPUJob, pods_by_type: Dict[ReplicaType, List[Pod]]) -> bool:
    chief = chief_type(job)
    if chief is not None:
        pods = pods_by_type.get(chief, [])
        pod0 = _find(pods, 0)
        return pod0 is not None and pod0.phase is PodPhase.RUNNING
    return any(
        p.phase is PodPhase.RUNNING for ps in pods_by_type.values() for p in ps
    )
