"""The TPUJob reconciler — syncTPUJob and friends.

Parity: the reference's reconcile path (SURVEY.md §3.2): work-queue key →
job lookup → terminal short-circuit → expectations guard → backoff/deadline
enforcement → per-replica-type pod+service reconcile (create missing
indices, apply restart policies, inject bootstrap env, gang annotations) →
status update through the status engine.

Level-triggered: every sync recomputes desired state from the cache and
diffs against observed pods; no step depends on remembering a previous
sync (informer resync heals missed events, SURVEY.md §5).

Restart-policy translation (no kubelet in our backends): ALWAYS and
ON_FAILURE are emulated operator-side — a failed pod is deleted and its
index recreated on the next sync (restart budget = RunPolicy.backoff_limit);
EXIT_CODE consults is_retryable_exit_code; NEVER leaves the failure on the
books.  The reference delegates ALWAYS/ON_FAILURE to kubelet in-place
restarts; semantics at the job level are identical (the replica comes
back with the same name/index/env; SURVEY.md §5 "Checkpoint / resume").
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tf_operator_tpu.api.types import (
    ANNOTATION_FABRIC_PORT,
    ANNOTATION_GANG_GROUP,
    ANNOTATION_TELEMETRY_PORT,
    LABEL_JOB_NAME,
    JobConditionType,
    PodPhase,
    ReplicaType,
    CleanPodPolicy,
    TPUJob,
    replica_labels,
    replica_name,
)
from tf_operator_tpu.api.validation import parse_tpu_topology
from tf_operator_tpu.backend.base import (
    AlreadyExistsError,
    ClusterBackend,
    NotFoundError,
    match_selector,
)
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.backend.objects import Pod, PodGroup, PodGroupPhase, Service
from tf_operator_tpu.bootstrap.cluster_spec import AddressResolver, dns_resolver
from tf_operator_tpu.bootstrap.tpu_env import worker_env
from tf_operator_tpu.controller.expectations import Expectations
from tf_operator_tpu.controller.informer import InformerCache
from tf_operator_tpu.controller.plan import sync_decide
from tf_operator_tpu.controller.status import (
    clear_condition,
    initialize_replica_statuses,
    is_running,
    set_condition,
    update_replica_statuses,
)
from tf_operator_tpu.utils.events import EventRecorder
from tf_operator_tpu.utils.logging import logger_for_job
from tf_operator_tpu.utils.metrics import Metrics, default_metrics
from tf_operator_tpu.utils.trace import Tracer, default_tracer


@dataclass
class ReconcilerConfig:
    #: global --enable-gang-scheduling flag (per-job spec can also enable)
    enable_gang_scheduling: bool = False
    #: inject reference-compatible TF_CONFIG next to the TPU env
    inject_tf_config: bool = True
    #: scheduler name stamped on gang pods (reference: volcano)
    gang_scheduler_name: str = "tpu-gang"
    resolver: AddressResolver = field(default=dns_resolver)
    #: decision core dispatch: None = native when available; False =
    #: Python twin (set by python-runtime controllers so use_native
    #: selects one stack end to end)
    use_native_decisions: Optional[bool] = None
    #: warn-log any sync slower than this (SURVEY.md §5 span logging);
    #: a thrashing job (expectations churn, hot requeue) surfaces here
    #: and in the tpujob_sync_duration_seconds histogram
    slow_sync_warn_seconds: float = 1.0
    #: observed-health rollup refresh floor: the block carries
    #: timestamps/ages that change every sync, so unthrottled it would
    #: turn every sync into a status write (and, on watch-fed stores,
    #: every status write into another sync).  A firing-set change
    #: bypasses the throttle — Degraded must land promptly.
    health_refresh_seconds: float = 5.0
    #: hard floor under health_refresh_seconds for liveness-only
    #: rewrites (nothing material changed — just updatedAt/ages).  The
    #: rollup's own status write feeds back as a watch event and
    #: another sync; at health_refresh_seconds=0 that feedback would
    #: livelock the queue rewriting updatedAt forever (each sync slow
    #: enough that round(now, 3) advances).  Material changes — the
    #: firing set, the autoscaler block — always bypass both throttles,
    #: so 0 still means "decisions and Degraded land immediately".
    health_rewrite_floor_seconds: float = 0.05
    #: observedHealth.throughputStepsPerSec is LIVE health: summary
    #: series whose newest record is older than this are ignored — a
    #: wedged trainer must not keep reporting its historical rate
    #: under a fresh updatedAt
    throughput_stale_seconds: float = 300.0
    #: fleet telemetry (ISSUE 15): inject a per-pod
    #: TPUJOB_TELEMETRY_PORT (+ the tpujob.dist/telemetry-port
    #: discovery annotation and the pod.create trace context) so every
    #: worker boots a scrapable telemetry server.  Off = pods export
    #: nothing, the pre-fleet behaviour.
    pod_telemetry: bool = True
    #: cross-pod KV fabric (ISSUE 17): allocate a per-pod
    #: TPUJOB_FABRIC_PORT (+ the tpujob.dist/fabric-port discovery
    #: annotation) so serving pods can export their prefix-fabric
    #: store and discover each other off live pod records — the
    #: telemetry-port mechanics, serving edition.  Off = no fabric
    #: port, pods serve standalone.
    pod_fabric: bool = True


class Reconciler:
    def __init__(
        self,
        job_store: JobStore,
        backend: ClusterBackend,
        cache: InformerCache,
        pod_expectations: Expectations,
        service_expectations: Expectations,
        recorder: Optional[EventRecorder] = None,
        metrics: Optional[Metrics] = None,
        config: Optional[ReconcilerConfig] = None,
        requeue_after: Optional[Callable[[str, float], None]] = None,
        tracer: Optional[Tracer] = None,
        alerts=None,
        autoscaler=None,
        telemetry=None,
        scheduler=None,
    ):
        self.jobs = job_store
        self.backend = backend
        self.cache = cache
        self.pod_exp = pod_expectations
        self.svc_exp = service_expectations
        self.recorder = recorder or EventRecorder()
        self.metrics = metrics or default_metrics
        self.config = config or ReconcilerConfig()
        self.tracer = tracer if tracer is not None else default_tracer
        self.requeue_after = requeue_after or (lambda key, delay: None)
        #: job key -> absolute deadline wakeup already scheduled
        self._deadline_scheduled: Dict[str, float] = {}
        #: utils/alerts.AlertEngine (None = no health rollup): the
        #: firing set drives the Degraded/SLOViolation condition and
        #: the observedHealth block published into TPUJob.status
        self.alerts = alerts
        #: controller/autoscaler.Autoscaler (None = no elastic scaling):
        #: its desired-replica overlay is applied to each sync's working
        #: copy, training resizes bounce the replica set (re-shard +
        #: resume), and its per-job state joins observedHealth
        self.autoscaler = autoscaler
        #: controller/telemetry.TelemetryScraper (None = no fleet
        #: plane): per-pod scrape rows join observedHealth — reads
        #: only, the scraper runs on its own thread and can never
        #: block a sync
        self.telemetry = telemetry
        #: controller/scheduler.Scheduler (None = no fleet queue):
        #: jobs declaring spec.scheduling create nothing until their
        #: gang is admitted; revocations park the job Queued, sheds
        #: bounce the slice set through the same re-shard path as the
        #: autoscaler, and the per-job block joins observedHealth
        self.scheduler = scheduler
        #: job key -> unix of the last health-rollup refresh (throttle)
        self._health_refreshed: Dict[str, float] = {}

    # ------------------------------------------------------------------ sync

    def sync(self, key: str) -> None:
        """One level-triggered reconcile of ``key`` ("<ns>/<name>").

        Span-instrumented (SURVEY.md §5): the whole sync runs under a
        ``reconcile <key>`` span (joining the enqueue trace when the
        controller started one; rooting a fresh trace when called
        directly), with child spans per plan step below.  Per-sync
        duration lands in the tpujob_sync_duration_seconds histogram,
        outcomes in tpujob_syncs_total{result=ok|error}, slow syncs
        warn-log WITH their trace id (exemplar linkage: the log line
        names the waterfall that explains it).
        """

        t0 = time.perf_counter()
        with self.tracer.span(f"reconcile {key}") as sp:
            try:
                self._sync(key)
            except Exception:
                self._observe_sync(key, time.perf_counter() - t0, "error", sp)
                raise
            self._observe_sync(key, time.perf_counter() - t0, "ok", sp)

    def _observe_sync(self, key: str, dt: float, result: str, span) -> None:
        self.metrics.observe_histogram("tpujob_sync_duration_seconds", dt)
        self.metrics.inc("tpujob_syncs_total", result=result)
        if dt >= self.config.slow_sync_warn_seconds:
            ns, _, name = key.partition("/")
            logger_for_job(ns, name).warning(
                "slow sync: %.3fs (threshold %.3fs, result=%s, trace=%s)",
                dt,
                self.config.slow_sync_warn_seconds,
                result,
                span.trace_id,
            )

    def _sync(self, key: str) -> None:
        job = self.cache.get_job(key)
        if job is None:
            # job deleted: expectations cleanup; owner-based GC of pods
            self.pod_exp.delete(key)
            self.svc_exp.delete(key)
            self._deadline_scheduled.pop(key, None)
            self._health_refreshed.pop(key, None)
            if self.autoscaler is not None:
                self.autoscaler.forget(key)
            if self.scheduler is not None:
                self.scheduler.forget(key)
            self._gc_orphans(key)
            return
        log = logger_for_job(job.metadata.namespace, job.metadata.name)

        if job.invalid_reason and not job.is_terminal():
            # server-side admission backstop (VERDICT r5 next #9): an
            # invalid object written out-of-band (no admission webhook)
            # is marked Failed/InvalidSpec + evented ONCE and never
            # reconciled — no pods, no services, no gang group
            old_status = job.status.clone()
            msg = f"invalid TPUJob spec: {job.invalid_reason}"
            self._clear_live_health(job)
            set_condition(job, JobConditionType.FAILED, "InvalidSpec", msg)
            self.recorder.event(key, "Warning", "InvalidSpec", msg)
            self.metrics.inc("tpujob_invalid_total")
            log.warning("refusing to reconcile: %s", msg)
            self._update_status(job, old_status)
            return

        if job.is_terminal():
            self._deadline_scheduled.pop(key, None)
            if job.invalid_reason:
                # terminal AND invalid (our own InvalidSpec mark, or a
                # corrupted finished job): nothing to clean up that the
                # spec-less skeleton could name — leave it be
                return
            self._cleanup_terminal(job)
            return

        if not (self.pod_exp.satisfied(key) and self.svc_exp.satisfied(key)):
            # cache can't be trusted yet; watch events will re-enqueue
            span = self.tracer.current_span()
            if span is not None:
                span.add_event(
                    "expectations.pending",
                    pods=self.pod_exp.pending(key),
                    services=self.svc_exp.pending(key),
                )
            return

        old_status = job.status.clone()
        if not job.status.replica_statuses:
            initialize_replica_statuses(job)
        if job.status.start_time is None:
            job.status.start_time = time.time()
            set_condition(
                job, JobConditionType.CREATED, "JobCreated", f"TPUJob {key} is created."
            )
            self.recorder.event(key, "Normal", "JobCreated", "job accepted by reconciler")

        # desired-replica overlay (controller/autoscaler.py): the
        # autoscaler's decisions overwrite replica counts on THIS
        # sync's working copy only — the stored spec stays the user's
        # declaration — so planning, services, gang sizing and success
        # evaluation all see one consistent scaled world
        if self.autoscaler is not None:
            self.autoscaler.apply(job)

        with self.tracer.span("pods.claim") as claim_sp:
            pods_by_type = self._claim_pods(job)
            claim_sp.set_attribute(
                "claimed", sum(len(v) for v in pods_by_type.values())
            )

        # fleet-scheduling gate (controller/scheduler.py): a job that
        # declared spec.scheduling creates NOTHING until the fleet
        # queue admits its whole gang, and a revoked gang is torn down
        # and parked Queued until capacity returns — the graceful half
        # of cross-job preemption
        if self.scheduler is not None and self.scheduler.manages(job):
            if not self._sync_scheduling(job, pods_by_type, old_status):
                return

        # elastic training resize: a decided re-shard bounces the whole
        # replica set — the world size is baked into every pod's
        # bootstrap env, so survivors must restart to form the new
        # world and resume from the latest checkpoint
        # (parallel/checkpoint.restore_latest redistributes the
        # artifact onto whatever mesh the survivors form)
        if self.autoscaler is not None and self._bounce_for_reshard(
            job, pods_by_type
        ):
            self._update_status(job, old_status)
            return

        # fleet-preemption shed: same bounce mechanics, scheduler-decided
        if self.scheduler is not None and self._bounce_for_preemption(
            job, pods_by_type
        ):
            self._update_status(job, old_status)
            return

        # ---- deadline / backoff enforcement (before creating anything)
        if self._past_active_deadline(job):
            self._fail_job(job, "DeadlineExceeded", "job ran past activeDeadlineSeconds")
            self._update_status(job, old_status)
            return
        self._schedule_deadline_wakeup(job)

        # ---- ONE batch decision call: success evaluation + every
        # replica type's plan (native syncdecide.cc when available)
        with self.tracer.span("plan.decide"):
            decision = sync_decide(
                job, pods_by_type, use_native=self.config.use_native_decisions
            )
        succeeded, reason = decision.succeeded, decision.reason
        if succeeded:
            update_replica_statuses(job, pods_by_type)
            job.status.completion_time = time.time()
            self._clear_live_health(job)
            set_condition(job, JobConditionType.SUCCEEDED, "JobSucceeded", reason)
            self.recorder.event(key, "Normal", "JobSucceeded", reason)
            self.metrics.inc("tpujob_jobs_succeeded_total")
            self._observe_completion(job)
            self._update_status(job, old_status)
            return

        # ---- gang group before any pod (all-or-nothing admission)
        gang = self.config.enable_gang_scheduling or job.spec.enable_gang_scheduling
        if gang:
            with self.tracer.span("podgroup.sync"):
                self._sync_pod_group(job)

        # ---- per-replica-type reconcile
        failed_fatal: Optional[str] = None
        restarting = False
        for rtype in job.spec.ordered_types():
            spec = job.spec.replica_specs[rtype]
            pods = pods_by_type.get(rtype, [])
            outcome = self._reconcile_pods(job, rtype, pods, gang, decision.plans[rtype])
            with self.tracer.span(f"services.reconcile {rtype.value}"):
                self._reconcile_services(job, rtype, spec)
            if outcome == "fatal" and failed_fatal is None:
                failed_fatal = f"{rtype.value} replica failed permanently"
            restarting = restarting or outcome == "restarting"

        update_replica_statuses(job, pods_by_type)

        if failed_fatal:
            # _reconcile_pods may already have set FAILED with a more
            # specific reason (BackoffLimitExceeded); don't overwrite it
            if not job.status.has_condition(JobConditionType.FAILED):
                self._fail_job(job, "ReplicaFailed", failed_fatal)
        elif restarting:
            set_condition(
                job, JobConditionType.RESTARTING, "ReplicaRestarting", "replica restart in flight"
            )
            self.metrics.inc("tpujob_jobs_restarted_total")
        elif is_running(job, pods_by_type):
            if not job.status.has_condition(JobConditionType.RUNNING):
                self._observe_startup_latency(job)
            set_condition(job, JobConditionType.RUNNING, "JobRunning", f"TPUJob {key} is running.")

        self._rollup_health(job)
        self._update_status(job, old_status)
        log.debug("sync complete")

    # ----------------------------------------------------------- pod claims

    def _claim_pods(self, job: TPUJob) -> Dict[ReplicaType, List[Pod]]:
        """ControllerRefManager parity (SURVEY.md §3.2 ClaimPods):

        - label-matching pod owned by us → claimed;
        - label-matching pod with NO owner → **adopted** (ownership
          patched through the backend) — an operator restart that minted
          a new job uid, or a manually created pod, re-enters management;
        - pod owned by us whose labels no longer match the selector →
          **orphaned** (ownership released; the pod stops being ours);
        - label-matching pod owned by a *different* controller → ignored.
        """

        ns = job.metadata.namespace
        selector = {LABEL_JOB_NAME: job.metadata.name}
        out: Dict[ReplicaType, List[Pod]] = {}
        # label-indexed read (client-go Indexer parity): O(own pods)
        for pod in self.cache.list_pods(ns, selector):
            owner = pod.metadata.owner_uid
            if owner and owner != job.metadata.uid:
                continue  # another controller's pod
            if not owner:
                try:
                    # NOTE re-entrancy: the fake/local backends emit the
                    # resulting MODIFIED event *synchronously under this
                    # call stack*, so the informer re-enqueues this job
                    # while its sync is still running.  That is safe —
                    # the workqueue dedupes and the follow-up sync is a
                    # no-op (tests/test_adoption.py pins it) — but a
                    # future backend that dispatches watch events on
                    # another thread must still deliver them through the
                    # informer (never mutate the cache directly), or the
                    # cloned-pod bookkeeping below goes stale.
                    self.backend.update_pod_owner(
                        ns, pod.metadata.name, job.metadata.uid
                    )
                except NotFoundError:
                    continue  # deleted under us: watch will re-sync
                except NotImplementedError:
                    pass  # backend can't patch: manage by label alone
                # never mutate the cached object in place — the cache
                # copy is shared and must only change via watch events
                pod = pod.clone()
                pod.metadata.owner_uid = job.metadata.uid
                self.recorder.event(
                    job.key, "Normal", "AdoptedPod",
                    f"adopted ownerless pod {pod.metadata.name}",
                )
            rtype = pod.replica_type
            if rtype is None:
                continue
            out.setdefault(rtype, []).append(pod)
        # orphan pass over the owner index: pods we own whose labels no
        # longer select them
        for pod in self.cache.list_pods_owned(job.metadata.uid):
            if pod.metadata.namespace != ns or match_selector(
                pod.metadata.labels, selector
            ):
                continue
            try:
                self.backend.update_pod_owner(ns, pod.metadata.name, None)
            except (NotFoundError, NotImplementedError):
                continue
            self.recorder.event(
                job.key, "Normal", "OrphanedPod",
                f"released pod {pod.metadata.name} (selector no longer matches)",
            )
        return out

    # --------------------------------------------------- elastic resize

    def _bounce_for_reshard(self, job: TPUJob, pods_by_type) -> bool:
        """Execute pending training resizes: delete every pod of the
        resized replica set (the next sync recreates them at the new
        world size with fresh bootstrap env; the training processes
        restore from the latest async checkpoint).  Returns True when
        anything was bounced — the caller ends the sync and lets the
        watch-confirmed deletions gate the recreate."""

        key = job.key
        bounced = False
        for rtype in self.autoscaler.take_reshard(key):
            live = [
                p
                for p in pods_by_type.get(rtype, [])
                if p.phase in (PodPhase.PENDING, PodPhase.RUNNING)
            ]
            if not live:
                # the set already finished (a resize decided while the
                # last pods were succeeding): resizing a completed set
                # would delete its success record and re-run the job —
                # drop the stale decision and let success evaluation
                # proceed this same sync
                self.autoscaler.consume_reshard(key, rtype)
                continue
            want = job.spec.pod_count(rtype)
            self.recorder.event(
                key, "Normal", "Resharding",
                f"elastic resize: restarting {rtype.value} replicas at "
                f"world size {want} (re-shard + resume from checkpoint)",
            )
            self.metrics.inc("tpujob_reshards_total")
            for p in pods_by_type.get(rtype, []):
                self._delete_pod(key, p)
            self.autoscaler.consume_reshard(key, rtype)
            bounced = True
        return bounced

    # --------------------------------------------------- fleet scheduling

    def _sync_scheduling(self, job: TPUJob, pods_by_type, old_status) -> bool:
        """Admission gate for fleet-managed jobs.  Returns True when
        the sync may proceed (gang admitted); False when the job was
        parked Queued (status written, sync over).

        Queued teardown is the GRACEFUL half of revocation: live pods
        are deleted (the trainer's async checkpoint survives on disk),
        the gang group is released so the chips actually free, and the
        job waits visibly — Queued condition, queue-position gauge,
        `tpujob_gang_waiting_replicas` — until the scheduler re-admits
        it, at which point the normal create path rebuilds the world
        and the trainer restores from its latest checkpoint."""

        key = job.key
        phase = self.scheduler.admission(job)
        if phase == "admitted":
            # the shed ceiling rides this sync's working copy, AFTER
            # the autoscaler's overlay — the scheduler only clamps, so
            # the two subsystems cannot fight (coexistence contract,
            # see controller/scheduler.py docstring)
            self.scheduler.apply(job)
            clear_condition(
                job, JobConditionType.QUEUED, "Admitted",
                "gang admitted by fleet scheduler",
            )
            if self.scheduler.take_resume(key):
                live = [
                    p
                    for pods in pods_by_type.values()
                    for p in pods
                    if p.phase is PodPhase.RUNNING
                ]
                if live:
                    msg = (
                        "resumed from latest checkpoint after preemption "
                        f"({len(live)} pods running)"
                    )
                    set_condition(
                        job, JobConditionType.RESUMED,
                        "ResumedFromCheckpoint", msg,
                    )
                    self.recorder.event(key, "Normal", "Resumed", msg)
                    self.scheduler.consume_resume(key)
            return True

        # ---- queued: tear down, park, wait
        reason = self.scheduler.queue_reason(key)
        rev = self.scheduler.take_revocation(key)
        if rev is not None:
            msg = (
                f"gang revoked by fleet scheduler (for {rev.get('by', 'capacity')}); "
                "queued for re-admission, will resume from checkpoint"
            )
            set_condition(job, JobConditionType.PREEMPTED, "GangRevoked", msg)
            self.recorder.event(key, "Warning", "Preempted", msg)
            self.scheduler.consume_revocation(key)
        # delete EVERY claimed pod, not just live ones: a backend
        # revocation fails its victims' pods (exit 137), and a corpse
        # left behind would be read as a replica failure at
        # re-admission — the parked gang must leave nothing to misread
        for pods in pods_by_type.values():
            for p in pods:
                self._delete_pod(key, p)
        # release the gang grant so the freed chips are really free
        # (the group is recreated by the normal path on re-admission)
        try:
            if self.backend.get_pod_group(
                job.metadata.namespace, job.metadata.name
            ):
                self.backend.delete_pod_group(
                    job.metadata.namespace, job.metadata.name
                )
        except NotFoundError:
            pass
        # Running is a live-state marker; a parked gang is not running
        clear_condition(
            job, JobConditionType.RUNNING, "GangQueued",
            "gang parked by fleet scheduler",
        )
        set_condition(
            job, JobConditionType.QUEUED, reason,
            f"gang waiting in fleet queue ({reason})",
        )
        # the whole gang is waiting — same gauge a Pending pod-group
        # drives, so the slice autoscaling policy and the queue agree
        self.metrics.set(
            "tpujob_gang_waiting_replicas",
            float(job.spec.total_pods()),
            job=key,
        )
        self._rollup_health(job)
        self._update_status(job, old_status)
        return False

    def _bounce_for_preemption(self, job: TPUJob, pods_by_type) -> bool:
        """Execute a scheduler-decided slice shed: delete the TPU_SLICE
        pods so the next sync recreates the set at the shed-to world
        size (same mechanics as _bounce_for_reshard — re-shard + resume
        from the latest async checkpoint, `dp`-only-over-DCN intact)."""

        key = job.key
        target = self.scheduler.take_preemption(key)
        if target is None:
            return False
        pods = pods_by_type.get(ReplicaType.TPU_SLICE, [])
        live = [
            p for p in pods if p.phase in (PodPhase.PENDING, PodPhase.RUNNING)
        ]
        if not live:
            # the set already finished — shedding a completed set would
            # re-run the job (same guard as the autoscaler bounce)
            self.scheduler.consume_preemption(key)
            return False
        want = job.spec.pod_count(ReplicaType.TPU_SLICE)
        msg = (
            f"fleet preemption: shedding to {target} slice(s) "
            f"(world size {want}; re-shard + resume from checkpoint)"
        )
        set_condition(job, JobConditionType.PREEMPTED, "SliceShed", msg)
        self.recorder.event(key, "Warning", "Preempted", msg)
        self.metrics.inc("tpujob_reshards_total")
        for p in pods:
            self._delete_pod(key, p)
        self.scheduler.consume_preemption(key)
        return True

    # ------------------------------------------------------- pod reconcile

    def _reconcile_pods(
        self,
        job: TPUJob,
        rtype: ReplicaType,
        pods: List[Pod],
        gang: bool,
        plan,
    ) -> str:
        """Returns "ok" | "restarting" | "fatal".

        ``plan`` is this type's slice of the sync's one batch decision
        (controller/plan.sync_decide — native C++ when available); this
        method executes it against the backend and records events/metrics.
        """

        key = job.key
        by_index: Dict[int, List[Pod]] = {}
        for p in pods:
            idx = p.replica_index
            if idx is not None:
                by_index.setdefault(idx, []).append(p)
        limit = job.spec.run_policy.backoff_limit

        # scale-in (dynamic workers): drop indices beyond the want count
        for idx in sorted(set(plan.scale_in)):
            for p in by_index.get(idx, []):
                self._delete_pod(key, p)
        for idx in plan.create:
            self._create_pod(job, rtype, idx, gang)

        outcome = "fatal" if plan.fatal else "ok"
        for idx, exit_code in plan.restart:
            job.status.restart_count += 1
            self.recorder.event(
                key,
                "Warning",
                "RestartingReplica",
                f"{rtype.value}-{idx} exited {exit_code}; restarting "
                f"({job.status.restart_count} restarts)",
            )
            self._delete_pod(key, by_index[idx][0])
            if outcome == "ok":
                outcome = "restarting"
        if plan.backoff_exceeded:
            self._fail_job(
                job,
                "BackoffLimitExceeded",
                f"restart budget exhausted ({limit})",
            )
            return "fatal"
        return outcome

    def _create_pod(self, job: TPUJob, rtype: ReplicaType, index: int, gang: bool) -> None:
        key = job.key
        name = replica_name(job.metadata.name, rtype, index)
        # the span opens BEFORE env construction: its (trace, span) ids
        # ride the pod env as the trace-stitching context (ISSUE 15) —
        # the harness roots the pod's train trace under this exact
        # pod.create span, and the telemetry scraper folds the pod's
        # spans back, so /traces/<trace-id> shows reconcile -> create
        # -> train as ONE waterfall
        with self.tracer.span(
            f"pod.create {name}",
            # the job attribute is the timeline endpoint's exact-match
            # key — span-NAME prefix matching would leak job "train"
            # into job "train-eval"'s timeline
            attributes={
                "replicaType": rtype.value, "index": index, "job": key,
            },
        ) as sp:
            template = job.spec.replica_specs[rtype].template
            containers = [c.clone() for c in template.containers]
            env = worker_env(
                job, rtype, index, self.config.resolver, tf_config=self.config.inject_tf_config
            )
            telemetry_port = None
            if self.config.pod_telemetry:
                from tf_operator_tpu.bootstrap.tpu_env import (
                    ENV_PARENT_SPAN_ID,
                    ENV_TELEMETRY_PORT,
                    ENV_TRACE_ID,
                )
                from tf_operator_tpu.controller.telemetry import (
                    alloc_telemetry_port,
                )

                telemetry_port = alloc_telemetry_port()
                env[ENV_TELEMETRY_PORT] = str(telemetry_port)
                env[ENV_TRACE_ID] = sp.trace_id
                env[ENV_PARENT_SPAN_ID] = sp.span_id
                sp.set_attribute("telemetryPort", telemetry_port)
            fabric_port = None
            if self.config.pod_fabric:
                from tf_operator_tpu.bootstrap.tpu_env import ENV_FABRIC_PORT
                from tf_operator_tpu.controller.telemetry import (
                    alloc_telemetry_port,
                )

                # same allocator as telemetry: bind port 0, let the OS
                # pick a free one, hand it to the pod by env + annotation
                fabric_port = alloc_telemetry_port()
                env[ENV_FABRIC_PORT] = str(fabric_port)
                sp.set_attribute("fabricPort", fabric_port)
            for c in containers:
                merged = dict(env)
                merged.update(c.env)  # user-specified env wins, like the reference
                c.env = merged

            pod = Pod(containers=containers)
            pod.metadata.name = name
            pod.metadata.namespace = job.metadata.namespace
            pod.metadata.owner_uid = job.metadata.uid
            pod.metadata.labels = {**template.labels, **replica_labels(job.metadata.name, rtype, index)}
            pod.metadata.annotations = dict(template.annotations)
            if telemetry_port is not None:
                # the discovery half: the scraper reads targets off
                # live pod records, so the pod record carries its port
                pod.metadata.annotations[ANNOTATION_TELEMETRY_PORT] = str(
                    telemetry_port
                )
            if fabric_port is not None:
                pod.metadata.annotations[ANNOTATION_FABRIC_PORT] = str(
                    fabric_port
                )
            pod.scheduler_name = template.scheduler_name
            pod.node_selector = dict(template.node_selector)
            if rtype is ReplicaType.TPU_SLICE:
                # per-POD chips = per-host share of the slice (a multi-host
                # slice runs one pod per host VM); ceil so Σ per-pod chips
                # never under-counts the gang group's whole-slice accounting
                spec_ts = job.spec.replica_specs[rtype]
                chips = parse_tpu_topology(spec_ts.tpu_topology)
                hosts = spec_ts.slice_host_count()
                pod.chip_request = max(1, -(-chips // hosts))
            if gang:
                pod.metadata.annotations[ANNOTATION_GANG_GROUP] = job.metadata.name
                pod.scheduler_name = pod.scheduler_name or self.config.gang_scheduler_name

            self.pod_exp.expect_creations(key, 1)
            try:
                self.backend.create_pod(pod)
            except AlreadyExistsError:
                # stale cache (expired expectation / informer lag):
                # reconcile again once the watch catches up
                sp.add_event("already-exists")
                self.pod_exp.creation_observed(key)
                return
            except Exception:
                self.pod_exp.creation_observed(key)
                raise
        self.metrics.inc("tpujob_pods_created_total", replica_type=rtype.value)
        self.recorder.event(key, "Normal", "SuccessfulCreatePod", f"created pod {name}")

    def _delete_pod(self, key: str, pod: Pod) -> None:
        self.pod_exp.expect_deletions(key, 1)
        with self.tracer.span(f"pod.delete {pod.metadata.name}") as sp:
            try:
                self.backend.delete_pod(pod.metadata.namespace, pod.metadata.name)
            except NotFoundError:
                sp.add_event("not-found")
                self.pod_exp.deletion_observed(key)
                return
            except Exception:
                self.pod_exp.deletion_observed(key)
                raise
        self.metrics.inc("tpujob_pods_deleted_total")
        self.recorder.event(key, "Normal", "SuccessfulDeletePod", f"deleted pod {pod.metadata.name}")

    # --------------------------------------------------- service reconcile

    def _reconcile_services(self, job: TPUJob, rtype: ReplicaType, spec) -> None:
        """One headless service per replica index (SURVEY.md §2 "Service
        reconciler") — the stable DNS names the cluster spec points at."""

        key = job.key
        want = job.spec.pod_count(rtype)
        prefix = f"{job.metadata.name}-{rtype.lower_name}-"
        existing = {
            s.metadata.name
            for s in self.cache.list_services(
                job.metadata.namespace, {LABEL_JOB_NAME: job.metadata.name}
            )
        }
        # scale-in: drop services for indices beyond the want count,
        # symmetric with the pod scale-in loop
        for name in existing:
            idx_s = name[len(prefix):] if name.startswith(prefix) else ""
            if idx_s.isdigit() and int(idx_s) >= want:
                self.svc_exp.expect_deletions(key, 1)
                try:
                    self.backend.delete_service(job.metadata.namespace, name)
                except NotFoundError:
                    self.svc_exp.deletion_observed(key)
                except Exception:
                    # balance the expectation on ANY failure (symmetric
                    # with _delete_pod) or the leaked expected-deletion
                    # stalls the job until the expectations timeout
                    self.svc_exp.deletion_observed(key)
                    raise

        from tf_operator_tpu.bootstrap.cluster_spec import _replica_port

        port = _replica_port(job, rtype)
        for idx in range(want):
            name = replica_name(job.metadata.name, rtype, idx)
            if name in existing:
                continue
            svc = Service(selector=replica_labels(job.metadata.name, rtype, idx), port=port)
            svc.metadata.name = name
            svc.metadata.namespace = job.metadata.namespace
            svc.metadata.owner_uid = job.metadata.uid
            svc.metadata.labels = replica_labels(job.metadata.name, rtype, idx)
            self.svc_exp.expect_creations(key, 1)
            try:
                self.backend.create_service(svc)
            except AlreadyExistsError:
                self.svc_exp.creation_observed(key)
            except Exception:
                self.svc_exp.creation_observed(key)
                raise

    # ------------------------------------------------------------- gang

    def _sync_pod_group(self, job: TPUJob) -> None:
        """SyncPodGroup parity (SURVEY.md §3.4): one group per job,
        min_member = total replicas, chip_request = Σ slice chips."""

        chips = 0
        slice_spec = job.spec.replica_specs.get(ReplicaType.TPU_SLICE)
        if slice_spec is not None:
            chips = parse_tpu_topology(slice_spec.tpu_topology) * int(slice_spec.replicas or 0)
        sp = job.spec.run_policy.scheduling_policy
        min_member = sp.min_member if sp and sp.min_member else job.spec.total_pods()
        existing = self.backend.get_pod_group(job.metadata.namespace, job.metadata.name)
        if existing is not None:
            # slice-loss signal (ISSUE 14): a gang stuck Pending means
            # the declared topology no longer fits the pool (capacity
            # shrink revoked it — kubesim/fake /_capacity semantics).
            # The gauge is what default_slice_training_policy binds, so
            # the autoscaler can shed whole slices and re-shard onto
            # the survivors instead of waiting forever.
            waiting = (
                min_member
                if existing.phase is PodGroupPhase.PENDING
                else 0
            )
            self.metrics.set(
                "tpujob_gang_waiting_replicas", float(waiting), job=job.key
            )
            # dynamic scale: keep gang size/chip accounting in step
            if existing.min_member != min_member or existing.chip_request != chips:
                self.backend.update_pod_group(
                    job.metadata.namespace, job.metadata.name, min_member, chips
                )
            return
        group = PodGroup(min_member=min_member, chip_request=chips)
        group.metadata.name = job.metadata.name
        group.metadata.namespace = job.metadata.namespace
        group.metadata.owner_uid = job.metadata.uid
        group.metadata.labels = {LABEL_JOB_NAME: job.metadata.name}
        try:
            self.backend.create_pod_group(group)
        except AlreadyExistsError:
            return
        self.recorder.event(
            job.key,
            "Normal",
            "CreatedPodGroup",
            f"gang group min_member={group.min_member} chips={chips}",
        )

    # ------------------------------------------------------ terminal paths

    def _clear_live_health(self, job: TPUJob) -> None:
        """Terminal paths drop LIVE health: the Degraded condition and
        the observedHealth block describe the run while it happens — a
        job that never syncs again must not keep reporting its last
        firing alerts (or a frozen checkpoint age) as current, and the
        condition would otherwise be pinned True forever."""

        clear_condition(
            job, JobConditionType.DEGRADED, "JobFinished",
            "terminal state clears degraded",
        )
        job.status.observed_health = {}
        # a finished job must not keep a gang-waiting level latched for
        # the slice autoscaling policies (per-object gauge hygiene —
        # the autoscaler_desired_replicas rule)
        self.metrics.clear_gauge("tpujob_gang_waiting_replicas", job=job.key)
        # same hygiene for the fleet queue: a finished job must not
        # hold a queue position, a stall stamp, or quota chips
        if self.scheduler is not None:
            self.scheduler.forget(job.key)

    def _fail_job(self, job: TPUJob, reason: str, message: str) -> None:
        job.status.completion_time = job.status.completion_time or time.time()
        self._clear_live_health(job)
        set_condition(job, JobConditionType.FAILED, reason, message)
        self.recorder.event(job.key, "Warning", "JobFailed", message)
        self.metrics.inc("tpujob_jobs_failed_total")

    def _cleanup_terminal(self, job: TPUJob) -> None:
        """CleanPodPolicy + TTL (SURVEY.md §3.5)."""

        policy = job.spec.run_policy.clean_pod_policy or CleanPodPolicy.RUNNING
        key = job.key
        pods = self.cache.list_pods(job.metadata.namespace, {LABEL_JOB_NAME: job.metadata.name})
        if policy is not CleanPodPolicy.NONE:
            for pod in pods:
                if policy is CleanPodPolicy.ALL or pod.phase in (
                    PodPhase.RUNNING,
                    PodPhase.PENDING,
                ):
                    self._delete_pod(key, pod)
            for svc in self.cache.list_services(
                job.metadata.namespace, {LABEL_JOB_NAME: job.metadata.name}
            ):
                self.svc_exp.expect_deletions(key, 1)
                try:
                    self.backend.delete_service(svc.metadata.namespace, svc.metadata.name)
                except NotFoundError:
                    self.svc_exp.deletion_observed(key)
        try:
            if self.backend.get_pod_group(job.metadata.namespace, job.metadata.name):
                self.backend.delete_pod_group(job.metadata.namespace, job.metadata.name)
        except NotFoundError:
            pass

        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is not None and job.status.completion_time is not None:
            remaining = job.status.completion_time + ttl - time.time()
            if remaining <= 0:
                try:
                    self.jobs.delete(job.metadata.namespace, job.metadata.name)
                except NotFoundError:
                    pass
            else:
                self.requeue_after(key, remaining)

    def _gc_orphans(self, key: str) -> None:
        """Owner-GC parity: job object gone → its pods/services go too.

        The deleted job's uid is no longer known here, so ownership is
        checked against the *live* jobs: a label-matching object whose
        owner_uid belongs to a job that still exists is another
        controller's property (the adoption pass deliberately ignored
        it — see _claim_pods) and must survive name reuse.  Ownerless
        or dead-owner objects are collected.
        """

        ns, _, name = key.partition("/")
        live_uids = {
            j.metadata.uid for j in self.jobs.list(ns) if j.metadata.uid
        }
        for pod in self.cache.list_pods(ns, {LABEL_JOB_NAME: name}):
            if pod.metadata.owner_uid and pod.metadata.owner_uid in live_uids:
                continue
            try:
                self.backend.delete_pod(ns, pod.metadata.name)
            except NotFoundError:
                pass
        for svc in self.cache.list_services(ns, {LABEL_JOB_NAME: name}):
            if svc.metadata.owner_uid and svc.metadata.owner_uid in live_uids:
                continue
            try:
                self.backend.delete_service(ns, svc.metadata.name)
            except NotFoundError:
                pass
        try:
            if self.backend.get_pod_group(ns, name):
                self.backend.delete_pod_group(ns, name)
        except NotFoundError:
            pass

    # --------------------------------------------------------- time limits

    def _past_active_deadline(self, job: TPUJob) -> bool:
        deadline = job.spec.run_policy.active_deadline_seconds
        if deadline is None or job.status.start_time is None:
            return False
        return time.time() - job.status.start_time >= deadline

    def _schedule_deadline_wakeup(self, job: TPUJob) -> None:
        deadline = job.spec.run_policy.active_deadline_seconds
        if deadline is None or job.status.start_time is None:
            return
        due = job.status.start_time + deadline
        # schedule at most once per (job, due-time): a busy job syncs
        # constantly and must not pile one heap entry per sync
        if self._deadline_scheduled.get(job.key) == due:
            return
        remaining = due - time.time()
        if remaining > 0:
            self._deadline_scheduled[job.key] = due
            self.requeue_after(job.key, remaining + 0.01)

    # ------------------------------------------------------- health rollup

    def _rollup_health(self, job: TPUJob) -> None:
        """Publish live health into TPUJob.status (ISSUE 6 rollup half):
        a ``Degraded`` condition driven by the alert engine's firing
        set plus an ``observedHealth`` block (firing alerts, stall
        count, restart count, checkpoint age, recent throughput) — so
        ``tpujob get/describe`` shows health, not just phase.

        No-op without an engine.  Refreshes are throttled
        (``health_refresh_seconds``) because the block carries ages
        that change every sync; a CHANGE in the firing set bypasses the
        throttle so conditions land promptly.
        """

        if (
            self.alerts is None
            and self.autoscaler is None
            and self.telemetry is None
            and self.scheduler is None
        ):
            return
        if job.is_terminal():
            # the failed_fatal path reaches here AFTER _fail_job cleared
            # Degraded; re-marking a terminal job would pin the
            # condition forever (terminal jobs never sync again)
            return
        key = job.key
        # ONE firing snapshot for names, reason, and message — the
        # evaluator thread may transition rules between calls, and
        # reason/message must never disagree
        firing_alerts = self.alerts.firing() if self.alerts is not None else []
        firing = sorted(a.rule.name for a in firing_alerts)
        auto_blk = (
            self.autoscaler.health_block(job)
            if self.autoscaler is not None
            else None
        )
        sched_blk = (
            self.scheduler.health_block(job)
            if self.scheduler is not None
            else None
        )
        now = time.time()
        throttled = now - self._health_refreshed.get(key, 0.0) < max(
            self.config.health_refresh_seconds,
            self.config.health_rewrite_floor_seconds,
        )
        if (
            throttled
            and firing == job.status.observed_health.get("firingAlerts", [])
            # a scale decision must land promptly, like a firing change
            and auto_blk == job.status.observed_health.get("autoscaler")
            # so must a queue/preemption transition
            and sched_blk == job.status.observed_health.get("scheduler")
        ):
            return
        self._health_refreshed[key] = now

        # ---- Degraded condition + one Warning/Normal event per flip
        if firing:
            from tf_operator_tpu.utils.alerts import BurnRateRule

            reason = (
                "SLOViolation"
                if any(isinstance(a.rule, BurnRateRule) for a in firing_alerts)
                else "HealthDegraded"
            )
            msg = "alerts firing: " + ", ".join(firing)
            newly = not job.status.has_condition(JobConditionType.DEGRADED)
            if set_condition(job, JobConditionType.DEGRADED, reason, msg) and newly:
                self.recorder.event(key, "Warning", reason, msg)
                self.metrics.inc("tpujob_degraded_total")
        elif clear_condition(
            job, JobConditionType.DEGRADED, "Recovered",
            "all alerts resolved",
        ):
            self.recorder.event(
                key, "Normal", "SLORecovered", "all alerts resolved"
            )

        # ---- observedHealth block
        health: Dict[str, object] = {
            "firingAlerts": firing,
            "stallCount": int(self.metrics.total("watchdog_stall_total")),
            "restartCount": job.status.restart_count,
            "updatedAt": round(now, 3),
        }
        # checkpoint freshness: the POD-scope summary-series stamp wins
        # over the operator-process gauge (the PR 6 scope gap, closed —
        # same helper the autoscaler's resize gate uses, so status and
        # gate can never disagree); ONE tail read serves both it and
        # the throughput window
        from tf_operator_tpu.controller.autoscaler import job_checkpoint_age

        series = self._read_series_tail(job)
        age = job_checkpoint_age(job, now, metrics=self.metrics, series=series)
        if age is not None:
            health["lastCheckpointAgeSeconds"] = round(age, 1)
        tput = self._recent_throughput(job, series=series)
        if tput is not None:
            health["throughputStepsPerSec"] = tput
        if auto_blk:
            health["autoscaler"] = auto_blk
        if sched_blk:
            health["scheduler"] = sched_blk
        # fleet telemetry (ISSUE 15): per-pod scrape rows — staleness,
        # failure counts, federated step rate — so describe shows the
        # FLEET's health, not just the operator's own aggregates
        if self.telemetry is not None:
            pod_rows = self.telemetry.job_rows(key, now=now)
            if pod_rows:
                health["pods"] = pod_rows
        job.status.observed_health = health

    def _read_series_tail(self, job: TPUJob) -> "Optional[List[dict]]":
        """One read of the job's summary-series tail per rollup, shared
        by the checkpoint-age and throughput consumers (None = no
        series)."""

        from tf_operator_tpu.utils.summaries import (
            ANNOTATION_SUMMARY_DIR,
            read_series,
        )

        sdir = job.metadata.annotations.get(ANNOTATION_SUMMARY_DIR)
        if not sdir:
            return None
        try:
            return read_series(sdir, limit=50)
        except OSError:
            return None

    def _recent_throughput(
        self, job: TPUJob, series: "Optional[List[dict]]" = None
    ) -> Optional[float]:
        """Δstep/Δtime over the tail of the job's summary series (the
        same per-job metrics the API's /metrics sub-resource serves);
        None when the job publishes no series."""

        if series is None:
            series = self._read_series_tail(job)
        if series is None:
            return None
        series = series[-20:]
        if len(series) < 2:
            return None
        # staleness bound: the tail must be RECENT — a trainer that
        # hung hours ago still has a perfectly healthy-looking last-20
        # window, and reporting it as live throughput is exactly the
        # failure observedHealth exists to expose
        if time.time() - series[-1].get("time", 0.0) > (
            self.config.throughput_stale_seconds
        ):
            return None
        d_step = series[-1].get("step", 0) - series[0].get("step", 0)
        d_time = series[-1].get("time", 0.0) - series[0].get("time", 0.0)
        if d_time <= 0 or d_step <= 0:
            return None
        return round(d_step / d_time, 3)

    # -------------------------------------------------------------- status

    def _update_status(self, job: TPUJob, old_status) -> None:
        if job.status != old_status:
            with self.tracer.span("status.update"):
                try:
                    self.jobs.update_status(
                        job.metadata.namespace, job.metadata.name, job.status
                    )
                except NotFoundError:
                    pass

    def _observe_startup_latency(self, job: TPUJob) -> None:
        if job.status.start_time is not None:
            self.metrics.observe(
                "tpujob_startup_latency_seconds", time.time() - job.status.start_time
            )

    def _observe_completion(self, job: TPUJob) -> None:
        if job.status.start_time and job.status.completion_time:
            self.metrics.observe(
                "tpujob_completion_seconds",
                job.status.completion_time - job.status.start_time,
            )
