"""Rate-limited, deduplicating work queue.

Parity: ``k8s.io/client-go/util/workqueue``'s rate-limiting queue as used
by the reference's controller (SURVEY.md §2 "TFJob controller core",
§3.1 hot loop #1).  Semantics reproduced:

- **dedup**: adding a key already queued (or dirty while processing) does
  not duplicate work; a key re-added mid-processing is reprocessed once.
- **per-item exponential backoff** via ``add_rate_limited``; ``forget``
  resets the failure count after a clean sync.
- **delayed adds** (``add_after``) for TTL/deadline re-enqueues.

Pure Python here; the C++ native engine provides the same surface
(tf_operator_tpu/native) and either can back the controller.
"""

from __future__ import annotations

import heapq
import random
import threading
import time
from typing import Dict, List, Optional, Set, Tuple


class WorkQueue:
    def __init__(
        self,
        base_delay: float = 0.005,
        max_delay: float = 60.0,
        jitter: bool = True,
        rng: Optional[random.Random] = None,
    ):
        self._lock = threading.Condition()
        #: full jitter on rate-limited requeues: when one apiserver
        #: outage fails every in-flight sync at once, the retries must
        #: not re-arrive as one synchronized wave (rng injectable for
        #: deterministic tests; jitter=False restores the exact
        #: client-go ItemExponentialFailureRateLimiter delays)
        self.jitter = jitter
        self._rng = rng or random.Random()
        self._queue: List[str] = []
        self._queued: Set[str] = set()
        self._processing: Set[str] = set()
        self._dirty: Set[str] = set()
        self._failures: Dict[str, int] = {}
        self._delayed: List[Tuple[float, int, str]] = []  # heap (when, seq, key)
        self._seq = 0
        self._shutdown = False
        self.base_delay = base_delay
        self.max_delay = max_delay

    # -- core ---------------------------------------------------------------

    def add(self, key: str) -> None:
        with self._lock:
            if self._shutdown:
                return
            if key in self._processing:
                self._dirty.add(key)
                return
            if key in self._queued:
                return
            self._queued.add(key)
            self._queue.append(key)
            self._lock.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        """Block for the next key; None on timeout or shutdown."""

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                self._drain_delayed_locked()
                if self._queue:
                    key = self._queue.pop(0)
                    self._queued.discard(key)
                    self._processing.add(key)
                    return key
                if self._shutdown:
                    return None
                wait = self._next_wait_locked(deadline)
                if wait is not None and wait <= 0:
                    if deadline is not None and time.monotonic() >= deadline:
                        return None
                    continue
                self._lock.wait(wait)
                if deadline is not None and time.monotonic() >= deadline and not self._queue:
                    self._drain_delayed_locked()
                    if not self._queue:
                        return None

    def done(self, key: str) -> None:
        with self._lock:
            self._processing.discard(key)
            if key in self._dirty:
                self._dirty.discard(key)
                if key not in self._queued:
                    self._queued.add(key)
                    self._queue.append(key)
                    self._lock.notify()

    # -- rate limiting ------------------------------------------------------

    def add_rate_limited(self, key: str) -> float:
        """Re-add after exponential backoff with full jitter; returns
        the delay applied.  The failure-count read, bump, and delay
        computation happen under ONE lock acquisition so concurrent
        workers requeuing the same key can't race the exponent."""

        with self._lock:
            failures = self._failures.get(key, 0)
            self._failures[key] = failures + 1
            cap = min(self.base_delay * (2**failures), self.max_delay)
            delay = self._rng.uniform(0.0, cap) if self.jitter else cap
        self.add_after(key, delay)
        return delay

    def forget(self, key: str) -> None:
        with self._lock:
            self._failures.pop(key, None)

    def num_requeues(self, key: str) -> int:
        with self._lock:
            return self._failures.get(key, 0)

    # -- delayed ------------------------------------------------------------

    def add_after(self, key: str, delay: float) -> None:
        if delay <= 0:
            self.add(key)
            return
        with self._lock:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (time.monotonic() + delay, self._seq, key))
            self._lock.notify()

    def _drain_delayed_locked(self) -> None:
        now = time.monotonic()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, key = heapq.heappop(self._delayed)
            if key in self._processing:
                self._dirty.add(key)
            elif key not in self._queued:
                self._queued.add(key)
                self._queue.append(key)

    def _next_wait_locked(self, deadline: Optional[float]) -> Optional[float]:
        candidates = []
        if self._delayed:
            candidates.append(self._delayed[0][0] - time.monotonic())
        if deadline is not None:
            candidates.append(deadline - time.monotonic())
        return min(candidates) if candidates else None

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue) + len(self._delayed)
