"""Single-page web dashboard served at GET /.

Parity: the reference's older trees shipped a dashboard (Go REST
backend + React frontend) that could *list, create and delete* TFJobs
(SURVEY.md §2 "Dashboard").  The equivalent here is one dependency-free
HTML page over the operator's own job API: job table with
replica/condition state, per-job detail with conditions + events,
auto-refresh, a paste-a-manifest submit box (JSON or YAML → POST) and
a delete-with-confirmation button — the full list/create/delete verb
set, closing the write-path gap VERDICT r3 named.

Observability panels (fed by /metrics, /alerts and the tracing
subsystem's /traces endpoints, utils/trace.py):

- **alerts** — the alert engine's lifecycle state (utils/alerts.py),
  firing rules first and colored by state, with the measured burn
  rates / levels and the breach message;
- **autoscaler** — per-policy live state (controller/autoscaler.py,
  breaching first) + the scale-decision tail from GET /autoscaler:
  the act half next to the alerts panel's observe half;
- **fleet queue** (ISSUE 16) — the fleet scheduler's pending queue
  (priority then age, with quota group and wait age), admitted gangs
  (including shed-to-smaller-world state) and the admit/shed/revoke
  decision tail from GET /scheduler; self-hides when no job declares
  spec.scheduling;

- **api client health** — retry/circuit/watch-recovery counters, with
  exemplar trace links (`# exemplar` comment lines in the exposition)
  so an error counter deep-links to the waterfall that explains it;
- **workqueue** — depth gauge + queue-latency histogram
  (`workqueue_depth`, `workqueue_queue_latency_seconds`);
- **traces** — recent trace summaries (tail sampling keeps error and
  slow traces), slow queue waits flagged, click-through to a span
  waterfall rendered from /traces/<id>;
- **kv arena** (ISSUE 11) — the serving plane's block-arena occupancy
  strip, one stacked band per replica rendered from the
  `/debug/arena` timeline (live blocks, prefix-cached share, queued
  demand overflow, and since ISSUE 12 the swapped-out block band —
  preempted seats' KV living host-side) — the time-series twin of the
  instantaneous `kv_blocks_pressure` gauge.  The panel self-hides when there is no
  paged-pool data: the operator API has no `/debug/arena` route (the
  fetch 404s), and serve_lm without a paged pool answers 200 with an
  empty `replicas` list — both paths leave the panel hidden, so the
  operator dashboard and an embedded serving dashboard share one page;
- **kv fabric** (ISSUE 17) — the cross-pod prefix fabric's peer table
  (liveness, advertised key count, catalog generation) and pull ledger
  (hit/miss/failed + wire bytes) from serve_lm's `/debug/fabric`;
  self-hides by the same 404 convention as the arena panel.
"""

DASHBOARD_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>tpu-operator</title>
<style>
  body { font-family: ui-monospace, SFMono-Regular, Menlo, monospace;
         margin: 2rem; background: #fafafa; color: #1a1a1a; }
  h1 { font-size: 1.2rem; } h2 { font-size: 1rem; margin-top: 1.5rem; }
  table { border-collapse: collapse; width: 100%; background: #fff; }
  th, td { text-align: left; padding: .4rem .8rem;
           border-bottom: 1px solid #e5e5e5; font-size: .85rem; }
  th { background: #f0f0f0; }
  tr.sel { background: #eef6ff; } tr[data-key] { cursor: pointer; }
  .Succeeded { color: #0a7d32; } .Failed { color: #b3261e; }
  .Running { color: #0b57d0; } .Pending, .Created { color: #666; }
  .Restarting { color: #a86500; } .Degraded { color: #b3261e; }
  tr.alert-firing td { color: #b3261e; font-weight: 600; }
  tr.alert-pending td { color: #a86500; }
  tr.alert-resolved td { color: #0a7d32; }
  #detail { white-space: pre-wrap; background: #fff; padding: 1rem;
            border: 1px solid #e5e5e5; font-size: .8rem; }
  #client-health { white-space: pre-wrap; background: #fff; padding: .6rem;
                   border: 1px solid #e5e5e5; font-size: .75rem; }
  #client-health.degraded { border-color: #b3261e; }
  #workqueue { white-space: pre-wrap; background: #fff; padding: .6rem;
               border: 1px solid #e5e5e5; font-size: .75rem; }
  #autoscaler-decisions { white-space: pre-wrap; background: #fff;
               padding: .6rem; border: 1px solid #e5e5e5;
               font-size: .75rem; }
  tr.trace-err td:first-child { color: #b3261e; }
  tr.trace-slow td:first-child { color: #a86500; }
  #waterfall { background: #fff; border: 1px solid #e5e5e5;
               padding: .6rem; font-size: .72rem; }
  .wf-row { display: flex; align-items: center; height: 1.1rem; }
  .wf-name { width: 34%; overflow: hidden; white-space: nowrap;
             text-overflow: ellipsis; }
  .wf-lane { position: relative; flex: 1; height: .7rem;
             background: #f6f6f6; }
  .wf-bar { position: absolute; height: 100%; background: #0b57d0;
            min-width: 2px; }
  .wf-bar.err { background: #b3261e; }
  .wf-dur { width: 5.5rem; text-align: right; color: #888; }
  .muted { color: #888; font-size: .75rem; }
  #manifest { width: 100%; box-sizing: border-box; font-family: inherit;
              font-size: .8rem; border: 1px solid #e5e5e5; }
  button { font-family: inherit; font-size: .8rem; cursor: pointer; }
  #delbtn { color: #b3261e; }
</style>
</head>
<body>
<h1>tpu-operator <span class="muted" id="refreshed"></span></h1>
<table id="jobs">
  <thead><tr><th>namespace</th><th>name</th><th>replicas</th>
  <th>state</th><th>restarts</th></tr></thead>
  <tbody></tbody>
</table>
<h2 id="detail-title" style="display:none">
  <span id="detail-name"></span>
  <button id="delbtn" onclick="deleteJob()">delete</button>
</h2>
<div id="spark" style="display:none"></div>
<div id="detail" style="display:none"></div>
<h2>alerts</h2>
<table id="alerts">
  <thead><tr><th>rule</th><th>state</th><th>severity</th>
  <th>value</th><th>detail</th></tr></thead>
  <tbody><tr><td class="muted" colspan="5">no alert engine data yet</td></tr></tbody>
</table>
<h2>autoscaler</h2>
<table id="autoscaler">
  <thead><tr><th>job</th><th>replicas</th><th>desired</th>
  <th>breaching</th><th>signals</th></tr></thead>
  <tbody><tr><td class="muted" colspan="5">no autoscaled jobs</td></tr></tbody>
</table>
<div id="autoscaler-decisions" class="muted"></div>
<div id="scheduler-panel" style="display:none">
<h2>fleet queue</h2>
<table id="scheduler">
  <thead><tr><th>pos</th><th>job</th><th>class</th><th>quota</th>
  <th>chips</th><th>waiting</th><th>reason</th></tr></thead>
  <tbody></tbody>
</table>
<div id="scheduler-decisions" class="muted"></div>
</div>
<div id="fleet-panel" style="display:none">
<h2>fleet</h2>
<table id="fleet">
  <thead><tr><th>job</th><th>pod</th><th>step/s</th>
  <th>dcn sync</th><th>ckpt age</th><th>scrape age</th><th>state</th></tr></thead>
  <tbody></tbody>
</table>
</div>
<h2>api client health</h2>
<div id="client-health" class="muted">no apiserver client traffic</div>
<h2>workqueue</h2>
<div id="workqueue" class="muted">no queue traffic</div>
<h2>slo</h2>
<table id="slo">
  <thead><tr><th>latency family</th><th>labels</th><th>count</th>
  <th>p50 &le;</th><th>p99 &le;</th></tr></thead>
  <tbody><tr><td class="muted" colspan="5">no latency histograms yet</td></tr></tbody>
</table>
<div id="arena-panel" style="display:none">
<h2>kv arena</h2>
<div id="arena"></div>
</div>
<div id="fabric-panel" style="display:none">
<h2>kv fabric</h2>
<table id="fabric">
  <thead><tr><th>peer</th><th>state</th><th>keys</th>
  <th>generation</th></tr></thead>
  <tbody></tbody>
</table>
<div id="fabric-summary" class="muted"></div>
</div>
<div id="costplane-panel" style="display:none">
<h2>device cost plane</h2>
<table id="costplane-memory">
  <thead><tr><th>device</th><th>accounted</th><th>headroom</th>
  <th>coverage</th><th>components</th></tr></thead>
  <tbody></tbody>
</table>
<div id="costplane-compiles" class="muted"></div>
</div>
<h2>traces</h2>
<table id="traces">
  <thead><tr><th>trace</th><th>root</th><th>spans</th><th>duration</th>
  <th>queue wait</th><th>flags</th></tr></thead>
  <tbody><tr><td class="muted" colspan="6">no traces yet</td></tr></tbody>
</table>
<div id="waterfall" style="display:none"></div>
<h2>submit job</h2>
<textarea id="manifest" rows="10"
  placeholder="paste a TPUJob manifest (JSON or YAML)"></textarea>
<div>
  namespace <input id="ns" value="default" size="12">
  <button onclick="submitJob()">submit</button>
  <span id="submit-msg" class="muted"></span>
</div>
<script>
let selected = null;

function state(job) {
  const conds = (job.status && job.status.conditions) || [];
  const active = conds.filter(c => c.status === "True").map(c => c.type);
  for (const t of ["Succeeded", "Failed"]) if (active.includes(t)) return t;
  // live health outranks phase (matches the tpujob CLI)
  if (active.includes("Degraded")) return "Degraded";
  return active.length ? active[active.length - 1] : "Pending";
}

function replicas(job) {
  const specs = (job.spec && job.spec.tpuReplicaSpecs) || {};
  return Object.entries(specs)
    .map(([t, s]) => `${t}:${s.replicas ?? 1}`).join(" ");
}

async function refresh() {
  const res = await fetch("/apis/v1/tpujobs");
  const items = (await res.json()).items || [];
  const tbody = document.querySelector("#jobs tbody");
  tbody.innerHTML = "";
  for (const job of items) {
    const key = `${job.metadata.namespace}/${job.metadata.name}`;
    const tr = document.createElement("tr");
    tr.dataset.key = key;
    const st = state(job);
    // textContent only — job names are user input
    const cells = [
      job.metadata.namespace, job.metadata.name, replicas(job), st,
      String((job.status && job.status.restartCount) || 0),
    ];
    for (const [i, text] of cells.entries()) {
      const td = document.createElement("td");
      td.textContent = text;
      if (i === 3) td.className = st;
      tr.appendChild(td);
    }
    tr.onclick = () => { selected = key; detail(); highlight(); };
    if (key === selected) tr.classList.add("sel");
    tbody.appendChild(tr);
  }
  document.getElementById("refreshed").textContent =
    "refreshed " + new Date().toLocaleTimeString();
  if (selected) detail();
  refreshAlerts();
  refreshAutoscaler();
  refreshScheduler();
  refreshHealth();
  refreshTraces();
  refreshArena();
  refreshFabric();
  refreshCostPlane();
  refreshFleet();
}

async function refreshCostPlane() {
  // device cost plane (ISSUE 20): the HBM accountant's per-device
  // table (headroom-worst-first — the wire's sort order) plus a
  // one-line compile-ledger digest.  Hidden when the process serves
  // neither route (older builds 404) or the accountant is empty.
  let mem = null, comp = null;
  try {
    const res = await fetch("/debug/memory");
    if (res.ok) mem = await res.json();
  } catch (e) {}
  try {
    const res = await fetch("/debug/compiles");
    if (res.ok) comp = await res.json();
  } catch (e) {}
  const panel = document.getElementById("costplane-panel");
  const devices = (mem && mem.devices) || [];
  const haveMem = devices.some(d => d.accounted_bytes > 0);
  const haveComp = comp && comp.total > 0;
  if (!haveMem && !haveComp) { panel.style.display = "none"; return; }
  panel.style.display = "";
  const gb = b => b == null ? "?" : (b / 1073741824).toFixed(2) + " GiB";
  const tbody = document.querySelector("#costplane-memory tbody");
  tbody.innerHTML = "";
  for (const d of devices) {
    const tr = document.createElement("tr");
    // a device past 90% of its known limit renders like a firing alert
    if (d.limit_bytes && d.headroom_bytes != null &&
        d.headroom_bytes < 0.1 * d.limit_bytes)
      tr.classList.add("alert-firing");
    const comps = Object.entries(d.components || {})
      .filter(([, b]) => b > 0).sort((a, b) => b[1] - a[1])
      .map(([c, b]) => `${c}=${gb(b)}`).join(" ");
    const cells = [
      d.device, gb(d.accounted_bytes), gb(d.headroom_bytes),
      d.coverage == null ? "?" : (100 * d.coverage).toFixed(1) + "%",
      comps || "none",
    ];
    for (const text of cells) {
      const td = document.createElement("td");
      td.textContent = text;
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
  const line = [];
  if (comp) {
    line.push(`compiles: ${comp.total}`);
    const progs = Object.entries(comp.byProgram || {})
      .sort((a, b) => b[1].total - a[1].total).slice(0, 5)
      .map(([p, s]) => `${p}:${s.total}`).join(" ");
    if (progs) line.push(progs);
  }
  document.getElementById("costplane-compiles").textContent =
    line.join(" — ");
}

async function refreshFleet() {
  // fleet telemetry panel (controller/telemetry.py): per-pod scrape
  // state from /federate/targets (stale-first, the server's order)
  // joined with the federated per-pod series parsed out of /federate —
  // step rate, DCN-vs-ICI grad-sync seconds, checkpoint age.  Hidden
  // until the scraper has targets (library/serving deployments).
  let snap, text;
  try {
    snap = await (await fetch("/federate/targets")).json();
    text = await (await fetch("/federate")).text();
  } catch (e) { return; }
  const panel = document.getElementById("fleet-panel");
  const targets = snap.targets || [];
  if (!targets.length) { panel.style.display = "none"; return; }
  panel.style.display = "";
  // one pass over the federated exposition: value per (family, labels)
  const vals = {};
  const re = /^([A-Za-z0-9_:]+)\\{(.*)\\} ([0-9.eE+-]+)$/;
  for (const l of text.split("\\n")) {
    const m = l.match(re);
    if (m) vals[m[1] + "|" + m[2]] = parseFloat(m[3]);
  }
  const pick = (fam, t, extra) => {
    // match on the federated decoration regardless of label order
    const want = [`job="${t.job}"`, `replica_index="${t.replicaIndex}"`,
                  `replica_type="${t.replicaType}"`].concat(extra || []);
    for (const key of Object.keys(vals)) {
      if (!key.startsWith(fam + "|")) continue;
      if (want.every(w => key.includes(w))) return vals[key];
    }
    return undefined;
  };
  const tbody = document.querySelector("#fleet tbody");
  tbody.innerHTML = "";
  const now = Date.now() / 1000;
  for (const t of targets) {
    const steps = pick("train_window_steps_per_second", t);
    const dcn = pick("train_dcn_sync_seconds_sum", t, ['fabric="dcn"']);
    const ici = pick("train_dcn_sync_seconds_sum", t, ['fabric="ici"']);
    const ckpt = pick("checkpoint_last_success_unix", t);
    const cells = [
      t.job, t.replica + (t.slice ? ` (slice ${t.slice})` : ""),
      steps === undefined ? "-" : steps.toFixed(2),
      dcn === undefined && ici === undefined ? "-" :
        `${(dcn || 0).toFixed(3)}s dcn / ${(ici || 0).toFixed(3)}s ici`,
      ckpt === undefined || !ckpt ? "-" : `${(now - ckpt).toFixed(0)}s`,
      t.lastScrapeAgeSeconds == null ? "never"
        : `${t.lastScrapeAgeSeconds.toFixed(1)}s`,
      t.stale ? "stale" : "ok",
    ];
    const tr = document.createElement("tr");
    for (const [i, c] of cells.entries()) {
      const td = document.createElement("td");
      td.textContent = c;
      if (i === 6) td.className = t.stale ? "Failed" : "Succeeded";
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
}

async function refreshArena() {
  // KV-arena occupancy strip (ISSUE 11): per-replica timeline from
  // /debug/arena — live (blue) with the prefix-cached share (green)
  // stacked from the bottom, queued demand (amber) above the line.
  // No data hides the panel: the operator API 404s (no such route),
  // serve_lm without a paged pool answers an empty replicas list.
  let snap;
  try {
    const res = await fetch("/debug/arena");
    if (!res.ok) throw new Error("no arena");
    snap = await res.json();
  } catch (e) {
    document.getElementById("arena-panel").style.display = "none";
    return;
  }
  const reps = (snap.replicas || []).filter(r => (r.samples || []).length);
  const panel = document.getElementById("arena-panel");
  if (!reps.length) { panel.style.display = "none"; return; }
  panel.style.display = "";
  const el = document.getElementById("arena");
  el.innerHTML = "";
  const W = 640, H = 48;
  for (const rep of reps) {
    const samples = rep.samples.slice(-160);
    const usable = rep.usable || 1;
    const svg = document.createElementNS("http://www.w3.org/2000/svg", "svg");
    svg.setAttribute("width", W); svg.setAttribute("height", H);
    svg.style.background = "#f6f6f6"; svg.style.border = "1px solid #e5e5e5";
    // x-axis is TIME, not sample count: the ring collapses identical
    // consecutive samples, so a sample's bar must stretch until the
    // NEXT state change or the strip would compress quiet plateaus
    // into slivers and stretch bursts across the whole width
    const t0 = samples[0].unix;
    const span = Math.max(samples[samples.length - 1].unix - t0, 1e-9);
    const xs = samples.map(s => W * (s.unix - t0) / span);
    for (const [i, s] of samples.entries()) {
      const xEnd = i + 1 < samples.length ? xs[i + 1] : W;
      const bw = Math.max(1, xEnd - xs[i]);
      // clamp INTO the canvas: the newest sample IS the latest state
      // change (dedupe), and at xs=W it would render clipped,
      // contradicting the text label below
      const x = Math.min(xs[i], W - bw).toFixed(2);
      const live = Math.min(1, s.live / usable);
      const cached = Math.min(live, s.prefix_cached / usable);
      const queued = Math.min(1, s.queued_demand / usable);
      const mk = (frac, y0frac, color) => {
        if (frac <= 0) return;
        const r = document.createElementNS(
          "http://www.w3.org/2000/svg", "rect");
        r.setAttribute("x", x); r.setAttribute("width", bw.toFixed(2));
        r.setAttribute("y", (H * (1 - y0frac - frac)).toFixed(2));
        r.setAttribute("height", Math.max(1, H * frac).toFixed(2));
        r.setAttribute("fill", color);
        svg.appendChild(r);
      };
      const swapped = Math.min(1, (s.swapped || 0) / usable);
      mk(live - cached, cached, "#0b57d0");   // seat-mapped blocks
      mk(cached, 0, "#0a7d32");               // prefix-cached share
      // queued demand renders as an over-line marker band at the top
      if (queued > 0) mk(Math.min(0.12, 0.12 * queued), 0.88, "#a86500");
      // swapped-out blocks (ISSUE 12): host-resident KV of preempted
      // seats — a purple under-line band, so a thrashing pool reads
      // as live pressure on top AND spill volume below
      if (swapped > 0) mk(Math.min(0.12, 0.12 * swapped), 0, "#7a2ea0");
    }
    const last = samples[samples.length - 1];
    const label = document.createElement("div");
    label.className = "muted";
    // ISSUE 13: a disaggregated fleet's strips are read per phase role
    const roleTag = rep.role && rep.role !== "unified" ? ` [${rep.role}]` : "";
    label.textContent =
      `replica ${rep.replica}${roleTag}: ${last.live}/${usable} blocks live ` +
      `(${last.prefix_cached} prefix-cached), ` +
      `${last.queued_demand} queued demand, ` +
      `${last.swapped || 0} swapped, ` +
      `${last.seats_active} seats — ${samples.length} samples`;
    el.appendChild(svg); el.appendChild(label);
  }
}

async function refreshFabric() {
  // cross-pod KV fabric panel (ISSUE 17): this pod's catalog + peer
  // table from /debug/fabric — liveness per peer, advertised key
  // counts, and the pull ledger (hit/miss/failed + bytes over the
  // wire).  Hidden when there is no fabric: the operator API has no
  // /debug/fabric route (fetch 404s), and serve_lm without a prefix
  // fabric answers 404 too — both leave the panel dark.
  let snap;
  try {
    const res = await fetch("/debug/fabric");
    if (!res.ok) throw new Error("no fabric");
    snap = await res.json();
  } catch (e) {
    document.getElementById("fabric-panel").style.display = "none";
    return;
  }
  const fab = snap.fabric || {};
  document.getElementById("fabric-panel").style.display = "";
  const tbody = document.querySelector("#fabric tbody");
  tbody.innerHTML = "";
  const peers = fab.peers || [];
  if (!peers.length) {
    const tr = document.createElement("tr");
    const td = document.createElement("td");
    td.textContent = "no peers (local-only fabric)"; td.className = "muted";
    td.colSpan = 4; tr.appendChild(td); tbody.appendChild(tr);
  }
  for (const p of peers) {
    const tr = document.createElement("tr");
    if (p.up === false) tr.classList.add("alert-firing");
    const cells = [
      p.peer,
      p.up === null ? "unknown" : (p.up ? "up" : "down"),
      String(p.keys), String(p.generation),
    ];
    for (const text of cells) {
      const td = document.createElement("td");
      td.textContent = text;  // peer addrs ride pod annotations
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
  const pulls = fab.pulls || {};
  const fails = Object.entries(fab.pull_failures || {})
    .map(([r, n]) => `${r}:${n}`).join(" ");
  document.getElementById("fabric-summary").textContent =
    `${fab.blocks || 0} blocks published (gen ${fab.generation || 0}), ` +
    `pulls hit=${pulls.hit || 0} miss=${pulls.miss || 0} ` +
    `failed=${pulls.failed || 0}, ` +
    `${fab.bytes_pulled || 0} bytes pulled` +
    (fails ? ` — failures ${fails}` : "");
}

async function refreshAutoscaler() {
  // the act half of the alerts panel (controller/autoscaler.py):
  // per-policy live state breaching-first, plus the decision tail
  let snap;
  try { snap = await (await fetch("/autoscaler")).json(); }
  catch (e) { return; }
  const tbody = document.querySelector("#autoscaler tbody");
  tbody.innerHTML = "";
  const policies = snap.policies || [];
  if (!policies.length) {
    const tr = document.createElement("tr");
    const td = document.createElement("td");
    td.textContent = "no autoscaled jobs"; td.className = "muted";
    td.colSpan = 5; tr.appendChild(td); tbody.appendChild(tr);
  }
  for (const p of policies) {
    const tr = document.createElement("tr");
    if (p.breaching) tr.classList.add("alert-firing");
    const sig = Object.entries(p.signals || {})
      .map(([n, v]) => `${n}:${v.breaching ? "breach" : "ok"}`).join(" ");
    const cells = [
      p.job, p.replicaType,
      p.desiredReplicas == null ? "spec" : String(p.desiredReplicas),
      p.breaching ? "yes" : "no", sig,
    ];
    for (const text of cells) {
      const td = document.createElement("td");
      td.textContent = text;  // job names are user input
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
  const dec = (snap.decisions || []).slice(0, 8);
  document.getElementById("autoscaler-decisions").textContent = dec.length
    ? dec.map(d =>
        `${new Date(d.time * 1000).toLocaleTimeString()} ${d.job} ` +
        `${d.replicaType} ${d.direction} ${d.from}->${d.to}: ${d.reason}`
      ).join("\\n")
    : "no scale decisions yet";
}

async function refreshScheduler() {
  // fleet scheduler panel (controller/scheduler.py): the pending queue
  // priority-then-age from GET /scheduler, admitted gangs below it as
  // context, plus the decision tail (admit/shed/revoke).  Hidden until
  // the scheduler manages at least one gang — most deployments never
  // declare spec.scheduling and should not see an empty panel.
  let snap;
  try { snap = await (await fetch("/scheduler")).json(); }
  catch (e) { return; }
  const queue = snap.queue || [];
  const admitted = snap.admitted || [];
  const decisions = snap.decisions || [];
  const panel = document.getElementById("scheduler-panel");
  if (!queue.length && !admitted.length && !decisions.length) {
    panel.style.display = "none"; return;
  }
  panel.style.display = "";
  const tbody = document.querySelector("#scheduler tbody");
  tbody.innerHTML = "";
  for (const q of queue) {
    const tr = document.createElement("tr");
    tr.classList.add("alert-pending");
    const cells = [
      String(q.position), q.job, q.priorityClass, q.quotaGroup,
      String(q.demandChips), `${Math.round(q.waitSeconds)}s`, q.reason,
    ];
    for (const text of cells) {
      const td = document.createElement("td");
      td.textContent = text;  // job names are user input
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
  for (const a of admitted) {
    const tr = document.createElement("tr");
    const cells = [
      "-", a.job, a.priorityClass, a.quotaGroup,
      String(a.demandChips),
      a.shedTo != null ? `shed to ${a.shedTo}` : "admitted", "",
    ];
    for (const text of cells) {
      const td = document.createElement("td");
      td.textContent = text;
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
  const dec = decisions.slice(0, 8);
  document.getElementById("scheduler-decisions").textContent = dec.length
    ? dec.map(d =>
        `${new Date(d.time * 1000).toLocaleTimeString()} ${d.job} ` +
        `${d.action} [${d.priorityClass}]: ${d.reason}`
      ).join("\\n")
    : "no scheduling decisions yet";
}

async function refreshAlerts() {
  // the alert engine's lifecycle state (utils/alerts.py): firing rules
  // first, so the thing that needs acting on is the first row
  let snap;
  try { snap = await (await fetch("/alerts")).json(); }
  catch (e) { return; }
  const items = snap.alerts || [];
  const tbody = document.querySelector("#alerts tbody");
  tbody.innerHTML = "";
  if (!items.length) {
    const tr = document.createElement("tr");
    const td = document.createElement("td");
    td.textContent = "no alert rules configured"; td.className = "muted";
    td.colSpan = 5; tr.appendChild(td); tbody.appendChild(tr);
    return;
  }
  for (const a of items) {
    const tr = document.createElement("tr");
    if (a.state !== "inactive") tr.classList.add(`alert-${a.state}`);
    const value = Object.entries(a.value || {})
      .map(([k, v]) => `${k}=${typeof v === "number" ? v.toFixed(2) : v}`)
      .join(" ");
    const detailTxt = a.state === "inactive"
      ? `${a.metric} (${a.kind})` : (a.message || a.metric);
    for (const text of [a.name, a.state, a.severity, value, detailTxt]) {
      const td = document.createElement("td");
      td.textContent = text;
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
}

async function refreshHealth() {
  // retry / circuit-breaker / watch-recovery counters from the shared
  // metrics registry (backend/retry.py): how rough the apiserver
  // connection is, straight from /metrics
  let text;
  try { text = await (await fetch("/metrics")).text(); }
  catch (e) { return; }
  const all = text.split("\\n");
  const lines = all.filter(l =>
    l.startsWith("api_client_") || l.startsWith("api_watch_") ||
    l.startsWith("api_events_dropped") || l.startsWith("api_event_read_") ||
    l.startsWith("# exemplar api_"));
  const el = document.getElementById("client-health");
  el.textContent = lines.length ? lines.join("\\n")
                                : "no apiserver client traffic";
  const bad = lines.some(l =>
    (l.startsWith("api_client_giveups_total") ||
     l.startsWith("api_client_circuit_open_total") ||
     l.startsWith("api_events_dropped_total")) &&
    parseFloat(l.split(" ").pop()) > 0);
  el.classList.toggle("degraded", bad);
  refreshWorkqueue(all);
  refreshSLO(all);
}

function refreshSLO(metricLines) {
  // SLO panel: p50/p99 per latency-histogram series, straight from the
  // *_bucket lines of /metrics (utils/metrics.py labeled histograms).
  // Families: user-facing serving SLOs (serve_*), the training/serving
  // sync ledgers, control-plane sync + queue + API request latencies.
  const WANT = /^(serve_|serving_dispatch_seconds|train_sync_seconds|workqueue_queue_latency_seconds|tpujob_sync_duration_seconds|api_request_seconds)/;
  const series = {};
  const re = /^([A-Za-z0-9_:]+)_bucket\\{(.*)\\} ([0-9.eE+-]+)$/;
  for (const l of metricLines) {
    const m = l.match(re);
    if (!m || !WANT.test(m[1])) continue;
    const le = (m[2].match(/le="([^"]+)"/) || [])[1];
    if (le === undefined) continue;
    // merge across {replica=} AND {role=}: multi-replica serving must
    // read as ONE user-facing quantile row (cumulative bucket counts
    // at the same le sum across replicas; a disaggregated fleet's
    // phase roles merge away the same way); /metrics keeps the raw
    // per-replica/per-role series for capacity eyes
    const rest = m[2].replace(/le="[^"]+",?/, "")
      .replace(/replica="[^"]+",?/, "")
      .replace(/role="[^"]+",?/, "").replace(/,$/, "");
    const key = m[1] + "|" + rest;
    const s = (series[key] = series[key] || { fam: m[1], labels: rest, sum: {} });
    const bound = le === "+Inf" ? Infinity : parseFloat(le);
    s.sum[bound] = (s.sum[bound] || 0) + parseFloat(m[3]);
  }
  for (const key of Object.keys(series)) {
    const s = series[key];
    s.b = Object.keys(s.sum).map(k => [parseFloat(k), s.sum[k]]);
  }
  const tbody = document.querySelector("#slo tbody");
  const keys = Object.keys(series).sort();
  tbody.innerHTML = "";
  if (!keys.length) {
    const tr = document.createElement("tr");
    const td = document.createElement("td");
    td.textContent = "no latency histograms yet"; td.className = "muted";
    td.colSpan = 5; tr.appendChild(td); tbody.appendChild(tr);
    return;
  }
  const fmt = v => v === Infinity ? "+Inf" :
    (v >= 1 ? v.toFixed(2) + " s" : (1000 * v).toFixed(1) + " ms");
  for (const key of keys) {
    const s = series[key];
    s.b.sort((x, y) => x[0] - y[0]);
    const count = s.b.length ? s.b[s.b.length - 1][1] : 0;
    if (!count) continue;
    const q = p => { for (const [le, c] of s.b) if (c >= p * count) return le;
                     return Infinity; };
    const tr = document.createElement("tr");
    for (const text of [s.fam, s.labels, String(count),
                        fmt(q(0.5)), fmt(q(0.99))]) {
      const td = document.createElement("td");
      td.textContent = text;
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
}

function refreshWorkqueue(metricLines) {
  // depth gauge + queue-latency histogram (controller/controller.py
  // observes enqueue->dequeue latency per item)
  const el = document.getElementById("workqueue");
  const pick = p => metricLines.find(l => l.startsWith(p));
  const num = l => (l ? parseFloat(l.split(" ").pop()) : NaN);
  const depth = num(pick("workqueue_depth"));
  const count = num(pick("workqueue_queue_latency_seconds_count"));
  const sum = num(pick("workqueue_queue_latency_seconds_sum"));
  if (isNaN(count) || count === 0) {
    el.textContent = "no queue traffic"; return;
  }
  el.textContent =
    `depth ${isNaN(depth) ? 0 : depth}` +
    ` | items dequeued ${count}` +
    ` | mean queue wait ${(1000 * sum / count).toFixed(2)} ms` +
    ` — slow waits carry their trace id in the traces table below`;
}

let selectedTrace = null;

async function refreshTraces() {
  let items;
  try { items = (await (await fetch("/traces")).json()).items || []; }
  catch (e) { return; }
  const tbody = document.querySelector("#traces tbody");
  tbody.innerHTML = "";
  if (!items.length) {
    const tr = document.createElement("tr");
    const td = document.createElement("td");
    td.textContent = "no traces yet"; td.className = "muted";
    td.colSpan = 6; tr.appendChild(td); tbody.appendChild(tr);
    return;
  }
  for (const t of items.slice(0, 20)) {
    const tr = document.createElement("tr");
    tr.dataset.key = t.traceId;
    if (t.error) tr.classList.add("trace-err");
    else if (t.slow) tr.classList.add("trace-slow");
    const flags = [t.error ? "error" : "", t.slow ? "slow" : "",
                   t.droppedSpans ? `dropped ${t.droppedSpans}` : ""]
      .filter(Boolean).join(" ");
    const cells = [
      t.traceId, t.root, String(t.spanCount),
      `${(1000 * t.duration).toFixed(1)} ms`,
      t.queueLatency != null ? `${(1000 * t.queueLatency).toFixed(2)} ms` : "",
      flags,
    ];
    for (const text of cells) {
      const td = document.createElement("td");
      td.textContent = text;
      tr.appendChild(td);
    }
    tr.onclick = () => { selectedTrace = t.traceId; showWaterfall(); };
    tbody.appendChild(tr);
  }
}

async function showWaterfall() {
  const el = document.getElementById("waterfall");
  if (!selectedTrace) { el.style.display = "none"; return; }
  let trace;
  try { trace = await (await fetch(`/traces/${selectedTrace}`)).json(); }
  catch (e) { return; }
  const spans = (trace.spans || [])
    .slice().sort((a, b) => a.startMono - b.startMono);
  if (!spans.length) { el.style.display = "none"; return; }
  const t0 = Math.min(...spans.map(s => s.startMono));
  const t1 = Math.max(...spans.map(s => s.startMono + (s.duration || 0)));
  const total = (t1 - t0) || 1e-9;
  el.innerHTML = "";
  const head = document.createElement("div");
  head.className = "muted";
  head.textContent = `trace ${trace.traceId}` +
    (trace.droppedSpans ? ` (${trace.droppedSpans} spans dropped)` : "");
  el.appendChild(head);
  for (const s of spans) {
    const row = document.createElement("div");
    row.className = "wf-row";
    const name = document.createElement("div");
    name.className = "wf-name";
    name.textContent = `${s.kind === "internal" ? "" : s.kind + " "}${s.name}`;
    name.title = JSON.stringify(s.attributes);
    const lane = document.createElement("div");
    lane.className = "wf-lane";
    const bar = document.createElement("div");
    bar.className = "wf-bar" + (s.status === "error" ? " err" : "");
    bar.style.left = `${(100 * (s.startMono - t0) / total).toFixed(2)}%`;
    bar.style.width =
      `${Math.max(0.2, 100 * (s.duration || 0) / total).toFixed(2)}%`;
    lane.appendChild(bar);
    const dur = document.createElement("div");
    dur.className = "wf-dur";
    dur.textContent = `${(1000 * (s.duration || 0)).toFixed(2)} ms`;
    row.appendChild(name); row.appendChild(lane); row.appendChild(dur);
    el.appendChild(row);
  }
  el.style.display = "";
}

function highlight() {
  for (const tr of document.querySelectorAll("#jobs tbody tr"))
    tr.classList.toggle("sel", tr.dataset.key === selected);
}

async function detail() {
  const [ns, name] = selected.split("/");
  const base = `/apis/v1/namespaces/${ns}/tpujobs/${name}`;
  const jobRes = await fetch(base);
  if (!jobRes.ok) {
    selected = null;
    document.getElementById("detail-title").style.display = "none";
    document.getElementById("detail").style.display = "none";
    document.getElementById("spark").style.display = "none";
    return;
  }
  const job = await jobRes.json();
  const events = (await (await fetch(base + "/events")).json()).items || [];
  const pods = (await (await fetch(base + "/pods")).json()).items || [];
  const series = (await (await fetch(base + "/metrics")).json()).items || [];
  let text = "";
  text += "conditions:\\n";
  for (const c of (job.status && job.status.conditions) || [])
    text += `  ${c.type.padEnd(12)} ${String(c.status).padEnd(6)} ` +
            `${(c.reason || "").padEnd(24)} ${c.message || ""}\\n`;
  text += "\\npods:\\n";
  for (const p of pods)
    text += `  ${p.name.padEnd(28)} ${p.phase}` +
            (p.exitCode != null ? ` (exit ${p.exitCode})` : "") + "\\n";
  text += "\\nevents:\\n";
  for (const e of events)
    text += `  ${e.type.padEnd(8)} ${e.reason.padEnd(24)} ${e.message}\\n`;
  if (series.length) {
    text += "\\nmetrics (last 10 of " + series.length + "):\\n";
    for (const m of series.slice(-10)) {
      const rest = Object.entries(m)
        .filter(([k]) => k !== "step" && k !== "time")
        .map(([k, v]) => `${k}=${typeof v === "number" ? v.toFixed(4) : v}`)
        .join(" ");
      text += `  step ${String(m.step).padEnd(8)} ${rest}\\n`;
    }
  }
  drawSpark(series);
  document.getElementById("detail-name").textContent = selected;
  document.getElementById("detail-title").style.display = "";
  const el = document.getElementById("detail");
  el.style.display = ""; el.textContent = text;
}

async function submitJob() {
  const ns = document.getElementById("ns").value.trim() || "default";
  const body = document.getElementById("manifest").value;
  const msg = document.getElementById("submit-msg");
  msg.textContent = "submitting...";
  const res = await fetch(
    `/apis/v1/namespaces/${encodeURIComponent(ns)}/tpujobs`,
    { method: "POST", headers: { "Content-Type": "application/yaml" }, body });
  if (res.ok) {
    const job = await res.json();
    msg.textContent = `created ${ns}/${job.metadata.name}`;
    document.getElementById("manifest").value = "";
    refresh();
  } else {
    const e = await res.json().catch(() => ({}));
    msg.textContent = `error ${res.status}: ${e.error || res.statusText}`;
  }
}

async function deleteJob() {
  if (!selected) return;
  const [ns, name] = selected.split("/");
  if (!confirm(`delete tpujob ${selected}? its pods will be torn down`))
    return;
  const res = await fetch(
    `/apis/v1/namespaces/${encodeURIComponent(ns)}/tpujobs/` +
    encodeURIComponent(name), { method: "DELETE" });
  const msg = document.getElementById("submit-msg");
  if (res.ok) { msg.textContent = `deleted ${selected}`; selected = null; }
  else {
    const e = await res.json().catch(() => ({}));
    msg.textContent = `delete error ${res.status}: ${e.error || ""}`;
  }
  refresh();
}

function drawSpark(series) {
  const el = document.getElementById("spark");
  const pts = series.filter(m => typeof m.loss === "number");
  if (pts.length < 2) { el.style.display = "none"; return; }
  const w = 420, h = 64, pad = 4;
  const losses = pts.map(m => m.loss);
  const lo = Math.min(...losses), hi = Math.max(...losses);
  const span = hi - lo || 1;
  const xy = losses.map((v, i) => {
    const x = pad + (w - 2 * pad) * i / (losses.length - 1);
    const y = pad + (h - 2 * pad) * (1 - (v - lo) / span);
    return `${x.toFixed(1)},${y.toFixed(1)}`;
  }).join(" ");
  el.style.display = "";
  el.innerHTML = "";
  const svg = document.createElementNS("http://www.w3.org/2000/svg", "svg");
  svg.setAttribute("width", w); svg.setAttribute("height", h);
  const line = document.createElementNS("http://www.w3.org/2000/svg", "polyline");
  line.setAttribute("points", xy);
  line.setAttribute("fill", "none");
  line.setAttribute("stroke", "#0b57d0");
  line.setAttribute("stroke-width", "1.5");
  svg.appendChild(line);
  const label = document.createElement("div");
  label.className = "muted";
  label.textContent =
    `loss ${losses[0].toFixed(4)} → ${losses[losses.length-1].toFixed(4)} ` +
    `(${pts.length} points)`;
  el.appendChild(svg); el.appendChild(label);
}

refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
