from tf_operator_tpu.server.api import ApiServer

__all__ = ["ApiServer"]
