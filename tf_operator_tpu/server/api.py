"""HTTP API for the operator process.

Parity: in the reference the Kubernetes API server *is* the job API and
the operator only serves metrics/health on a monitoring port (SURVEY.md
§2 "Operator entrypoint", "Metrics"); the dashboard's Go backend proxies
the API server (§1 L9).  Our local backends have no kube-apiserver, so
the operator binary carries the equivalent surface itself:

    GET  /healthz                                     liveness
    GET  /metrics                                     Prometheus text
    GET  /slo                                         control-plane SLO quantiles
    GET  /alerts                                      alert-engine state (firing first)
    GET  /autoscaler                                  scale decisions + policy state
    GET  /scheduler                                   fleet queue + decision log
    GET  /traces                                      recent trace summaries
    GET  /traces/{id}                                 one trace's span waterfall
    GET  /debug/stacks                                all-thread stack dump
    GET  /apis/v1/tpujobs                             list (all ns)
    GET  /apis/v1/namespaces/{ns}/tpujobs             list
    POST /apis/v1/namespaces/{ns}/tpujobs             create (manifest)
    GET  /apis/v1/namespaces/{ns}/tpujobs/{name}      get
    DEL  /apis/v1/namespaces/{ns}/tpujobs/{name}      delete
    GET  /apis/v1/namespaces/{ns}/tpujobs/{name}/events
    GET  /apis/v1/namespaces/{ns}/tpujobs/{name}/metrics   step series
    GET  /apis/v1/namespaces/{ns}/tpujobs/{name}/pods
    GET  /apis/v1/namespaces/{ns}/tpujobs/{name}/pods/{pod}/log

Everything is JSON; manifests use the serde camelCase shape (POST also
accepts YAML — the dashboard's submit box and `tpujob submit -f` both
speak it).  `/debug/stacks` is the pprof-equivalent debug surface the
reference exposes on its monitoring port (SURVEY.md §5 "optional Go
pprof"): a plain-text dump of every thread's current stack, served on
every replica (leader or not) because its job is diagnosing a hung
control plane.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple

from tf_operator_tpu.api.serde import job_from_dict, job_to_dict
from tf_operator_tpu.api.types import LABEL_JOB_NAME
from tf_operator_tpu.backend.base import AlreadyExistsError, ClusterBackend, NotFoundError
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.utils.events import EventRecorder
from tf_operator_tpu.utils.metrics import Metrics, finite_summary
from tf_operator_tpu.utils.trace import (
    TRACE_HEADER,
    Tracer,
    default_tracer,
    extract_headers,
)


def _job_timeline(tracer, ns: str, name: str) -> dict:
    """Traces linked to one job (reconcile sync / pod.create / folded
    pod spans), plus the newest one flattened chronologically — the
    ``GET .../tpujobs/{name}/timeline`` body."""

    key = f"{ns}/{name}"
    store = tracer.store
    matched = []
    for summary in store.summaries(limit=0):
        trace = store.trace(summary["traceId"])
        if trace is None:
            continue
        hit = False
        for s in trace["spans"]:
            # exact matches only: a name-PREFIX match on pod.create
            # would leak job "train" into "train-eval"'s timeline (and
            # across namespaces — pod names carry neither).  The
            # reconcile span name embeds <ns>/<name>; pod.create spans
            # carry the job key as an attribute.
            if (
                s.get("name", "") == f"reconcile {key}"
                or s.get("attributes", {}).get("job") == key
            ):
                hit = True
                break
        if hit:
            matched.append((summary["startUnix"], trace))
    matched.sort(key=lambda t: t[0])
    out = {"job": key, "traceIds": [t["traceId"] for _, t in matched]}
    if matched:
        # the flattened timeline prefers the newest trace carrying the
        # stitched vertical (a pod.create or folded pod-side train
        # span) — a busy job's newest matching trace is usually a
        # boring resync sync, which would bury the waterfall that
        # matters
        def vertical(trace) -> bool:
            return any(
                s.get("name", "").startswith(("pod.create ", "train "))
                for s in trace["spans"]
            )

        newest = next(
            (t for _, t in reversed(matched) if vertical(t)),
            matched[-1][1],
        )
        spans = sorted(
            newest["spans"], key=lambda s: s.get("startUnix", 0.0)
        )
        out["timeline"] = {
            "traceId": newest["traceId"],
            "droppedSpans": newest["droppedSpans"],
            "spans": [
                {
                    "name": s.get("name"),
                    "kind": s.get("kind"),
                    "startUnix": s.get("startUnix"),
                    "duration": s.get("duration"),
                    "status": s.get("status"),
                    "spanId": s.get("spanId"),
                    "parentId": s.get("parentId"),
                }
                for s in spans
            ],
        }
    return out


def _pod_to_dict(pod) -> dict:
    return {
        "name": pod.metadata.name,
        "namespace": pod.metadata.namespace,
        "labels": dict(pod.metadata.labels),
        # reconciler-stamped discovery (telemetry/fabric ports ride
        # tpujob.dist/* annotations — the tpujob CLI resolves them here)
        "annotations": dict(pod.metadata.annotations),
        "phase": pod.phase.value,
        "exitCode": pod.exit_code,
        "replicaType": pod.replica_type.value if pod.replica_type else None,
        "replicaIndex": pod.replica_index,
    }


class ApiServer:
    """Threaded HTTP server over a JobStore + ClusterBackend pair."""

    def __init__(
        self,
        job_store: JobStore,
        backend: ClusterBackend,
        metrics: Metrics,
        recorder: EventRecorder,
        host: str = "127.0.0.1",
        port: int = 0,
        namespace: str = "",
        leadership: Optional[Callable[[], Tuple[bool, Optional[str]]]] = None,
        tracer: Optional[Tracer] = None,
        alerts=None,
        autoscaler=None,
        telemetry=None,
        scheduler=None,
    ):
        self.jobs = job_store
        self.backend = backend
        self.metrics = metrics
        self.recorder = recorder
        #: utils/alerts.AlertEngine serving GET /alerts; defaults to the
        #: process-global engine so the endpoint exists (empty/inactive)
        #: even on binaries that never start an evaluator
        if alerts is None:
            from tf_operator_tpu.utils.alerts import default_engine

            alerts = default_engine
        self.alerts = alerts
        #: controller/autoscaler.Autoscaler serving GET /autoscaler;
        #: defaults to the process-global instance (same contract as
        #: /alerts: the endpoint exists, empty, on every binary)
        if autoscaler is None:
            from tf_operator_tpu.controller.autoscaler import (
                default_autoscaler,
            )

            autoscaler = default_autoscaler
        self.autoscaler = autoscaler
        #: controller/scheduler.Scheduler serving GET /scheduler; same
        #: contract as /autoscaler — the endpoint exists (empty queue)
        #: on every binary, populated only where a fleet scheduler runs
        if scheduler is None:
            from tf_operator_tpu.controller.scheduler import (
                default_scheduler,
            )

            scheduler = default_scheduler
        self.scheduler = scheduler
        #: controller/telemetry.TelemetryScraper serving GET /federate;
        #: defaults to the process-global instance (the /alerts
        #: contract: the endpoint exists, empty, on every binary)
        if telemetry is None:
            from tf_operator_tpu.controller.telemetry import default_scraper

            telemetry = default_scraper
        self.telemetry = telemetry
        #: request spans + the /traces read surface; in-process the
        #: controller, backends and (kube-sim) the embedded apiserver
        #: all share this tracer's store, so /traces/<id> returns the
        #: complete waterfall for one trace id
        self.tracer = tracer if tracer is not None else default_tracer
        #: when set, the job API serves only this namespace (--namespace)
        self.namespace = namespace
        #: () -> (is_leader, holder_identity).  With --leader-elect each
        #: standby has its OWN in-memory JobStore and no running
        #: controller — a create accepted there would 201 but never
        #: reconcile, and a read would serve the standby's EMPTY store
        #: (wrong, not just stale).  So the whole job API is refused
        #: with 503 + the current holder until this process leads; only
        #: /healthz, /metrics and the dashboard shell stay open.
        self.leadership = leadership
        outer = self

        class Handler(BaseHTTPRequestHandler):
            server_version = "tpu-operator/1"

            def log_message(self, fmt, *args):  # quiet by default
                pass

            # -- helpers ---------------------------------------------------
            def _send(self, code: int, payload, content_type="application/json"):
                body = (
                    payload.encode()
                    if isinstance(payload, str)
                    else json.dumps(payload, indent=1).encode()
                )
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                span = getattr(self, "_trace_span", None)
                if span is not None:
                    self.send_header(TRACE_HEADER, span.trace_id)
                    span.set_attribute("status", code)
                self.end_headers()
                self.wfile.write(body)

            @staticmethod
            def _route_class(route: str) -> str:
                """Bounded-cardinality route label for the request
                latency histogram: names/namespaces collapse to the
                resource shape (``tpujobs``, ``tpujobs/events``, ...)
                so a burst of jobs cannot mint unbounded label sets."""

                parts = [p for p in route.split("/") if p]
                if not parts:
                    return "/"
                if parts[0] != "apis":
                    return parts[0] if len(parts) == 1 else f"{parts[0]}/*"
                # /apis/v1/tpujobs | /apis/v1/namespaces/{ns}/tpujobs[/{name}[/sub...]]
                if parts[2:3] == ["namespaces"]:
                    rest = parts[4:]
                else:
                    rest = parts[2:]
                resource = rest[0] if rest else "?"
                sub = rest[2] if len(rest) > 2 else ""
                return f"{resource}/{sub}" if sub else resource

            def _traced(self, method: str, impl):
                """Run a verb handler under a server span (joining an
                incoming x-trace-id); observability endpoints are NOT
                traced — the dashboard polls them every 2s and the
                resulting ok-and-fast traces would only churn the
                store's eviction.  EVERY request (traced or not)
                observes ``api_request_seconds{method=,route=}`` — the
                control-plane half of the SLO exposition."""

                route = self.path.split("?")[0]
                t0 = time.perf_counter()
                try:
                    untraced = (
                        "/healthz", "/metrics", "/slo", "/alerts",
                        "/autoscaler", "/scheduler", "/traces",
                        "/debug", "/federate",
                    )
                    if method == "GET" and (
                        route == "/" or any(
                            route == u or route.startswith(u + "/")
                            for u in untraced
                        )
                    ):
                        # keep-alive reuses the handler across requests:
                        # a stale span from the previous request must
                        # not stamp this untraced response
                        self._trace_span = None
                        return impl()
                    tid, parent = extract_headers(self.headers)
                    span = outer.tracer.start_span(
                        f"api {method} {route}",
                        kind="server", trace_id=tid, parent_id=parent,
                        attributes={"method": method},
                    )
                    self._trace_span = span
                    with span:
                        return impl()
                finally:
                    outer.metrics.observe_histogram(
                        "api_request_seconds",
                        time.perf_counter() - t0,
                        method=method, route=self._route_class(route),
                    )

            def _error(self, code: int, message: str):
                self._send(code, {"error": message})

            def _route(self):
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                return parts

            def _not_leader(self) -> bool:
                if outer.leadership is None:
                    return False
                is_leader, holder = outer.leadership()
                if is_leader:
                    return False
                self._send(
                    503,
                    {
                        "error": "this operator replica is not the leader; "
                        "mutating verbs are served by the leader only",
                        "leader": holder or "unknown",
                    },
                )
                return True

            def _ns_forbidden(self, ns: str) -> bool:
                if outer.namespace and ns != outer.namespace:
                    self._error(
                        403,
                        f"operator is scoped to namespace {outer.namespace!r}",
                    )
                    return True
                return False

            # -- verbs -----------------------------------------------------
            def do_GET(self):
                return self._traced("GET", self._do_get)

            def do_POST(self):
                return self._traced("POST", self._do_post)

            def do_DELETE(self):
                return self._traced("DELETE", self._do_delete)

            def _do_get(self):
                p = self._route()
                try:
                    if not p:
                        from tf_operator_tpu.server.dashboard import DASHBOARD_HTML

                        return self._send(200, DASHBOARD_HTML, "text/html")
                    if p == ["healthz"]:
                        # liveness plus an apiserver-client fault digest
                        # (backend/retry.py counters): "ok" stays the
                        # first token so probes keep matching, and the
                        # digest tells an operator at a glance whether
                        # the control plane is riding out API faults
                        m = outer.metrics
                        body = (
                            "ok\n"
                            f"api_client_retries_total "
                            f"{m.total('api_client_retries_total'):g}\n"
                            f"api_client_giveups_total "
                            f"{m.total('api_client_giveups_total'):g}\n"
                            f"api_client_circuit_open_total "
                            f"{m.total('api_client_circuit_open_total'):g}\n"
                            f"api_events_dropped_total "
                            f"{m.total('api_events_dropped_total'):g}\n"
                        )
                        return self._send(200, body, "text/plain")
                    if p == ["metrics"]:
                        return self._send(
                            200, outer.metrics.exposition(), "text/plain"
                        )
                    if p == ["slo"]:
                        # the control-plane twin of serve_lm's /slo:
                        # per-label-set quantile summaries over the
                        # operator's latency families — both planes
                        # expose the same SLO read contract.  Merged
                        # across {replica=} like serve_lm's (an
                        # embedded/forwarded serving family must
                        # summarize as ONE fleet quantile, not N
                        # per-replica rows)
                        fams = {}
                        for fam in (
                            "api_request_seconds",
                            "tpujob_sync_duration_seconds",
                            "workqueue_queue_latency_seconds",
                        ):
                            fams[fam] = [
                                {**dict(labels), **finite_summary(summary)}
                                for labels, summary in sorted(
                                    outer.metrics.histogram_family_merged(
                                        fam
                                    ).items()
                                )
                            ]
                        return self._send(200, {
                            "histograms": fams,
                            "gauges": {
                                "workqueue_depth": outer.metrics.gauge(
                                    "workqueue_depth"
                                ),
                            },
                        })
                    if p == ["alerts"]:
                        # the alert engine's lifecycle state (firing
                        # first) — served on every replica like
                        # /metrics; the dashboard's alerts panel and
                        # external pollers read this
                        return self._send(200, outer.alerts.snapshot())
                    if p == ["autoscaler"]:
                        # the autoscaler's decision log + per-policy
                        # live state (breaching first) — the act half
                        # of the /alerts observe half
                        return self._send(200, outer.autoscaler.snapshot())
                    if p == ["scheduler"]:
                        # the fleet scheduler's pending queue (priority
                        # then age), admitted gangs, quota accounting
                        # and newest-first decision log — the `tpujob
                        # queue` read and the dashboard's queue panel
                        return self._send(200, outer.scheduler.snapshot())
                    if p == ["federate"]:
                        # fleet telemetry (ISSUE 15): every federated
                        # family — pod-scope series mirrored into the
                        # operator registry, decorated {job,
                        # replica_type, replica_index, slice} — in
                        # Prometheus text, the federation contract
                        return self._send(
                            200,
                            outer.telemetry.federate_text(),
                            "text/plain",
                        )
                    if p == ["federate", "targets"]:
                        # per-target scrape state, stale-first — the
                        # `tpujob telemetry` read and the dashboard's
                        # fleet panel
                        return self._send(
                            200, outer.telemetry.targets_snapshot()
                        )
                    # trace read surface: served on every replica
                    # (leader or standby) like /metrics — its job is
                    # diagnosing whichever process you can reach
                    if p == ["traces"]:
                        return self._send(
                            200,
                            {"items": outer.tracer.store.summaries()},
                        )
                    if len(p) == 2 and p[0] == "traces":
                        trace = outer.tracer.store.trace(p[1])
                        if trace is None:
                            return self._error(
                                404, f"trace {p[1]} not found (evicted?)"
                            )
                        return self._send(200, trace)
                    if p == ["debug", "stacks"]:
                        from tf_operator_tpu.utils.watchdog import (
                            thread_stacks,
                        )

                        return self._send(200, thread_stacks(), "text/plain")
                    if p == ["debug", "flightrecorder"]:
                        # the black-box rings (utils/flight.py): what
                        # this process was doing just now, as JSONL —
                        # served on every replica like /debug/stacks
                        from tf_operator_tpu.utils.flight import (
                            default_recorder,
                        )

                        return self._send(
                            200,
                            default_recorder.dump_text(),
                            "application/x-ndjson",
                        )
                    if p == ["debug", "compiles"]:
                        # the device cost plane's compile ledger
                        # (ISSUE 20): this PROCESS's view — compiles
                        # attributed to trigger classes with walls and
                        # trace ids; the serving twin lives on
                        # serve_lm's /debug/compiles, and `tpujob top`
                        # reads both
                        from tf_operator_tpu.utils.costplane import (
                            default_costplane,
                        )

                        return self._send(
                            200, default_costplane.compiles.snapshot()
                        )
                    if p == ["debug", "memory"]:
                        # the HBM accountant's per-device component
                        # table, headroom-worst-first, with the
                        # accounted-vs-live coverage ratio (ISSUE 20)
                        from tf_operator_tpu.utils.costplane import (
                            default_costplane,
                        )

                        return self._send(
                            200, default_costplane.hbm.snapshot()
                        )
                    if p[0] == "apis" and self._not_leader():
                        return None
                    if p == ["apis", "v1", "tpujobs"]:
                        return self._send(
                            200,
                            {
                                "items": [
                                    job_to_dict(j)
                                    for j in outer.jobs.list(
                                        outer.namespace or None
                                    )
                                ]
                            },
                        )
                    if len(p) >= 5 and p[:3] == ["apis", "v1", "namespaces"]:
                        ns = p[3]
                        if self._ns_forbidden(ns):
                            return None
                        if p[4] != "tpujobs":
                            return self._error(404, "unknown resource")
                        if len(p) == 5:
                            return self._send(
                                200,
                                {
                                    "items": [
                                        job_to_dict(j)
                                        for j in outer.jobs.list(ns)
                                    ]
                                },
                            )
                        name = p[5]
                        job = outer.jobs.get(ns, name)
                        if job is None:
                            return self._error(404, f"tpujob {ns}/{name} not found")
                        if len(p) == 6:
                            return self._send(200, job_to_dict(job))
                        if p[6] == "events":
                            evs = outer.recorder.for_object(f"{ns}/{name}")
                            return self._send(
                                200,
                                {
                                    "items": [
                                        {
                                            "type": e.type,
                                            "reason": e.reason,
                                            "message": e.message,
                                            "timestamp": e.timestamp,
                                        }
                                        for e in evs
                                    ]
                                },
                            )
                        if p[6] == "metrics":
                            from tf_operator_tpu.utils.summaries import (
                                ANNOTATION_SUMMARY_DIR,
                                read_series,
                            )

                            sdir = job.metadata.annotations.get(
                                ANNOTATION_SUMMARY_DIR
                            )
                            if not sdir:
                                return self._send(200, {"items": []})
                            return self._send(
                                200, {"items": read_series(sdir, limit=500)}
                            )
                        if p[6] == "timeline":
                            # the stitched reconcile→pod vertical
                            # (ISSUE 15): traces touching this job —
                            # a reconcile sync span, a pod.create, or
                            # a folded pod-side span — newest first,
                            # with the newest one's spans flattened
                            # chronologically
                            return self._send(
                                200, _job_timeline(outer.tracer, ns, name)
                            )
                        if p[6] == "pods":
                            pods = outer.backend.list_pods(
                                ns, {LABEL_JOB_NAME: name}
                            )
                            if len(p) == 7:
                                return self._send(
                                    200,
                                    {"items": [_pod_to_dict(x) for x in pods]},
                                )
                            pod_name, tail = p[7], p[8] if len(p) > 8 else ""
                            if tail == "log":
                                log_fn = getattr(outer.backend, "pod_log", None)
                                if log_fn is None:
                                    return self._error(
                                        501, "backend does not serve logs"
                                    )
                                return self._send(
                                    200, log_fn(ns, pod_name), "text/plain"
                                )
                    return self._error(404, "not found")
                except NotFoundError as e:
                    return self._error(404, str(e))
                except Exception as e:  # noqa: BLE001 - HTTP boundary
                    return self._error(500, f"{type(e).__name__}: {e}")

            def _do_post(self):
                p = self._route()
                try:
                    if self._not_leader():
                        return None
                    if (
                        len(p) == 5
                        and p[:3] == ["apis", "v1", "namespaces"]
                        and p[4] == "tpujobs"
                    ):
                        if self._ns_forbidden(p[3]):
                            return None
                        length = int(self.headers.get("Content-Length", 0))
                        raw = self.rfile.read(length)
                        try:
                            manifest = json.loads(raw)
                        except json.JSONDecodeError:
                            import yaml

                            try:
                                manifest = yaml.safe_load(raw)
                            except yaml.YAMLError as e:
                                return self._error(
                                    422, f"manifest parse error: {e}"
                                )
                        if not isinstance(manifest, dict):
                            return self._error(
                                422, "manifest must be a JSON/YAML mapping"
                            )
                        job = job_from_dict(manifest)
                        job.metadata.namespace = p[3]
                        stored = outer.jobs.create(job)
                        return self._send(201, job_to_dict(stored))
                    return self._error(404, "not found")
                except AlreadyExistsError as e:
                    return self._error(409, str(e))
                except (ValueError, KeyError, TypeError) as e:
                    # admission failure: bad manifest or validation error
                    return self._error(422, f"{type(e).__name__}: {e}")
                except Exception as e:  # noqa: BLE001 - HTTP boundary
                    return self._error(500, f"{type(e).__name__}: {e}")

            def _do_delete(self):
                p = self._route()
                try:
                    if self._not_leader():
                        return None
                    if (
                        len(p) == 6
                        and p[:3] == ["apis", "v1", "namespaces"]
                        and p[4] == "tpujobs"
                    ):
                        if self._ns_forbidden(p[3]):
                            return None
                        outer.jobs.delete(p[3], p[5])
                        return self._send(200, {"deleted": f"{p[3]}/{p[5]}"})
                    return self._error(404, "not found")
                except NotFoundError as e:
                    return self._error(404, str(e))
                except Exception as e:  # noqa: BLE001 - HTTP boundary
                    return self._error(500, f"{type(e).__name__}: {e}")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2.0)
