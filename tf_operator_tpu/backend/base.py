"""The cluster-backend interface: "5 verbs + watch" (SURVEY.md §7).

Parity: the slice of the Kubernetes API the reference's job controller
uses through client-go / PodControl / ServiceControl (SURVEY.md §2
"Generic job-controller runtime").  Kept deliberately tiny so the native
engine ↔ backend boundary stays manageable (SURVEY.md §7 hard part 5).
"""

from __future__ import annotations

import abc
from typing import Dict, List, Optional

from tf_operator_tpu.backend.objects import Pod, PodGroup, Service, WatchHandler


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(ValueError):
    pass


class ClusterBackend(abc.ABC):
    """Where pods/services/pod-groups live.

    Writes are requests to the cluster; observed state comes back
    asynchronously through the watch stream (level-triggered, like the
    reference's informers).  Reconcilers must NOT assume a create is
    visible in list results immediately — that gap is exactly what the
    Expectations mechanism guards (SURVEY.md §5 "Race detection").
    """

    # -- pods ---------------------------------------------------------------
    @abc.abstractmethod
    def create_pod(self, pod: Pod) -> None: ...

    @abc.abstractmethod
    def delete_pod(self, namespace: str, name: str) -> None: ...

    @abc.abstractmethod
    def list_pods(self, namespace: str, selector: Optional[Dict[str, str]] = None) -> List[Pod]: ...

    def update_pod_owner(self, namespace: str, name: str, owner_uid: Optional[str]) -> None:
        """Set (adopt) or clear (orphan) a pod's controller owner uid.

        ControllerRefManager parity (SURVEY.md §2 "Generic job-controller
        runtime"): the reconciler adopts label-matching ownerless pods and
        releases owned pods whose labels stopped matching.  Backends that
        cannot patch ownership may leave this unimplemented; the
        reconciler then skips adoption for them.
        """

        raise NotImplementedError

    # -- services -----------------------------------------------------------
    @abc.abstractmethod
    def create_service(self, svc: Service) -> None: ...

    @abc.abstractmethod
    def delete_service(self, namespace: str, name: str) -> None: ...

    @abc.abstractmethod
    def list_services(
        self, namespace: str, selector: Optional[Dict[str, str]] = None
    ) -> List[Service]: ...

    # -- gang groups --------------------------------------------------------
    @abc.abstractmethod
    def create_pod_group(self, group: PodGroup) -> None: ...

    @abc.abstractmethod
    def delete_pod_group(self, namespace: str, name: str) -> None: ...

    @abc.abstractmethod
    def update_pod_group(self, namespace: str, name: str, min_member: int, chip_request: int) -> None:
        """Resize a gang (dynamic scale); admission is re-evaluated."""

    @abc.abstractmethod
    def get_pod_group(self, namespace: str, name: str) -> Optional[PodGroup]: ...

    # -- watch --------------------------------------------------------------
    @abc.abstractmethod
    def subscribe(self, handler: WatchHandler) -> None:
        """Register a watch handler for all object kinds this backend owns."""

    def snapshot(self):
        """Full re-list for informer resync (SharedInformer parity,
        SURVEY.md §5: "periodic full re-list heals missed events").

        Returns (pods, services, pod_groups) — cloned, all namespaces —
        or None if this backend cannot re-list (resync then covers jobs
        only)."""

        return None

    def close(self) -> None:  # pragma: no cover - default no-op
        pass


def match_selector(labels: Dict[str, str], selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())
