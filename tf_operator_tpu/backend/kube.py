"""KubeBackend: the 5 ClusterBackend verbs + watch spoken as REAL
Kubernetes HTTP protocol (VERDICT r4 next #4).

Parity: the reference's client-go tier (SURVEY.md §1 L1 "Generated API
machinery", §2c row "Kubernetes API (HTTP/gRPC watch)") — typed
clients + shared watch streams against a kube-apiserver.  This client
speaks the genuine wire protocol:

- ``POST/GET/DELETE/PATCH`` against the real paths
  (``/api/v1/namespaces/{ns}/pods``,
  ``/apis/scheduling.volcano.sh/v1beta1/.../podgroups``), objects in
  real Kubernetes JSON (the same shapes ``backend/gke.py`` compiles —
  metadata/spec/status, ownerReferences, labelSelector list filters);
- ``?watch=true&resourceVersion=N`` chunked watch streams, one
  ``{"type": "ADDED"|"MODIFIED"|"DELETED", "object": {...}}`` JSON
  document per line, exactly client-go's framing;
- 409 Conflict → AlreadyExistsError, 404 → NotFoundError, and
  410 Gone on an expired watch window → full re-list + re-watch from
  the fresh resourceVersion (the client-go ListAndWatch recovery).

There is no cluster on this box (SURVEY.md §7: "a real GKE/TPU-VM
backend is an interface to be filled later"), so the server half is
``backend/kubesim.py`` — an in-repo threaded mini-apiserver with a
kubelet/scheduler simulation that runs pods as local subprocesses.
The client works against anything that speaks this protocol subset;
pointing it at a real apiserver is a ``--kube-url`` away (plus auth,
which the sim does not model).

The JSON codec lives here (``pod_to_json``/``pod_from_json`` etc.) and
is shared by the sim server, so both sides agree by construction and
the golden GKE compiler shapes stay the single source of truth for
what a compiled pod looks like.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
import urllib.parse
import urllib.request
from http.client import HTTPConnection
from typing import Any, Dict, List, Optional

_log = logging.getLogger("tpujob.kube")

from tf_operator_tpu.api.types import Container, ObjectMeta, PodPhase, Port
from tf_operator_tpu.backend.base import (
    AlreadyExistsError,
    ClusterBackend,
    NotFoundError,
)
from tf_operator_tpu.backend.local import LocalResolver
from tf_operator_tpu.backend.retry import NETWORK_ERRORS, watch_recovery
from tf_operator_tpu.backend.objects import (
    Pod,
    PodGroup,
    PodGroupPhase,
    Service,
    WatchEvent,
    WatchEventType,
    WatchHandler,
)

#: volcano's group apiVersion — the same wire shape backend/gke.py
#: compiles for gang scheduling
PODGROUP_API = "apis/scheduling.volcano.sh/v1beta1"
TPU_RESOURCE = "google.com/tpu"


# ---------------------------------------------------------------------------
# JSON codec: repo dataclasses <-> real Kubernetes object shapes
# ---------------------------------------------------------------------------


def _meta_to_json(meta: ObjectMeta) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "name": meta.name,
        "namespace": meta.namespace,
    }
    if meta.uid:
        out["uid"] = meta.uid
    if meta.labels:
        out["labels"] = dict(meta.labels)
    if meta.annotations:
        out["annotations"] = dict(meta.annotations)
    if meta.resource_version:
        out["resourceVersion"] = str(meta.resource_version)
    if meta.owner_uid:
        out["ownerReferences"] = [
            {
                "apiVersion": "tpujob.dist/v1",
                "kind": "TPUJob",
                "uid": meta.owner_uid,
                "controller": True,
            }
        ]
    return out


def _meta_from_json(m: Dict[str, Any]) -> ObjectMeta:
    owner_uid = ""
    for ref in m.get("ownerReferences", []):
        if ref.get("controller"):
            owner_uid = ref.get("uid", "")
            break
    rv = m.get("resourceVersion", "0")
    return ObjectMeta(
        name=m.get("name", ""),
        namespace=m.get("namespace", "default"),
        uid=m.get("uid", ""),
        labels=dict(m.get("labels", {})),
        annotations=dict(m.get("annotations", {})),
        resource_version=int(rv) if str(rv).isdigit() else 0,
        owner_uid=owner_uid,
    )


def _container_to_json(c: Container, chip_request: int) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": c.name}
    if c.image:
        out["image"] = c.image
    if c.command:
        out["command"] = list(c.command)
    if c.args:
        out["args"] = list(c.args)
    if c.env:
        out["env"] = [
            {"name": k, "value": v} for k, v in sorted(c.env.items())
        ]
    if c.ports:
        out["ports"] = [p.to_dict() for p in c.ports]
    resources = {k: dict(v) for k, v in (c.resources or {}).items()}
    if chip_request:
        limits = dict(resources.get("limits", {}))
        limits[TPU_RESOURCE] = str(chip_request)
        resources["limits"] = limits
    if resources:
        out["resources"] = resources
    if c.working_dir:
        out["workingDir"] = c.working_dir
    return out


def _container_from_json(c: Dict[str, Any]) -> Container:
    resources = {
        k: dict(v) for k, v in c.get("resources", {}).items()
        if isinstance(v, dict)
    }
    # the chip request round-trips separately (pod_from_json); keep the
    # raw resources dict as-is so unknown limits survive
    return Container(
        name=c.get("name", "tensorflow"),
        image=c.get("image", ""),
        command=list(c.get("command", [])),
        args=list(c.get("args", [])),
        env={e["name"]: e.get("value", "") for e in c.get("env", [])},
        ports=[
            Port(name=p.get("name", ""), container_port=p["containerPort"])
            for p in c.get("ports", [])
        ],
        resources=resources,
        working_dir=c.get("workingDir", ""),
    )


def pod_to_json(pod: Pod) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "containers": [
            _container_to_json(c, pod.chip_request) for c in pod.containers
        ],
    }
    if pod.node_selector:
        spec["nodeSelector"] = dict(pod.node_selector)
    if pod.scheduler_name:
        spec["schedulerName"] = pod.scheduler_name
    status: Dict[str, Any] = {"phase": pod.phase.value}
    cstatus: Dict[str, Any] = {
        "name": pod.containers[0].name if pod.containers else "tensorflow",
        "restartCount": pod.restart_count,
    }
    if pod.exit_code is not None:
        cstatus["state"] = {"terminated": {"exitCode": pod.exit_code}}
    status["containerStatuses"] = [cstatus]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": _meta_to_json(pod.metadata),
        "spec": spec,
        "status": status,
    }


def pod_from_json(obj: Dict[str, Any]) -> Pod:
    spec = obj.get("spec", {})
    status = obj.get("status", {})
    containers = [_container_from_json(c) for c in spec.get("containers", [])]
    chip_request = 0
    for c in spec.get("containers", []):
        limits = c.get("resources", {}).get("limits", {})
        if TPU_RESOURCE in limits:
            chip_request = int(limits[TPU_RESOURCE])
            break
    exit_code = None
    restart_count = 0
    for cs in status.get("containerStatuses", []):
        restart_count = int(cs.get("restartCount", 0))
        term = cs.get("state", {}).get("terminated")
        if term is not None and "exitCode" in term:
            exit_code = int(term["exitCode"])
        break
    try:
        phase = PodPhase(status.get("phase", "Pending"))
    except ValueError:
        phase = PodPhase.UNKNOWN
    return Pod(
        metadata=_meta_from_json(obj.get("metadata", {})),
        containers=containers,
        scheduler_name=spec.get("schedulerName", ""),
        node_selector=dict(spec.get("nodeSelector", {})),
        phase=phase,
        exit_code=exit_code,
        restart_count=restart_count,
        chip_request=chip_request,
    )


def service_to_json(svc: Service) -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta_to_json(svc.metadata),
        "spec": {
            "clusterIP": "None",
            "selector": dict(svc.selector),
            "ports": [{"port": svc.port}] if svc.port else [],
        },
    }


def service_from_json(obj: Dict[str, Any]) -> Service:
    spec = obj.get("spec", {})
    ports = spec.get("ports", [])
    return Service(
        metadata=_meta_from_json(obj.get("metadata", {})),
        selector=dict(spec.get("selector", {})),
        port=int(ports[0]["port"]) if ports else 0,
    )


def podgroup_to_json(group: PodGroup) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "apiVersion": "scheduling.volcano.sh/v1beta1",
        "kind": "PodGroup",
        "metadata": _meta_to_json(group.metadata),
        "spec": {"minMember": group.min_member},
        "status": {"phase": group.phase.value},
    }
    if group.chip_request:
        out["spec"]["minResources"] = {TPU_RESOURCE: str(group.chip_request)}
    return out


def podgroup_from_json(obj: Dict[str, Any]) -> PodGroup:
    spec = obj.get("spec", {})
    chip = spec.get("minResources", {}).get(TPU_RESOURCE, "0")
    try:
        phase = PodGroupPhase(obj.get("status", {}).get("phase", "Pending"))
    except ValueError:
        phase = PodGroupPhase.PENDING
    return PodGroup(
        metadata=_meta_from_json(obj.get("metadata", {})),
        min_member=int(spec.get("minMember", 0)),
        chip_request=int(chip),
        phase=phase,
    )


KINDS = {
    "Pod": (pod_to_json, pod_from_json),
    "Service": (service_to_json, service_from_json),
    "PodGroup": (podgroup_to_json, podgroup_from_json),
}


def selector_param(selector: Optional[Dict[str, str]]) -> str:
    if not selector:
        return ""
    return ",".join(f"{k}={v}" for k, v in sorted(selector.items()))


def parse_selector(param: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in param.split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# HTTP error mapping
# ---------------------------------------------------------------------------


class ApiError(RuntimeError):
    def __init__(self, status: int, body: str):
        self.status = status
        #: float seconds from a Retry-After header, when the server
        #: sent one (429/503) — honored by backend/retry.RetryPolicy
        self.retry_after: Optional[float] = None
        super().__init__(f"apiserver {status}: {body[:200]}")


class GoneError(ApiError):
    """410: the requested resourceVersion fell out of the watch window."""


# ---------------------------------------------------------------------------
# the client
# ---------------------------------------------------------------------------


def http_json(
    host: str, port: int, method: str, path: str,
    body: Optional[dict] = None, timeout: float = 5.0,
    policy=None, metrics=None, client: str = "api", breaker=None,
    tracer=None,
) -> dict:
    """One JSON request with the apiserver error mapping (shared by
    KubeBackend and the TPUJob store, backend/kubejobs.py).

    With ``policy`` (a backend/retry.RetryPolicy) the request retries
    transient failures — 429/5xx responses, connection resets, broken
    sockets — under the policy's jittered-backoff budget, honoring
    Retry-After; 404/409/410 stay semantic and raise immediately.

    Tracing: when a trace is active (utils/trace contextvar), EVERY
    attempt — including each retry — records its own client span
    tagged with the 0-based ``attempt`` number and carries the trace
    id to the server in ``x-trace-id``, so one waterfall shows the
    whole retry ladder against the apiserver's matching server spans.
    Semantic statuses (404/409/410) stay span-status ok — they are
    normal reconcile traffic, exactly like the retry classifier.
    """

    from tf_operator_tpu.utils.trace import default_tracer, inject_headers

    tr = tracer if tracer is not None else default_tracer
    route = path.split("?")[0]
    attempt_n = [0]

    def attempt() -> dict:
        span = None
        if tr.current_trace_id() is not None:
            span = tr.start_span(
                f"http {method} {route}",
                kind="client",
                attributes={
                    "client": client, "method": method,
                    "attempt": attempt_n[0],
                },
            )
        attempt_n[0] += 1
        conn = HTTPConnection(host, port, timeout=timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            if span is not None:
                inject_headers(headers, span)
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            text = resp.read().decode(errors="replace")
            if span is not None:
                span.set_attribute("status", resp.status)
                if "FaultInjected" in text:
                    span.set_attribute("injectedFault", True)
            if resp.status == 404:
                raise NotFoundError(path)
            if resp.status == 409:
                raise AlreadyExistsError(path)
            if resp.status == 410:
                raise GoneError(410, text)
            if resp.status >= 400:
                err = ApiError(resp.status, text)
                ra = resp.getheader("Retry-After")
                if ra is not None:
                    try:
                        err.retry_after = float(ra)
                    except ValueError:
                        pass
                if span is not None:
                    span.set_error(f"apiserver {resp.status}")
                raise err
            return json.loads(text) if text else {}
        except NETWORK_ERRORS as e:
            if span is not None:
                span.set_error(f"{type(e).__name__}: {e}")
            raise
        finally:
            if span is not None:
                span.end()
            conn.close()

    if policy is None:
        return attempt()
    return policy.call(
        attempt, client=client, metrics=metrics, breaker=breaker,
    )


class KubeBackend(ClusterBackend):
    """ClusterBackend over the Kubernetes HTTP protocol.

    One background thread per resource kind runs the client-go
    ListAndWatch loop: list (capturing resourceVersion) → chunked
    watch from that version → dispatch events to subscribers → on
    disconnect or 410 Gone, re-list and re-watch.  Writes are plain
    REST verbs; the async gap between a write and its watch event is
    exactly the informer-cache lag the Expectations machinery guards
    (the sim can be told to delay delivery to test this, but the
    protocol itself is already asynchronous).
    """

    def __init__(
        self,
        base_url: str,
        connect_timeout: float = 5.0,
        retry=None,
        metrics=None,
        breaker=None,
        tracer=None,
    ):
        from tf_operator_tpu.backend.retry import CircuitBreaker, default_policy
        from tf_operator_tpu.utils.metrics import default_metrics
        from tf_operator_tpu.utils.trace import default_tracer

        u = urllib.parse.urlparse(base_url)
        if u.scheme != "http":
            raise ValueError(f"KubeBackend speaks plain http; got {base_url!r}")
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.timeout = connect_timeout
        #: retry policy for every plain REST verb (watch streams have
        #: their own ListAndWatch recovery loop, which this policy's
        #: jittered backoff also paces)
        self.retry = retry if retry is not None else default_policy()
        self.metrics = metrics if metrics is not None else default_metrics
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.tracer = tracer if tracer is not None else default_tracer
        #: local subprocess pods → local address resolution, same
        #: contract as LocalProcessBackend.resolver
        self.resolver = LocalResolver()
        self._handlers: List[WatchHandler] = []
        self._handlers_lock = threading.Lock()
        self._stop = threading.Event()
        self._watchers: List[threading.Thread] = []
        self._watch_conns: List[HTTPConnection] = []
        self._started = False

    # -- plain REST ---------------------------------------------------------

    def _request(
        self, method: str, path: str, body: Optional[dict] = None
    ) -> dict:
        return http_json(
            self.host, self.port, method, path, body, self.timeout,
            policy=self.retry, metrics=self.metrics, client="kube-backend",
            breaker=self.breaker, tracer=self.tracer,
        )

    @staticmethod
    def _collection(kind: str, namespace: Optional[str] = None) -> str:
        prefix = "/api/v1" if kind in ("Pod", "Service") else f"/{PODGROUP_API}"
        plural = {"Pod": "pods", "Service": "services", "PodGroup": "podgroups"}[kind]
        if namespace is None:
            return f"{prefix}/{plural}"
        return f"{prefix}/namespaces/{namespace}/{plural}"

    def _create(self, kind: str, obj) -> None:
        to_json, _ = KINDS[kind]
        ns = obj.metadata.namespace
        out = self._request("POST", self._collection(kind, ns), to_json(obj))
        # the server assigns uid + resourceVersion; reflect them back
        # into the caller's object like client-go's Create does
        meta = out.get("metadata", {})
        obj.metadata.uid = meta.get("uid", obj.metadata.uid)
        rv = meta.get("resourceVersion", "0")
        obj.metadata.resource_version = int(rv) if str(rv).isdigit() else 0

    def _delete(self, kind: str, namespace: str, name: str) -> None:
        self._request(
            "DELETE", f"{self._collection(kind, namespace)}/{name}"
        )

    def _list(
        self, kind: str, namespace: Optional[str],
        selector: Optional[Dict[str, str]] = None,
    ) -> tuple:
        _, from_json = KINDS[kind]
        path = self._collection(kind, namespace)
        sel = selector_param(selector)
        if sel:
            path += "?labelSelector=" + urllib.parse.quote(sel)
        out = self._request("GET", path)
        rv = out.get("metadata", {}).get("resourceVersion", "0")
        items = [from_json(o) for o in out.get("items", [])]
        return items, int(rv) if str(rv).isdigit() else 0

    # -- ClusterBackend verbs ----------------------------------------------

    def create_pod(self, pod: Pod) -> None:
        self._create("Pod", pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        self._delete("Pod", namespace, name)

    def list_pods(self, namespace: str, selector=None) -> List[Pod]:
        items, _ = self._list("Pod", namespace, selector)
        return items

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        try:
            out = self._request(
                "GET", f"{self._collection('Pod', namespace)}/{name}"
            )
        except NotFoundError:
            return None
        return pod_from_json(out)

    def update_pod_owner(
        self, namespace: str, name: str, owner_uid: Optional[str]
    ) -> None:
        refs = (
            [{
                "apiVersion": "tpujob.dist/v1",
                "kind": "TPUJob",
                "uid": owner_uid,
                "controller": True,
            }]
            if owner_uid
            else []
        )
        self._request(
            "PATCH",
            f"{self._collection('Pod', namespace)}/{name}",
            {"metadata": {"ownerReferences": refs}},
        )

    def pod_log(self, namespace: str, name: str) -> str:
        conn = HTTPConnection(self.host, self.port, timeout=self.timeout)
        try:
            conn.request(
                "GET", f"{self._collection('Pod', namespace)}/{name}/log"
            )
            resp = conn.getresponse()
            text = resp.read().decode(errors="replace")
            return text if resp.status == 200 else ""
        finally:
            conn.close()

    def create_service(self, svc: Service) -> None:
        self._create("Service", svc)

    def delete_service(self, namespace: str, name: str) -> None:
        self._delete("Service", namespace, name)

    def list_services(self, namespace: str, selector=None) -> List[Service]:
        items, _ = self._list("Service", namespace, selector)
        return items

    def create_pod_group(self, group: PodGroup) -> None:
        self._create("PodGroup", group)

    def delete_pod_group(self, namespace: str, name: str) -> None:
        self._delete("PodGroup", namespace, name)

    def update_pod_group(
        self, namespace: str, name: str, min_member: int, chip_request: int
    ) -> None:
        body: Dict[str, Any] = {"spec": {"minMember": min_member}}
        if chip_request:
            body["spec"]["minResources"] = {TPU_RESOURCE: str(chip_request)}
        else:
            body["spec"]["minResources"] = {}
        self._request(
            "PATCH",
            f"{self._collection('PodGroup', namespace)}/{name}",
            body,
        )

    def get_pod_group(self, namespace: str, name: str) -> Optional[PodGroup]:
        try:
            out = self._request(
                "GET", f"{self._collection('PodGroup', namespace)}/{name}"
            )
        except NotFoundError:
            return None
        return podgroup_from_json(out)

    def snapshot(self):
        """Full re-list of all three kinds (informer resync)."""

        pods, _ = self._list("Pod", None)
        services, _ = self._list("Service", None)
        groups, _ = self._list("PodGroup", None)
        return pods, services, groups

    # -- watch --------------------------------------------------------------

    def subscribe(self, handler: WatchHandler) -> None:
        with self._handlers_lock:
            self._handlers.append(handler)
            if not self._started:
                self._started = True
                for kind in KINDS:
                    t = threading.Thread(
                        target=self._watch_loop, args=(kind,), daemon=True,
                        name=f"kube-watch-{kind.lower()}",
                    )
                    self._watchers.append(t)
                    t.start()

    def _dispatch(self, ev: WatchEvent) -> None:
        with self._handlers_lock:
            handlers = list(self._handlers)
        for h in handlers:
            h(ev)

    def _watch_loop(self, kind: str) -> None:
        """client-go ListAndWatch: list → watch from rv → on
        disconnect/410, list again and re-watch.  Events between the
        dropped stream and the fresh list are healed by the informer's
        periodic resync (snapshot), the same division of labour as the
        reference."""

        _, from_json = KINDS[kind]
        rv = 0
        fails = 0  # consecutive broken streams/relists → jittered backoff
        while not self._stop.is_set():
            try:
                if rv == 0:
                    items, rv = self._list(kind, None)
                    # client-go ListAndWatch feeds the LISTED objects
                    # to the informer, not just the resourceVersion:
                    # objects that existed before this client started
                    # (operator restart over a live cluster) must
                    # reach the cache as events, or a fresh reconciler
                    # would re-create pods that already run.  Replayed
                    # ADDEDs on reconnect are level-triggered no-ops.
                    for obj in items:
                        self._dispatch(
                            WatchEvent(
                                type=WatchEventType.ADDED,
                                kind=kind,
                                obj=obj,
                            )
                        )
                # resume from the last event the stream delivered — a
                # cleanly closed stream (real apiservers recycle watch
                # connections every few minutes) re-watches from there,
                # NOT from the stale list rv (which would replay every
                # event since the initial list as duplicates)
                rv = self._stream(kind, rv, from_json)
                fails = 0
            except GoneError:
                # expired window (or an injected 410 storm): full
                # re-list, with backoff so a storm can't relist-spin
                fails = watch_recovery(
                    fails, stop=self._stop, policy=self.retry,
                    metrics=self.metrics, kind=kind, gone=True,
                )
                rv = 0
            except Exception as e:  # noqa: BLE001 - ListAndWatch recovery
                # anything else is a broken stream (half-closed socket
                # raises assorted http.client internals mid-chunk);
                # recover exactly like client-go: re-list, re-watch —
                # under jittered backoff so a flapping apiserver isn't
                # hammered by every watcher at once
                fails = watch_recovery(
                    fails, stop=self._stop, policy=self.retry,
                    metrics=self.metrics, kind=kind, log=_log, exc=e,
                )
                rv = 0

    def _stream(self, kind: str, rv: int, from_json) -> int:
        """One watch connection; returns the resourceVersion of the
        last event delivered (== the passed rv if none arrived) so the
        caller can resume without duplicates after a clean close."""

        conn = HTTPConnection(self.host, self.port)
        self._watch_conns.append(conn)
        try:
            path = (
                f"{self._collection(kind, None)}"
                f"?watch=true&resourceVersion={rv}"
            )
            conn.request("GET", path)
            resp = conn.getresponse()
            if resp.status == 410:
                raise GoneError(410, "")
            if resp.status != 200:
                raise ApiError(resp.status, "")
            while not self._stop.is_set():
                line = resp.readline()
                if not line:
                    return rv  # clean close: resume from last event
                line = line.strip()
                if not line:
                    continue
                doc = json.loads(line)
                if doc.get("type") == "ERROR":
                    status = doc.get("object", {})
                    if status.get("code") == 410:
                        raise GoneError(410, "")
                    raise ApiError(int(status.get("code", 500)), str(status))
                obj = from_json(doc["object"])
                rv = max(rv, obj.metadata.resource_version)
                self._dispatch(
                    WatchEvent(
                        type=WatchEventType(doc["type"]), kind=kind, obj=obj
                    )
                )
            return rv
        finally:
            try:
                self._watch_conns.remove(conn)
            except ValueError:
                pass
            conn.close()

    def close(self) -> None:
        self._stop.set()
        for conn in list(self._watch_conns):
            try:
                conn.sock and conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        for t in self._watchers:
            t.join(timeout=2.0)
