"""TPUJob store — the "API server" surface for job objects.

Parity: the reference's TFJob CRD storage + admission path (SURVEY.md §1
L1/L4): create runs defaulting and validation (the CRD admission
equivalent), status updates go through a dedicated method (the status
subresource), and watchers receive job events that the controller's
informer handlers consume (SURVEY.md §2 "Job lifecycle hooks").

In-proc for both the fake and local-process backends; a real-cluster
backend would implement the same surface over its control plane.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional

from tf_operator_tpu.api.defaults import set_defaults
from tf_operator_tpu.api.types import TPUJob, TPUJobStatus
from tf_operator_tpu.api.validation import validate
from tf_operator_tpu.backend.base import AlreadyExistsError, NotFoundError
from tf_operator_tpu.backend.objects import WatchEvent, WatchEventType, WatchHandler


class JobStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._jobs: Dict[str, TPUJob] = {}
        self._handlers: List[WatchHandler] = []
        self._uid_counter = itertools.count(1)

    def subscribe(self, handler: WatchHandler) -> None:
        with self._lock:
            self._handlers.append(handler)

    def _emit(self, etype: WatchEventType, job: TPUJob) -> None:
        ev = WatchEvent(type=etype, kind="TPUJob", obj=job)
        for h in list(self._handlers):
            h(ev)

    def create(self, job: TPUJob) -> TPUJob:
        """Admission: default, validate, assign uid, store, notify."""

        with self._lock:
            if job.key in self._jobs:
                raise AlreadyExistsError(job.key)
            set_defaults(job)
            validate(job)
            if not job.metadata.uid:
                job.metadata.uid = f"job-uid-{next(self._uid_counter)}"
            stored = job.deepcopy()
            self._jobs[stored.key] = stored
            self._emit(WatchEventType.ADDED, stored)
            return stored.deepcopy()

    def get(self, namespace: str, name: str) -> Optional[TPUJob]:
        with self._lock:
            job = self._jobs.get(f"{namespace}/{name}")
            return job.deepcopy() if job else None

    def list(self, namespace: Optional[str] = None) -> List[TPUJob]:
        with self._lock:
            return [
                j.deepcopy()
                for j in self._jobs.values()
                if namespace is None or j.metadata.namespace == namespace
            ]

    def update_status(self, namespace: str, name: str, status: TPUJobStatus) -> TPUJob:
        """The status-subresource write (SURVEY.md §3.2 final step)."""

        with self._lock:
            job = self._jobs.get(f"{namespace}/{name}")
            if job is None:
                raise NotFoundError(f"{namespace}/{name}")
            job.status = status.clone()  # never alias caller state
            job.metadata.resource_version += 1
            self._emit(WatchEventType.MODIFIED, job.deepcopy())
            return job.deepcopy()

    def update_spec(self, job: TPUJob) -> TPUJob:
        """Spec edits (e.g. scaling Replicas for dynamic workers)."""

        with self._lock:
            stored = self._jobs.get(job.key)
            if stored is None:
                raise NotFoundError(job.key)
            set_defaults(job)
            validate(job)
            stored.spec = job.spec.clone()
            stored.metadata.resource_version += 1
            self._emit(WatchEventType.MODIFIED, stored.deepcopy())
            return stored.deepcopy()

    def delete(self, namespace: str, name: str) -> None:
        with self._lock:
            job = self._jobs.pop(f"{namespace}/{name}", None)
            if job is None:
                raise NotFoundError(f"{namespace}/{name}")
            self._emit(WatchEventType.DELETED, job)
