"""Shared API-client fault tolerance: RetryPolicy + CircuitBreaker.

Parity: client-go wraps every apiserver round-trip in rest.Request's
backoff manager + the shared flowcontrol rate limiter, and the
reference operator inherits that for free.  The HTTP clients here
(``backend/kube.py``, ``backend/kubejobs.py``, ``cmd/leader.py``) were
single naked calls; this module is the one place their retry behaviour
lives so all three layers degrade the same way under apiserver faults
(``backend/kubesim.FaultInjector`` is the matching server half).

Semantics:

- **exponential backoff with full jitter**: attempt ``n`` sleeps
  ``uniform(0, min(base * 2**n, max_delay))`` — the AWS-architecture
  full-jitter scheme, chosen so a fleet of clients whose requests all
  failed together (apiserver restart) do not re-arrive together;
- **retry-on rules**: 429/500/502/503/504 responses retry for every
  verb (the sim injects faults *before* the verb executes, and against
  a real apiserver a replayed create surfaces as 409 → the reconciler
  already treats AlreadyExists as success); 404/409/410 are semantic
  outcomes and never retry; network-level errors (connection refused/
  reset, half-closed sockets mid-chunk) retry likewise;
- **Retry-After honoring**: a 429/503 carrying ``Retry-After`` floors
  the next sleep at that value (capped — a hostile/buggy server must
  not park a client for minutes);
- **budgets**: ``max_attempts`` bounds tries; ``deadline`` bounds the
  wall-clock a call spends before dispatching another attempt —
  attempts themselves are not preemptible, so a call can overrun the
  deadline by at most ONE in-flight attempt (the transport timeout);
- **circuit breaker**: after N *consecutive* retryable failures the
  circuit opens and calls fail fast with ``CircuitOpenError``, except
  one serialized probe at a time — a hung apiserver costs one parked
  thread instead of one per caller, and a recovered apiserver closes
  the circuit on the very first call after it returns.

Observability: every retry/giveup/circuit transition increments
labelled counters in a ``utils/metrics.Metrics`` registry and stamps a
last-error gauge, so ``/metrics`` (and the dashboard's client-health
panel) shows exactly how rough the apiserver connection is.
"""

from __future__ import annotations

import http.client
import random
import threading
import time
from typing import Callable, Optional

from tf_operator_tpu.utils.trace import current_trace_id

#: statuses safe to retry blindly (see module docstring for why this
#: includes non-idempotent verbs against this operator's apiservers)
RETRYABLE_STATUS = (429, 500, 502, 503, 504)

#: transport-level failures: the request may never have been processed
NETWORK_ERRORS = (OSError, http.client.HTTPException)


class CircuitOpenError(RuntimeError):
    """Fail-fast result while the breaker is open (apiserver presumed
    down); callers treat it like any other transient API error."""


class CircuitBreaker:
    """Consecutive-failure breaker with SERIALIZED probes.

    After ``failure_threshold`` consecutive retryable failures the
    circuit opens.  Open does not time-gate recovery: one caller at a
    time is let straight through as the probe — so the first call
    after the apiserver returns succeeds immediately and closes the
    circuit (a time-gated half-open would keep refusing service for a
    reset window after recovery, which turned an healed outage into
    spurious 5xx from the operator's own API).  While a probe is in
    flight every other caller fails fast — the protection that matters
    when the apiserver *hangs* rather than refuses, because at most
    one thread is ever parked on the dead connection.  A probe stuck
    past ``probe_timeout`` is presumed dead and its slot reclaimed.
    """

    def __init__(
        self,
        failure_threshold: int = 8,
        probe_timeout: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = int(failure_threshold)
        self.probe_timeout = float(probe_timeout)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._open = False
        self._probe_started: Optional[float] = None

    def _probe_active_locked(self) -> bool:
        return (
            self._probe_started is not None
            and self._clock() - self._probe_started < self.probe_timeout
        )

    @property
    def state(self) -> str:
        """closed / open (tripped, next caller becomes the probe) /
        half-open (tripped with the trial probe in flight)."""

        with self._lock:
            if not self._open:
                return "closed"
            return "half-open" if self._probe_active_locked() else "open"

    def allow(self) -> bool:
        """True when a call may proceed (closed, or this caller takes
        the probe slot)."""

        with self._lock:
            if not self._open:
                return True
            if self._probe_active_locked():
                return False  # another thread holds the probe slot
            self._probe_started = self._clock()
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._open = False
            self._probe_started = None

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probe_started = None
            if self._failures >= self.failure_threshold:
                self._open = True


class RetryPolicy:
    """Exponential-backoff-with-full-jitter retry around one callable.

    Shareable across threads; per-call state is local.  ``sleep`` and
    ``rng`` are injectable so tests run deterministic and instant.
    """

    def __init__(
        self,
        max_attempts: int = 5,
        base_delay: float = 0.05,
        max_delay: float = 2.0,
        deadline: Optional[float] = 15.0,
        retry_status=RETRYABLE_STATUS,
        retry_after_cap: float = 5.0,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.deadline = None if deadline is None else float(deadline)
        self.retry_status = frozenset(retry_status)
        self.retry_after_cap = float(retry_after_cap)
        self._rng = rng or random.Random()
        self._sleep = sleep
        self._clock = clock

    # -- classification -----------------------------------------------------

    def classify(self, exc: BaseException):
        """(retryable, retry_after_floor) for a raised attempt.

        Duck-typed on ``.status`` / ``.retry_after`` so this module
        doesn't import the client error types it serves (kube.py
        imports us)."""

        status = getattr(exc, "status", None)
        if isinstance(status, int):
            if status in self.retry_status:
                ra = getattr(exc, "retry_after", None)
                return True, (float(ra) if ra is not None else None)
            return False, None
        if isinstance(exc, NETWORK_ERRORS):
            return True, None
        return False, None

    def backoff(self, attempt: int) -> float:
        """Full-jitter delay for the given 0-based attempt number."""

        cap = min(self.base_delay * (2.0 ** attempt), self.max_delay)
        return self._rng.uniform(0.0, cap)

    # -- the loop -----------------------------------------------------------

    def call(
        self,
        fn: Callable[[], object],
        *,
        client: str = "api",
        metrics=None,
        breaker: Optional[CircuitBreaker] = None,
        retryable_result: Optional[Callable[[object], object]] = None,
    ):
        """Run ``fn`` under this policy.

        ``retryable_result`` covers clients that return statuses rather
        than raising (cmd/leader.py): a truthy verdict retries like a
        retryable exception — return a float to floor the next sleep
        at a server-advertised Retry-After — and the last result is
        RETURNED (not raised) when the budget runs out, so the caller
        keeps its own status handling.  On giveup after raised errors,
        the last underlying exception re-raises unchanged so caller
        ``except`` clauses keep working.
        """

        start = self._clock()
        attempt = 0
        while True:
            if breaker is not None and not breaker.allow():
                if metrics is not None:
                    metrics.inc(
                        "api_client_circuit_open_total",
                        exemplar=current_trace_id(),
                        client=client,
                    )
                raise CircuitOpenError(
                    f"{client}: circuit open (apiserver presumed down)"
                )
            try:
                out = fn()
            except Exception as e:  # noqa: BLE001 - classification below
                retryable, retry_after = self.classify(e)
                if breaker is not None:
                    if retryable:
                        breaker.record_failure()
                    else:
                        breaker.record_success()  # server answered
                if not retryable:
                    # semantic outcomes (404 probe-miss, 409 create
                    # race, 410 window-expiry) are normal reconcile
                    # traffic — counting them would make a perfectly
                    # healthy client look permanently degraded
                    raise
                if metrics is not None:
                    metrics.inc(
                        "api_client_errors_total",
                        exemplar=current_trace_id(),
                        client=client,
                        error=type(e).__name__,
                    )
                    metrics.set(
                        "api_client_last_error_unixtime",
                        time.time(),
                        client=client,
                    )
                if not self._schedule(
                    start, attempt, retry_after, client, metrics
                ):
                    raise
                attempt += 1
                continue
            verdict = (
                retryable_result(out)
                if retryable_result is not None
                else None
            )
            # ANY numeric verdict — including 0.0, a legal
            # "Retry-After: 0, retry immediately" — means retry; only
            # False/None mean the result is final (bool first: False
            # is an int instance)
            if isinstance(verdict, bool):
                retry_wanted, result_retry_after = verdict, None
            elif isinstance(verdict, (int, float)):
                retry_wanted, result_retry_after = True, float(verdict)
            else:
                retry_wanted, result_retry_after = bool(verdict), None
            if retry_wanted:
                if metrics is not None:
                    metrics.inc(
                        "api_client_errors_total",
                        exemplar=current_trace_id(),
                        client=client,
                        error="retryable_status",
                    )
                    metrics.set(
                        "api_client_last_error_unixtime",
                        time.time(),
                        client=client,
                    )
                if breaker is not None:
                    breaker.record_failure()
                if not self._schedule(
                    start, attempt, result_retry_after, client, metrics
                ):
                    return out  # budget spent: caller sees the status
                attempt += 1
                continue
            if breaker is not None:
                breaker.record_success()
            return out

    def _schedule(
        self, start, attempt, retry_after, client, metrics
    ) -> bool:
        """Sleep before the next attempt; False = budget exhausted."""

        if attempt + 1 >= self.max_attempts:
            if metrics is not None:
                metrics.inc(
                    "api_client_giveups_total",
                    exemplar=current_trace_id(), client=client,
                )
            return False
        delay = self.backoff(attempt)
        if retry_after is not None:
            delay = max(delay, min(retry_after, self.retry_after_cap))
        if (
            self.deadline is not None
            and (self._clock() - start) + delay > self.deadline
        ):
            if metrics is not None:
                metrics.inc(
                    "api_client_giveups_total",
                    exemplar=current_trace_id(), client=client,
                )
            return False
        if metrics is not None:
            metrics.inc("api_client_retries_total", client=client)
        self._sleep(delay)
        return True


def watch_recovery(
    fails: int,
    *,
    stop,
    policy: "RetryPolicy",
    metrics,
    kind: str,
    log=None,
    exc: Optional[BaseException] = None,
    gone: bool = False,
) -> int:
    """One ListAndWatch failure-recovery step, shared by the watch
    loops in kube.py and kubejobs.py so their behaviour can't drift:
    bump the right counter (``api_watch_gone_total`` for an expired
    window / 410 storm, ``api_watch_restarts_total`` for a broken
    stream), throttle-log broken streams (first failure, then every
    20th), and sleep a jittered backoff interruptible by ``stop``.
    Returns the new consecutive-failure count; callers reset it to 0
    after a stream completes cleanly.
    """

    fails += 1
    if gone:
        metrics.inc("api_watch_gone_total", kind=kind)
    else:
        metrics.inc("api_watch_restarts_total", kind=kind)
        if log is not None and (fails == 1 or fails % 20 == 0):
            log.warning(
                "%s watch broken (%s: %s); re-listing",
                kind,
                type(exc).__name__ if exc is not None else "?",
                exc,
            )
    if not stop.is_set():
        stop.wait(policy.backoff(min(fails, 6)))
    return fails


#: conservative defaults for control-loop clients (reconciler reads/
#: writes): a few quick tries, bounded well under a resync period
DEFAULT_POLICY_ARGS = dict(
    max_attempts=5, base_delay=0.05, max_delay=2.0, deadline=15.0
)


def default_policy(**overrides) -> RetryPolicy:
    args = dict(DEFAULT_POLICY_ARGS)
    args.update(overrides)
    return RetryPolicy(**args)


#: tight budget for serving-plane fabric pulls (ISSUE 17): admission
#: blocks on the pull and recompute is always the fallback, so give a
#: flaky peer a couple of quick chances and then get out of the way
FABRIC_PULL_POLICY_ARGS = dict(
    max_attempts=3, base_delay=0.02, max_delay=0.2, deadline=2.0
)


def fabric_pull_policy(**overrides) -> RetryPolicy:
    args = dict(FABRIC_PULL_POLICY_ARGS)
    args.update(overrides)
    return RetryPolicy(**args)
