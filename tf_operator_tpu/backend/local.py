"""Local-process backend: "pods" are real subprocesses on this host.

Parity: SURVEY.md §7 step 7 — the tier-3 e2e substrate.  Where the
reference's e2e suite runs against a real GKE cluster, this backend runs
each replica as a subprocess with the injected bootstrap env, so real
multi-process ``jax.distributed`` collectives over localhost prove the
whole chain (spec → reconcile → launch → bootstrap → status → cleanup)
without a cluster.

Address resolution: DNS names don't exist locally, so ``LocalResolver``
hands out deterministic ``127.0.0.1:<port>`` addresses per (job, replica,
port-kind) — the same resolver instance must be shared by the reconciler
config (env generation) and any observer.

Environment hygiene: this box pins the TPU platform through a
sitecustomize on PYTHONPATH; worker processes get PYTHONPATH reset to the
repo root so CPU workers are really CPU (tests) and platform selection is
the job spec's business (container env), not inherited ambience.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from tf_operator_tpu.api.types import ObjectMeta, PodPhase, ReplicaType, TPUJob
from tf_operator_tpu.backend.base import (
    AlreadyExistsError,
    ClusterBackend,
    NotFoundError,
    match_selector,
)
from tf_operator_tpu.backend.objects import (
    Pod,
    PodGroup,
    PodGroupPhase,
    Service,
    WatchEvent,
    WatchEventType,
    WatchHandler,
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _free_port() -> int:
    """An OS-assigned free port (bind :0, read, release).  A small
    close-to-use race remains, but unlike a fixed base-port convention
    it cannot systematically collide across concurrent backends —
    round-3's parallel test runs showed convention ports (42000+) are
    NOT parallel-safe (VERDICT r3 next #8)."""

    import socket

    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class LocalResolver:
    """Stable 127.0.0.1:<port> addresses for local replicas.

    Each (job, replica, port) key gets one port for the resolver's
    lifetime, so every pod's env advertises the same address before the
    process binds it.  Ports are OS-assigned by default; pass
    ``base_port`` for a deterministic range when debugging a single
    backend in isolation."""

    def __init__(self, base_port: Optional[int] = None):
        self._lock = threading.Lock()
        self._ports: Dict[tuple, int] = {}
        self._next = base_port

    def __call__(self, job: TPUJob, rtype: ReplicaType, index: int, port: int) -> str:
        key = (job.metadata.namespace, job.metadata.name, rtype.value, index, port)
        with self._lock:
            if key not in self._ports:
                if self._next is None:
                    self._ports[key] = _free_port()
                else:
                    self._ports[key] = self._next
                    self._next += 1
            return f"127.0.0.1:{self._ports[key]}"


class LocalProcessBackend(ClusterBackend):
    def __init__(self, log_dir: Optional[str] = None, poll_interval: float = 0.05):
        self.resolver = LocalResolver()
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="tpujob-local-")
        self._lock = threading.RLock()
        self._pods: Dict[str, Pod] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._services: Dict[str, Service] = {}
        self._groups: Dict[str, PodGroup] = {}
        self._handlers: List[WatchHandler] = []
        self._stop = threading.Event()
        self.poll_interval = poll_interval
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor.start()

    # -- watch --------------------------------------------------------------

    def subscribe(self, handler: WatchHandler) -> None:
        with self._lock:
            self._handlers.append(handler)

    def _emit(self, etype: WatchEventType, kind: str, obj) -> None:
        ev = WatchEvent(type=etype, kind=kind, obj=obj.clone())
        for h in list(self._handlers):
            h(ev)

    # -- pods ---------------------------------------------------------------

    def _build_env(self, pod: Pod) -> Dict[str, str]:
        env = dict(os.environ)
        # strip the box's TPU-pinning ambience; replicas opt back in via
        # their container env (JAX_PLATFORMS/PYTHONPATH) if they want TPU
        env["PYTHONPATH"] = _REPO_ROOT
        env.pop("JAX_PLATFORMS", None)
        main = pod.main_container()
        if main is not None:
            env.update(main.env)
        return env

    def _log_path(self, namespace: str, name: str) -> str:
        d = os.path.join(self.log_dir, namespace)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{name}.log")

    def create_pod(self, pod: Pod) -> None:
        with self._lock:
            if pod.key in self._pods:
                raise AlreadyExistsError(pod.key)
            main = pod.main_container()
            if main is None or not (main.command or main.args):
                raise ValueError(f"pod {pod.key}: no runnable command")
            pod.phase = PodPhase.PENDING
            self._pods[pod.key] = pod
            self._emit(WatchEventType.ADDED, "Pod", pod)
            cmd = list(main.command) + list(main.args)
            env = self._build_env(pod)

        # fork+exec happens outside the backend lock so spawns don't
        # serialize each other or stall the exit-monitor loop
        logf = open(self._log_path(pod.metadata.namespace, pod.metadata.name), "ab")
        try:
            proc = subprocess.Popen(
                cmd,
                env=env,
                stdout=logf,
                stderr=subprocess.STDOUT,
                # the repo root plays the container image's WORKDIR, so
                # manifest commands can use repo-relative paths
                cwd=main.working_dir or _REPO_ROOT,
                start_new_session=True,  # isolate signals per replica
            )
        except OSError as e:
            logf.write(f"spawn failed: {e}\n".encode())
            logf.close()
            with self._lock:
                pod.phase = PodPhase.FAILED
                pod.exit_code = 127
                self._emit(WatchEventType.MODIFIED, "Pod", pod)
            return
        logf.close()  # child holds its own fd now
        with self._lock:
            if pod.key not in self._pods:
                # deleted while we were spawning: kill the straggler
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                proc.wait()
                return
            self._procs[pod.key] = proc
            pod.phase = PodPhase.RUNNING
            self._emit(WatchEventType.MODIFIED, "Pod", pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            pod = self._pods.pop(key, None)
            if pod is None:
                raise NotFoundError(key)
            proc = self._procs.pop(key, None)
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                proc.wait(timeout=5.0)
        self._emit(WatchEventType.DELETED, "Pod", pod)

    def list_pods(self, namespace: str, selector=None) -> List[Pod]:
        with self._lock:
            return [
                p
                for p in self._pods.values()
                if p.metadata.namespace == namespace
                and match_selector(p.metadata.labels, selector)
            ]

    def snapshot(self):
        """Re-list for informer resync: cloned pods/services/groups."""

        with self._lock:
            return (
                [p.clone() for p in self._pods.values()],
                [s.clone() for s in self._services.values()],
                [g.clone() for g in self._groups.values()],
            )

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        return self._pods.get(f"{namespace}/{name}")

    def update_pod_owner(self, namespace: str, name: str, owner_uid: Optional[str]) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            pod = self._pods.get(key)
            if pod is None:
                raise NotFoundError(key)
            if pod.metadata.owner_uid == (owner_uid or ""):
                return
            pod.metadata.owner_uid = owner_uid or ""
            self._emit(WatchEventType.MODIFIED, "Pod", pod)

    def pod_log(self, namespace: str, name: str) -> str:
        path = self._log_path(namespace, name)
        try:
            with open(path, "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def _monitor_loop(self) -> None:
        """kubelet-equivalent: surface process exits as pod phases."""

        while not self._stop.is_set():
            with self._lock:
                items = list(self._procs.items())
            for key, proc in items:
                rc = proc.poll()
                if rc is None:
                    continue
                with self._lock:
                    pod = self._pods.get(key)
                    self._procs.pop(key, None)
                    if pod is None or pod.is_terminal():
                        continue
                    pod.exit_code = rc if rc >= 0 else 128 - rc  # signal death → 128+N
                    pod.phase = PodPhase.SUCCEEDED if rc == 0 else PodPhase.FAILED
                    self._emit(WatchEventType.MODIFIED, "Pod", pod)
            self._stop.wait(self.poll_interval)

    # -- services (record-only: localhost needs no DNS) ---------------------

    def create_service(self, svc: Service) -> None:
        with self._lock:
            if svc.key in self._services:
                raise AlreadyExistsError(svc.key)
            self._services[svc.key] = svc
            self._emit(WatchEventType.ADDED, "Service", svc)

    def delete_service(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            svc = self._services.pop(key, None)
            if svc is None:
                raise NotFoundError(key)
            self._emit(WatchEventType.DELETED, "Service", svc)

    def list_services(self, namespace: str, selector=None) -> List[Service]:
        with self._lock:
            return [
                s
                for s in self._services.values()
                if s.metadata.namespace == namespace
                and match_selector(s.metadata.labels, selector)
            ]

    # -- gang (single host: grants are immediate) ---------------------------

    def create_pod_group(self, group: PodGroup) -> None:
        with self._lock:
            if group.key in self._groups:
                raise AlreadyExistsError(group.key)
            group.phase = PodGroupPhase.GRANTED
            self._groups[group.key] = group
            self._emit(WatchEventType.ADDED, "PodGroup", group)

    def delete_pod_group(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            group = self._groups.pop(key, None)
            if group is None:
                raise NotFoundError(key)
            self._emit(WatchEventType.DELETED, "PodGroup", group)

    def update_pod_group(self, namespace: str, name: str, min_member: int, chip_request: int) -> None:
        with self._lock:
            group = self._groups.get(f"{namespace}/{name}")
            if group is None:
                raise NotFoundError(f"{namespace}/{name}")
            group.min_member = min_member
            group.chip_request = chip_request
            self._emit(WatchEventType.MODIFIED, "PodGroup", group)

    def get_pod_group(self, namespace: str, name: str) -> Optional[PodGroup]:
        return self._groups.get(f"{namespace}/{name}")

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for proc in procs:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        for proc in procs:  # reap: no zombies in the parent's table
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
        self._monitor.join(timeout=2.0)
