"""Cluster backends: where replicas actually run (SURVEY.md §7 step 2/7).

The reference talks to exactly one backend — the Kubernetes API server via
client-go.  Here the backend is pluggable behind a small interface
(``ClusterBackend``): an in-proc fake for tests, a local-subprocess backend
for real multi-process runs on one host, and (interface-only) a real
TPU-GKE backend.
"""

from tf_operator_tpu.backend.base import ClusterBackend  # noqa: F401
from tf_operator_tpu.backend.objects import (  # noqa: F401
    Pod,
    PodGroup,
    PodGroupPhase,
    Service,
    WatchEvent,
    WatchEventType,
)
