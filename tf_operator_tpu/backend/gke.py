"""TPUJob → real-Kubernetes manifest compiler (the GKE translation layer).

Parity: SURVEY.md §7 scopes the cluster substrate as "an in-proc fake
and a local-process backend now; a real GKE/TPU-VM backend is an
interface to be filled later".  A live GKE backend needs a cluster and
network this box doesn't have — but the *compilable* half doesn't
(VERDICT r3 missing #2): this module translates a TPUJob manifest into
exactly the Kubernetes objects the reference operator would create
(SURVEY.md §3.2's write boundary), so the declarative surface is
cluster-ready and golden-testable offline:

- one **Pod** per replica index, with the reference's label triple,
  TF_CONFIG / TPUJOB_* / TPU_WORKER_* / MEGASCALE_* env injected at the
  same point ``createNewPod`` would (SURVEY.md §2 "TF_CONFIG
  generation"), the ExitCode→Never pod-restart mapping, and — for
  TPU_SLICE replicas — the GKE TPU nodeSelectors
  (``cloud.google.com/gke-tpu-accelerator``/``-topology``) plus
  ``google.com/tpu`` chip limits per host;
- one **headless Service** per replica (stable DNS for the cluster
  spec — the ``<pod>.<ns>.svc`` names the dns_resolver emits);
- a **volcano PodGroup** (``scheduling.volcano.sh/v1beta1``) when gang
  scheduling is on, with ``minMember`` = total pod count and the
  ``scheduling.k8s.io/group-name`` annotation + ``schedulerName:
  volcano`` stamped on every pod (SURVEY.md §3.4).

What a LIVE backend still needs beyond this compiler (documented for
the interface): a kube-apiserver client implementing the 5
ClusterBackend verbs + watch (pods/services CRUD, exit-code and phase
readback), ownerReferences carrying the TPUJob CRD uid (unknowable
offline — the operator sets them at create time), and RBAC for
pods/services/events/podgroups.  See docs/ARCHITECTURE.md.

Usage:
    tpujob compile -f job.yaml            # multi-doc YAML on stdout
    from tf_operator_tpu.backend.gke import compile_job, to_yaml
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from tf_operator_tpu.api.types import (
    DEFAULT_PORT,
    DEFAULT_PORT_NAME,
    ReplicaType,
    RestartPolicy,
    TPUJob,
    replica_labels,
    replica_name,
)
from tf_operator_tpu.api.validation import CHIPS_PER_HOST, parse_tpu_topology
from tf_operator_tpu.bootstrap.cluster_spec import _replica_port, dns_resolver
from tf_operator_tpu.bootstrap.tpu_env import worker_env

#: volcano's pod→group binding annotation (the REAL scheduler's
#: convention; the in-proc backends use the internal
#: ANNOTATION_GANG_GROUP instead)
VOLCANO_GROUP_ANNOTATION = "scheduling.k8s.io/group-name"
VOLCANO_SCHEDULER = "volcano"

#: GKE accelerator nodeSelector value per TPU generation prefix
_GKE_ACCELERATOR = {
    "v4": "tpu-v4-podslice",
    "v5e": "tpu-v5-lite-podslice",
    "v5litepod": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
    "v6e": "tpu-v6e-slice",
}

#: chip count → GKE topology grid, PER GENERATION.  v5e/v6e slices are
#: 2-D ICI meshes; v4/v5p are 3-D torus grids ("2x2x1", "4x4x4", …) —
#: emitting a 2-D grid for a v4 slice produces a node selector no v4
#: nodepool matches (VERDICT r4 weak #3).
_GKE_TOPOLOGY_2D = {
    1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4",
    32: "4x8", 64: "8x8", 128: "8x16", 256: "16x16",
}
_GKE_TOPOLOGY_3D = {
    4: "2x2x1", 8: "2x2x2", 16: "2x2x4", 32: "2x4x4",
    64: "4x4x4", 128: "4x4x8", 256: "4x8x8", 512: "8x8x8",
    1024: "8x8x16", 2048: "8x16x16", 4096: "16x16x16",
}
_GKE_TOPOLOGY = {
    "v4": _GKE_TOPOLOGY_3D,
    "v5p": _GKE_TOPOLOGY_3D,
    "v5e": _GKE_TOPOLOGY_2D,
    "v5litepod": _GKE_TOPOLOGY_2D,
    "v6e": _GKE_TOPOLOGY_2D,
}


def _pod_restart_policy(rp: Optional[RestartPolicy]) -> str:
    """The reference's pod-level mapping: the operator owns retry for
    ExitCode (pod must NOT self-restart → Never); Always becomes
    OnFailure because bare pods forbid Always-after-success semantics
    the operator implements itself (SURVEY.md §3.2 "restart-policy
    mapping")."""

    if rp in (RestartPolicy.EXIT_CODE, RestartPolicy.NEVER, None):
        return "Never"
    return "OnFailure"


def _tpu_node_selector(topology: str) -> Dict[str, str]:
    gen = topology.split("-", 1)[0].lower()
    accel = _GKE_ACCELERATOR.get(gen)
    if accel is None:
        raise ValueError(
            f"no GKE accelerator mapping for TPU generation {gen!r} "
            f"(topology {topology!r}); known: {sorted(_GKE_ACCELERATOR)}"
        )
    chips = parse_tpu_topology(topology)
    grid = _GKE_TOPOLOGY[gen].get(chips)
    if grid is None:
        raise ValueError(
            f"no GKE topology grid for {chips} chips on {gen} "
            f"(topology {topology!r})"
        )
    return {
        "cloud.google.com/gke-tpu-accelerator": accel,
        "cloud.google.com/gke-tpu-topology": grid,
    }


def _container_to_k8s(c, env: Dict[str, str], tpu_chips: int) -> Dict[str, Any]:
    merged = dict(env)
    merged.update(c.env)  # user-specified env wins, like the reconciler
    out: Dict[str, Any] = {
        "name": c.name,
        "image": c.image or "REPLACE_WITH_TRAINING_IMAGE",
        "env": [
            {"name": k, "value": v} for k, v in sorted(merged.items())
        ],
    }
    if c.command:
        out["command"] = list(c.command)
    if c.args:
        out["args"] = list(c.args)
    ports = [
        {"name": p.name, "containerPort": p.container_port} for p in c.ports
    ]
    if not ports:
        # the defaulted port the cluster spec advertises must be open
        ports = [{"name": DEFAULT_PORT_NAME, "containerPort": DEFAULT_PORT}]
    out["ports"] = ports
    resources = dict(c.resources) if c.resources else {}
    if tpu_chips:
        limits = dict(resources.get("limits", {}))
        limits["google.com/tpu"] = str(tpu_chips)
        resources["limits"] = limits
    if resources:
        out["resources"] = resources
    return out


def _compile_pod(job: TPUJob, rtype: ReplicaType, index: int) -> Dict[str, Any]:
    spec = job.spec.replica_specs[rtype]
    template = spec.template
    name = replica_name(job.metadata.name, rtype, index)
    env = worker_env(job, rtype, index, dns_resolver)

    tpu_chips = 0
    node_selector = dict(template.node_selector)
    if rtype is ReplicaType.TPU_SLICE and spec.tpu_topology:
        node_selector.update(_tpu_node_selector(spec.tpu_topology))
        # per-host chip share of the atomic slice (one pod per host VM)
        chips = parse_tpu_topology(spec.tpu_topology)
        hosts = spec.slice_host_count()
        tpu_chips = min(CHIPS_PER_HOST, max(1, -(-chips // hosts)))

    labels = {**template.labels, **replica_labels(job.metadata.name, rtype, index)}
    annotations = dict(template.annotations)
    scheduler = template.scheduler_name
    if job.spec.enable_gang_scheduling:
        annotations[VOLCANO_GROUP_ANNOTATION] = job.metadata.name
        scheduler = scheduler or VOLCANO_SCHEDULER

    pod_spec: Dict[str, Any] = {
        "restartPolicy": _pod_restart_policy(spec.restart_policy),
        "containers": [
            _container_to_k8s(c, env, tpu_chips) for c in template.containers
        ],
    }
    if node_selector:
        pod_spec["nodeSelector"] = node_selector
    if scheduler:
        pod_spec["schedulerName"] = scheduler

    meta: Dict[str, Any] = {
        "name": name,
        "namespace": job.metadata.namespace,
        "labels": labels,
    }
    if annotations:
        meta["annotations"] = annotations
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": pod_spec}


def _compile_service(job: TPUJob, rtype: ReplicaType, index: int) -> Dict[str, Any]:
    name = replica_name(job.metadata.name, rtype, index)
    labels = replica_labels(job.metadata.name, rtype, index)
    port = _replica_port(job, rtype)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": name,
            "namespace": job.metadata.namespace,
            "labels": dict(labels),
        },
        "spec": {
            "clusterIP": "None",  # headless: DNS resolves to the pod IP
            "selector": dict(labels),
            "ports": [{"name": DEFAULT_PORT_NAME, "port": port}],
        },
    }


def _compile_podgroup(job: TPUJob) -> Dict[str, Any]:
    sp = job.spec.run_policy.scheduling_policy
    min_member = (
        sp.min_member
        if sp is not None and sp.min_member is not None
        else job.spec.total_pods()
    )
    out: Dict[str, Any] = {
        "apiVersion": "scheduling.volcano.sh/v1beta1",
        "kind": "PodGroup",
        "metadata": {
            "name": job.metadata.name,
            "namespace": job.metadata.namespace,
        },
        "spec": {"minMember": min_member},
    }
    if sp is not None and sp.queue:
        out["spec"]["queue"] = sp.queue
    if sp is not None and sp.priority_class:
        out["spec"]["priorityClassName"] = sp.priority_class
    return out


def compile_job(job: TPUJob) -> List[Dict[str, Any]]:
    """All Kubernetes objects for one TPUJob, in apply order: PodGroup
    (gang) first — pods referencing a group must find it — then per
    replica the headless Service before its Pod (the cluster-spec DNS
    names must resolve by the time training code reads TF_CONFIG)."""

    objs: List[Dict[str, Any]] = []
    if job.spec.enable_gang_scheduling:
        objs.append(_compile_podgroup(job))
    for rtype in job.spec.ordered_types():
        for index in range(job.spec.pod_count(rtype)):
            objs.append(_compile_service(job, rtype, index))
            objs.append(_compile_pod(job, rtype, index))
    return objs


def to_yaml(objs: List[Dict[str, Any]]) -> str:
    import yaml

    return yaml.safe_dump_all(objs, sort_keys=False, default_flow_style=False)


def compile_manifest(manifest: Dict[str, Any]) -> str:
    """dict manifest → defaults → admission validation → k8s YAML."""

    from tf_operator_tpu.api.defaults import set_defaults
    from tf_operator_tpu.api.serde import job_from_dict
    from tf_operator_tpu.api.validation import validate

    job = set_defaults(job_from_dict(manifest))
    validate(job)
    return to_yaml(compile_job(job))
