"""Runtime cluster objects: Pod, Service, PodGroup, watch events.

Parity: the k8s core objects the reference manipulates (SURVEY.md §3.2) —
reduced to the fields the reconciler actually uses.  ``PodGroup`` is the
gang-scheduling unit (reference: volcano/kube-batch PodGroup CRs,
SURVEY.md §3.4), generalised here to an atomic chip grant so a TPU slice
allocation is all-or-nothing.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

from tf_operator_tpu.api.types import (
    LABEL_JOB_NAME,
    LABEL_REPLICA_INDEX,
    LABEL_REPLICA_TYPE,
    Container,
    ObjectMeta,
    PodPhase,
    ReplicaType,
)


class WatchEventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: WatchEventType
    kind: str  # "Pod" | "Service" | "PodGroup" | "TPUJob"
    obj: Any


WatchHandler = Callable[[WatchEvent], None]


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    containers: List[Container] = field(default_factory=list)
    scheduler_name: str = ""
    node_selector: dict = field(default_factory=dict)
    phase: PodPhase = PodPhase.PENDING
    #: main-container exit code once terminal (None while running)
    exit_code: Optional[int] = None
    #: number of kubelet-level container restarts (RestartPolicy ALWAYS /
    #: ON_FAILURE restart in place rather than via operator delete+recreate)
    restart_count: int = 0
    #: chips this pod occupies (gang/capacity accounting; 0 = CPU-only)
    chip_request: int = 0

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @property
    def job_name(self) -> str:
        return self.metadata.labels.get(LABEL_JOB_NAME, "")

    @property
    def replica_type(self) -> Optional[ReplicaType]:
        t = self.metadata.labels.get(LABEL_REPLICA_TYPE)
        return ReplicaType.from_str(t) if t else None

    @property
    def replica_index(self) -> Optional[int]:
        i = self.metadata.labels.get(LABEL_REPLICA_INDEX)
        return int(i) if i is not None and i.isdigit() else None

    def is_terminal(self) -> bool:
        return self.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)

    def main_container(self, name: str = "tensorflow") -> Optional[Container]:
        for c in self.containers:
            if c.name == name:
                return c
        return None

    def clone(self) -> "Pod":
        return Pod(
            metadata=self.metadata.clone(),
            containers=[c.clone() for c in self.containers],
            scheduler_name=self.scheduler_name,
            node_selector=dict(self.node_selector),
            phase=self.phase,
            exit_code=self.exit_code,
            restart_count=self.restart_count,
            chip_request=self.chip_request,
        )


@dataclass
class Service:
    """Headless-service equivalent: a stable DNS name for one replica."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: dict = field(default_factory=dict)
    port: int = 0

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self) -> "Service":
        return Service(
            metadata=self.metadata.clone(),
            selector=dict(self.selector),
            port=self.port,
        )


class PodGroupPhase(str, enum.Enum):
    PENDING = "Pending"  # capacity not yet available — no member may run
    GRANTED = "Granted"  # all-or-nothing admission succeeded
    RELEASED = "Released"


@dataclass
class PodGroup:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    min_member: int = 0
    #: total chips the gang needs, all-or-nothing (0 = member-count only)
    chip_request: int = 0
    phase: PodGroupPhase = PodGroupPhase.PENDING

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self) -> "PodGroup":
        return PodGroup(
            metadata=self.metadata.clone(),
            min_member=self.min_member,
            chip_request=self.chip_request,
            phase=self.phase,
        )
