"""MiniApiServer: an in-repo kube-apiserver simulator (VERDICT r4
next #4's server half).

Speaks the protocol subset ``backend/kube.py``'s client needs — which
is the subset the reference's operator needs from a real apiserver
(SURVEY.md §1 L1, §3.2's write boundary):

- CRUD on pods/services (``/api/v1``) and volcano podgroups
  (``/apis/scheduling.volcano.sh/v1beta1``), objects stored as real
  Kubernetes JSON; 409 on create conflicts, 404 on missing objects;
- ``labelSelector`` list filtering;
- a global monotonically increasing **resourceVersion**, stamped on
  every write and returned on lists;
- **chunked watch streams** (``?watch=true&resourceVersion=N``): one
  JSON document per line, replayed from a bounded event log (requests
  below the log window get the real apiserver's **410 Gone**, forcing
  the client's re-list — the exact client-go recovery path), then live;
- ``PATCH`` merge semantics for ownerReferences (adoption/orphaning)
  and podgroup resize;
- ``GET .../pods/{name}/log`` serving the pod's stdout file.

Beyond the protocol, the sim embeds what a real cluster provides
around the apiserver so the tier-3 e2e suite can run unchanged:

- **scheduler sim**: volcano-style gang admission — a podgroup is
  Granted only if its chip request fits ``total_chips`` (None =
  unlimited); pods carrying the gang annotation stay Pending until
  their group grants (same semantics as ``backend/fake.py``);
- **kubelet sim**: admissible Pending pods' commands spawn as real
  local subprocesses (the ``backend/local.py`` contract: repo root as
  WORKDIR, PYTHONPATH reset, process-group isolation); exits surface
  as pod phase + containerStatuses exit codes through the store, with
  watch events.

Usage:
    sim = MiniApiServer(total_chips=None); sim.start()
    backend = KubeBackend(sim.url)           # backend/kube.py
    ... run the operator against `backend` ...
    backend.close(); sim.stop()
"""

from __future__ import annotations

import json
import os
import random
import re
import signal
import socket
import struct
import subprocess
import threading
import time
import urllib.parse
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from queue import Empty, Queue
from typing import Any, Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import ANNOTATION_GANG_GROUP
from tf_operator_tpu.backend.base import match_selector
from tf_operator_tpu.backend.kube import parse_selector
from tf_operator_tpu.utils.logging import logger_for_job
from tf_operator_tpu.utils.trace import TRACE_HEADER, extract_headers

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: events kept for watch replay; older resourceVersions get 410 Gone
EVENT_LOG_WINDOW = 4096

#: stored v1 Event objects are GC'd beyond this count (a real
#: apiserver expires events after ~1h TTL; without a cap a long-lived
#: sim would grow without bound)
MAX_EVENT_OBJECTS = 4096

_PLURALS = {
    "pods": "Pod",
    "services": "Service",
    "podgroups": "PodGroup",
    "leases": "Lease",
    "tpujobs": "TPUJob",
    "events": "Event",
}


class FaultRule:
    """One fault-injection rule: regex over the raw request line
    (path INCLUDING query, so ``watch=true`` streams are targetable),
    a verb set, an action, a probability, and an optional shot count.

    Modes:
      - ``error``:   reply ``status`` (with ``Retry-After: retry_after``
                     when given) INSTEAD of executing the verb — so a
                     client's blind retry of a non-idempotent verb is
                     safe against this server;
      - ``reset``:   hard-close the accepted socket (SO_LINGER 0 → RST,
                     the mid-handshake connection-reset case);
      - ``latency``: sleep ``delay`` seconds, then serve normally.
    """

    _ids = 0
    _ids_lock = threading.Lock()

    def __init__(
        self,
        path: str = ".*",
        methods: Optional[List[str]] = None,
        mode: str = "error",
        status: int = 503,
        retry_after: Optional[float] = None,
        delay: float = 0.0,
        probability: float = 1.0,
        times: Optional[int] = None,
    ):
        if mode not in ("error", "reset", "latency"):
            raise ValueError(f"unknown fault mode {mode!r}")
        with FaultRule._ids_lock:
            FaultRule._ids += 1
            self.id = FaultRule._ids
        self.path = path
        self.path_re = re.compile(path)
        self.methods = (
            None if methods is None else {m.upper() for m in methods}
        )
        self.mode = mode
        self.status = int(status)
        self.retry_after = None if retry_after is None else float(retry_after)
        self.delay = float(delay)
        self.probability = float(probability)
        self.remaining = None if times is None else int(times)
        self.injected = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "path": self.path,
            "methods": sorted(self.methods) if self.methods else None,
            "mode": self.mode,
            "status": self.status,
            "retryAfter": self.retry_after,
            "delay": self.delay,
            "probability": self.probability,
            "remaining": self.remaining,
            "injected": self.injected,
        }


class FaultInjector:
    """Per-route/per-verb fault schedule for MiniApiServer.

    Deterministic under a seed (chaos tests replay exactly); drivable
    in-process (``sim.faults.add(...)``) or over HTTP via the admin
    endpoint ``/_faults`` (GET = rules+counters, POST = add rule JSON,
    DELETE = clear) — the admin route itself is never injected.
    """

    def __init__(self, seed: Optional[int] = None):
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._rules: List[FaultRule] = []

    def add(self, **kw) -> FaultRule:
        rule = FaultRule(**kw)
        with self._lock:
            self._rules.append(rule)
        return rule

    def remove(self, rule_id: int) -> bool:
        with self._lock:
            before = len(self._rules)
            self._rules = [r for r in self._rules if r.id != rule_id]
            return len(self._rules) < before

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.to_dict() for r in self._rules]

    def total_injected(self) -> int:
        with self._lock:
            return sum(r.injected for r in self._rules)

    def decide(self, method: str, raw_path: str) -> Optional[tuple]:
        """First matching rule that fires wins; None = serve normally."""

        with self._lock:
            for r in self._rules:
                if r.methods is not None and method.upper() not in r.methods:
                    continue
                if not r.path_re.search(raw_path):
                    continue
                if r.remaining is not None and r.remaining <= 0:
                    continue
                if r.probability < 1.0 and self._rng.random() >= r.probability:
                    continue
                if r.remaining is not None:
                    r.remaining -= 1
                r.injected += 1
                if r.mode == "error":
                    return ("error", r.status, r.retry_after)
                if r.mode == "reset":
                    return ("reset",)
                return ("latency", r.delay)
        return None


def _field_get(obj: Dict[str, Any], dotted: str):
    cur: Any = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _merge_patch(obj: Dict[str, Any], patch: Dict[str, Any]) -> None:
    """Strategic-merge-lite, in place: dict values merge one level
    deep, everything else replaces (covers ownerReferences, status and
    podgroup spec resize).  The ONE merge used both to build the
    admission pre-check object and to apply the patch — shared so the
    validated object can never drift from the stored one."""

    for section, val in patch.items():
        if isinstance(val, dict) and isinstance(obj.get(section), dict):
            obj[section].update(val)
        else:
            obj[section] = val


def _labels(obj: Dict[str, Any]) -> Dict[str, str]:
    return obj.get("metadata", {}).get("labels", {}) or {}


class _Store:
    """The apiserver state: objects + resourceVersion + event log +
    watch fan-out.  One lock; every mutation stamps a fresh global
    resourceVersion, appends to the bounded event log, and wakes
    watchers."""

    def __init__(self):
        self.lock = threading.RLock()
        self.rv = 0
        #: (kind, ns, name) -> k8s JSON object
        self.objects: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        #: bounded replay window: (rv, kind, event-type, object-snapshot)
        self.log: deque = deque(maxlen=EVENT_LOG_WINDOW)
        self.watchers: List[Queue] = []
        self._uid = 0

    def next_uid(self, prefix: str) -> str:
        self._uid += 1
        return f"{prefix}-uid-{self._uid}"

    def bump(self, kind: str, etype: str, obj: Dict[str, Any]) -> None:
        """Stamp a new resourceVersion on obj and fan out the event.
        Caller holds the lock."""

        self.rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
        snapshot = json.loads(json.dumps(obj))  # watchers never alias
        self.log.append((self.rv, kind, etype, snapshot))
        for q in list(self.watchers):
            q.put((self.rv, kind, etype, snapshot))

    def oldest_rv(self) -> int:
        return self.log[0][0] if self.log else self.rv + 1


class MiniApiServer:
    def __init__(
        self,
        total_chips: Optional[int] = None,
        log_dir: Optional[str] = None,
        kubelet_interval: float = 0.05,
        fault_seed: Optional[int] = None,
        tracer=None,
        admission: bool = True,
    ):
        import tempfile

        from tf_operator_tpu.utils.trace import default_tracer

        self.store = _Store()
        #: server-side admission (VERDICT r5 next #9): POSTed TPUJob
        #: objects are parsed+validated and rejected 422 Invalid, the
        #: role a real cluster's admission webhook plays.  admission=
        #: False models a webhook-less apiserver (garbage CAN land in
        #: the store); the operator's informer-ingestion validation is
        #: the backstop there — invalid objects get a Failed/Invalid
        #: condition and are never reconciled.
        self.admission = bool(admission)
        #: per-route/per-verb fault schedule (chaos tests + /_faults)
        self.faults = FaultInjector(seed=fault_seed)
        #: server-side request spans: adopts an incoming x-trace-id
        #: (minting one otherwise) and echoes it on every response —
        #: in-process deployments share the operator's default tracer,
        #: so /traces/<id> shows client AND server halves of each call
        self.tracer = tracer if tracer is not None else default_tracer
        self.total_chips = total_chips
        #: optional controller/scheduler.Scheduler: capacity-shrink
        #: revocation routes victim choice through it (instead of
        #: blind LIFO) and GET /scheduler serves its snapshot; None
        #: falls back to the process-global default_scheduler for the
        #: route and to LIFO for revocation order
        self.scheduler = None
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="tpujob-kubesim-")
        self.kubelet_interval = kubelet_interval
        self._procs: Dict[Tuple[str, str, str], subprocess.Popen] = {}
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    @property
    def url(self) -> str:
        assert self._httpd is not None, "call start() first"
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "MiniApiServer":
        sim = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                sim._handle(self, "GET")

            def do_POST(self):
                sim._handle(self, "POST")

            def do_DELETE(self):
                sim._handle(self, "DELETE")

            def do_PATCH(self):
                sim._handle(self, "PATCH")

            def do_PUT(self):
                sim._handle(self, "PUT")

        self._handler_cls = Handler
        self._serve(("127.0.0.1", 0))
        k = threading.Thread(target=self._kubelet_loop, daemon=True)
        k.start()
        self._threads.append(k)
        return self

    def _serve(self, addr) -> None:
        """Bind + serve (shared by start and resume)."""

        self._httpd = ThreadingHTTPServer(addr, self._handler_cls)
        self._httpd.daemon_threads = True
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)

    def pause(self) -> None:
        """Simulate an apiserver NETWORK outage: close the HTTP
        listener AND sever established connections (the long-lived
        chunked watch streams break mid-flight, exactly like a real
        network partition) while the store, scheduler and kubelet
        sims keep running (real kubelets don't die when the apiserver
        does).  ``resume()`` rebinds the same port; clients recover
        through their re-list path."""

        assert self._httpd is not None, "not started"
        self._paused_addr = self._httpd.server_address[:2]
        self._paused.set()
        with self.store.lock:
            for q in list(self.store.watchers):
                q.put(None)  # wake blocked streams so they terminate
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None

    def resume(self) -> None:
        """End a pause(): rebind the remembered address and serve."""

        assert self._httpd is None and hasattr(self, "_paused_addr")
        self._paused.clear()
        self._serve(self._paused_addr)

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        with self.store.lock:
            procs = list(self._procs.values())
            self._procs.clear()
            for q in self.store.watchers:
                q.put(None)  # unblock stream threads
        for proc in procs:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        for proc in procs:
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass

    # -- HTTP dispatch ------------------------------------------------------

    @staticmethod
    def _reply(
        h, status: int, obj=None, text: Optional[str] = None,
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = (
            text.encode()
            if text is not None
            else json.dumps(obj if obj is not None else {}).encode()
        )
        span = getattr(h, "_trace_span", None)
        if span is not None:
            # commit the span to the store BEFORE any response bytes
            # reach the client: a caller may query the tracer the
            # instant it has our reply, and end() is idempotent so the
            # _handle finally-net stays a no-op (same contract as the
            # watch-accept path)
            span.set_attribute("status", status)
            span.end()
        h.send_response(status)
        h.send_header(
            "Content-Type",
            "text/plain" if text is not None else "application/json",
        )
        h.send_header("Content-Length", str(len(body)))
        if span is not None:
            # the propagation contract: EVERY response names its trace
            h.send_header(TRACE_HEADER, span.trace_id)
        for k, v in (headers or {}).items():
            h.send_header(k, v)
        h.end_headers()
        try:
            h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    @staticmethod
    def _status(code: int, reason: str, message: str) -> Dict[str, Any]:
        return {
            "apiVersion": "v1",
            "kind": "Status",
            "code": code,
            "reason": reason,
            "message": message,
        }

    def _parse_path(self, path: str):
        """(kind, namespace|None, name|None, subresource|None) or None."""

        parts = [p for p in path.split("/") if p]
        # /api/v1/..., /apis/scheduling.volcano.sh/v1beta1/...,
        # /apis/coordination.k8s.io/v1/... (Leases — leader election),
        # or /apis/tpujob.dist/v1/... (the TPUJob custom resource —
        # the reference's TFJob CRD tier)
        if parts[:2] == ["api", "v1"]:
            rest = parts[2:]
        elif parts[:3] == ["apis", "scheduling.volcano.sh", "v1beta1"]:
            rest = parts[3:]
        elif parts[:3] == ["apis", "coordination.k8s.io", "v1"]:
            rest = parts[3:]
        elif parts[:3] == ["apis", "tpujob.dist", "v1"]:
            rest = parts[3:]
        else:
            return None
        ns = None
        if rest[:1] == ["namespaces"] and len(rest) >= 3:
            ns = rest[1]
            rest = rest[2:]
        if not rest or rest[0] not in _PLURALS:
            return None
        kind = _PLURALS[rest[0]]
        name = rest[1] if len(rest) > 1 else None
        sub = rest[2] if len(rest) > 2 else None
        return kind, ns, name, sub

    def _handle(self, h, method: str) -> None:
        # server span: adopt the caller's trace (x-trace-id header) or
        # mint one; echoed on every reply by _reply, tagged with any
        # injected fault so the waterfall names the failure source
        tid, parent = extract_headers(h.headers)
        span = self.tracer.start_span(
            f"apiserver {method} {h.path.split('?')[0]}",
            kind="server",
            trace_id=tid,
            parent_id=parent,
            attributes={"method": method},
        )
        h._trace_span = span
        try:
            return self._handle_traced(h, method, span)
        finally:
            span.end()

    def _handle_traced(self, h, method: str, span) -> None:
        u = urllib.parse.urlparse(h.path)
        q = urllib.parse.parse_qs(u.query)
        if u.path == "/_faults":
            return self._admin_faults(h, method)
        if u.path == "/debug/flightrecorder" and method == "GET":
            # postmortem rings (utils/flight.py) — an admin/debug
            # route like /_faults, never itself fault-injected
            from tf_operator_tpu.utils.flight import default_recorder

            return self._reply(h, 200, text=default_recorder.dump_text())
        if u.path == "/alerts" and method == "GET":
            # the process-global alert engine's state (utils/alerts.py)
            # — admin/debug surface like /_faults, never injected: the
            # route that tells you things are on fire must not itself
            # be set on fire
            from tf_operator_tpu.utils.alerts import default_engine

            return self._reply(h, 200, default_engine.snapshot())
        if u.path == "/autoscaler" and method == "GET":
            # the process-global autoscaler's decisions + policy state
            # (controller/autoscaler.py) — debug surface, never injected
            from tf_operator_tpu.controller.autoscaler import (
                default_autoscaler,
            )

            return self._reply(h, 200, default_autoscaler.snapshot())
        if u.path == "/scheduler" and method == "GET":
            # the fleet scheduler's queue/quota/decision log
            # (controller/scheduler.py) — debug surface, never
            # injected: the route that explains who took your chips
            # must survive the chaos that took them
            sched = self.scheduler
            if sched is None:
                from tf_operator_tpu.controller.scheduler import (
                    default_scheduler,
                )

                sched = default_scheduler
            return self._reply(h, 200, sched.snapshot())
        if u.path == "/_capacity":
            return self._admin_capacity(h, method)
        act = self.faults.decide(method, h.path)
        if act is not None:
            span.set_attribute("fault", act[0])
            if act[0] == "error":
                _, code, retry_after = act
                span.set_error(f"injected {code}")
                extra = (
                    {"Retry-After": f"{retry_after:g}"}
                    if retry_after is not None
                    else None
                )
                return self._reply(
                    h,
                    code,
                    self._status(code, "FaultInjected", "injected fault"),
                    headers=extra,
                )
            if act[0] == "reset":
                span.set_error("injected connection reset")
                span.end()  # commit before the client sees ECONNRESET
                # RST, not FIN: SO_LINGER 0 makes close() abort the
                # connection, so the client sees ECONNRESET mid-request
                try:
                    h.connection.setsockopt(
                        socket.SOL_SOCKET,
                        socket.SO_LINGER,
                        struct.pack("ii", 1, 0),
                    )
                except OSError:
                    pass
                h.close_connection = True
                try:
                    h.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return None
            time.sleep(act[1])  # latency: delay, then serve normally
        parsed = self._parse_path(u.path)
        if parsed is None:
            return self._reply(
                h, 404, self._status(404, "NotFound", f"no route {u.path}")
            )
        kind, ns, name, sub = parsed
        try:
            if method == "GET" and name is None and q.get("watch", ["false"])[0] in ("true", "1"):
                rv = int(q.get("resourceVersion", ["0"])[0] or "0")
                return self._watch(h, kind, rv)
            if method == "GET" and name is None:
                sel = parse_selector(q.get("labelSelector", [""])[0])
                fsel = parse_selector(q.get("fieldSelector", [""])[0])
                return self._list(h, kind, ns, sel, fsel)
            if method == "GET" and sub == "log":
                return self._pod_log(h, ns, name)
            if method == "GET":
                return self._get(h, kind, ns, name)
            if method == "POST" and name is None:
                length = int(h.headers.get("Content-Length", "0"))
                obj = json.loads(h.rfile.read(length) or b"{}")
                return self._create(h, kind, ns, obj)
            if method == "DELETE" and name is not None:
                return self._delete_obj(h, kind, ns, name)
            if method == "PATCH" and name is not None:
                length = int(h.headers.get("Content-Length", "0"))
                patch = json.loads(h.rfile.read(length) or b"{}")
                return self._patch(h, kind, ns, name, patch)
            if method == "PUT" and name is not None:
                length = int(h.headers.get("Content-Length", "0"))
                obj = json.loads(h.rfile.read(length) or b"{}")
                return self._replace(h, kind, ns, name, obj)
        except (ValueError, KeyError) as e:
            return self._reply(
                h, 400, self._status(400, "BadRequest", repr(e))
            )
        self._reply(
            h, 405, self._status(405, "MethodNotAllowed", method)
        )

    def _admin_faults(self, h, method: str) -> None:
        """Chaos admin endpoint (never itself injected): GET lists the
        rules with their injected-counters, POST adds one rule (the
        FaultRule kwargs in JSON, camelCase retryAfter accepted),
        DELETE clears the schedule."""

        if method == "GET":
            return self._reply(h, 200, {"rules": self.faults.snapshot()})
        if method == "POST":
            length = int(h.headers.get("Content-Length", "0"))
            try:
                spec = json.loads(h.rfile.read(length) or b"{}")
                if not isinstance(spec, dict):
                    raise ValueError("rule must be a JSON object")
                if "retryAfter" in spec:
                    spec["retry_after"] = spec.pop("retryAfter")
                rule = self.faults.add(**spec)
            except (ValueError, TypeError, re.error) as e:
                return self._reply(
                    h, 400, self._status(400, "BadRequest", repr(e))
                )
            return self._reply(h, 201, rule.to_dict())
        if method == "DELETE":
            self.faults.clear()
            return self._reply(h, 200, self._status(200, "Success", "cleared"))
        return self._reply(
            h, 405, self._status(405, "MethodNotAllowed", method)
        )

    def _admin_capacity(self, h, method: str) -> None:
        """Capacity admin endpoint (never itself injected — the
        /_faults contract): GET reports total/granted chips, POST
        ``{"totalChips": N}`` (null = unlimited) resizes the simulated
        accelerator pool.  Shrinking PREEMPTS: most-recently granted
        gangs are revoked until the rest fit, their running pods are
        killed (they reap as Failed) — the capacity-loss scenario the
        elastic autoscaler's training policies exist to survive.
        Growing regrants pending gangs — "capacity returns"."""

        if method == "GET":
            with self.store.lock:
                granted = sum(
                    self._group_chips(o)
                    for key, o in self.store.objects.items()
                    if key[0] == "PodGroup"
                    and o.get("status", {}).get("phase") == "Granted"
                )
            return self._reply(
                h, 200, {"totalChips": self.total_chips, "grantedChips": granted}
            )
        if method == "POST":
            length = int(h.headers.get("Content-Length", "0"))
            try:
                spec = json.loads(h.rfile.read(length) or b"{}")
                total = spec.get("totalChips")
                if total is not None:
                    total = int(total)
                    if total < 0:
                        raise ValueError("totalChips must be >= 0 or null")
            except (ValueError, TypeError) as e:
                return self._reply(
                    h, 400, self._status(400, "BadRequest", repr(e))
                )
            revoked = self.set_total_chips(total)
            return self._reply(
                h, 200, {"totalChips": self.total_chips, "revoked": revoked}
            )
        return self._reply(
            h, 405, self._status(405, "MethodNotAllowed", method)
        )

    def set_total_chips(self, total_chips: Optional[int]) -> List[str]:
        """Resize the simulated chip pool (None = unlimited); returns
        the names of gang groups revoked by a shrink.  In-process twin
        of the /_capacity admin route."""

        to_kill: List[Tuple[str, str, str]] = []
        revoked: List[str] = []
        with self.store.lock:
            self.total_chips = total_chips
            if total_chips is not None:
                # revoke gangs until the rest fit — victim order comes
                # from the attached fleet scheduler's policy (lowest
                # priority class first, controller/scheduler
                # .choose_victims) when one is attached, else LIFO
                # (most-recently granted first — deterministic, and the
                # oldest work keeps its grant, the volcano-ish
                # convention)
                granted = [
                    (key, o)
                    for key, o in self.store.objects.items()
                    if key[0] == "PodGroup"
                    and o.get("status", {}).get("phase") == "Granted"
                ]
                in_use = sum(self._group_chips(o) for _, o in granted)
                victims = list(reversed(granted))
                if self.scheduler is not None:
                    by_key = {f"{k[1]}/{k[2]}": (k, o) for k, o in granted}
                    try:
                        order = self.scheduler.choose_victims(
                            [
                                {
                                    "key": f"{k[1]}/{k[2]}",
                                    "chips": self._group_chips(o),
                                }
                                for k, o in granted
                            ]
                        )
                        victims = [by_key[j] for j in order if j in by_key]
                    except Exception as e:  # noqa: BLE001 - fall back to LIFO
                        logger_for_job("-", "kubesim").warning(
                            "victim chooser failed, using LIFO: %s", e
                        )
                for key, o in victims:
                    if in_use <= total_chips:
                        break
                    o["status"]["phase"] = "Pending"
                    in_use -= self._group_chips(o)
                    revoked.append(key[2])
                    self.store.bump("PodGroup", "MODIFIED", o)
                    if self.scheduler is not None:
                        # synchronous park (see backend/fake.py): the
                        # scheduler learns the grant is gone before any
                        # sync observes the SIGTERM'd pods
                        try:
                            self.scheduler.note_revoked(
                                f"{key[1]}/{key[2]}", by="capacity-shrink"
                            )
                        except Exception as e:  # noqa: BLE001 - advisory
                            logger_for_job("-", "kubesim").warning(
                                "note_revoked(%s/%s) failed: %s",
                                key[1], key[2], e,
                            )
                    # attributed audit trail (no more anonymous exit
                    # 137): a v1 Event names the revoked gang and the
                    # capacity change, exactly what kubectl would show
                    now = time.time()
                    ev_ns, ev_name = key[1], key[2]
                    ev = {
                        "apiVersion": "v1",
                        "kind": "Event",
                        "metadata": {
                            "name": (
                                f"{ev_name}.preempted."
                                f"{int(now * 1e6):016x}"
                            ),
                            "namespace": ev_ns,
                        },
                        "type": "Warning",
                        "reason": "Preempted",
                        "message": (
                            f"gang {ev_name} revoked: capacity shrunk "
                            f"to {total_chips} chips (gang held "
                            f"{self._group_chips(o)})"
                        ),
                        "involvedObject": {
                            "apiVersion": "tpujob.dist/v1",
                            "kind": "TPUJob",
                            "name": ev_name,
                            "namespace": ev_ns,
                        },
                    }
                    self.store.objects[("Event", ev_ns, ev["metadata"]["name"])] = ev
                    self.store.bump("Event", "ADDED", ev)
                    # preempt the gang's pods: kill their processes so
                    # the kubelet reap marks them Failed with a signal
                    # exit — exactly what losing the slice looks like
                    ns = key[1]
                    for pkey, pobj in self.store.objects.items():
                        if pkey[0] != "Pod" or pkey[1] != ns:
                            continue
                        ann = (
                            pobj.get("metadata", {}).get("annotations", {})
                            or {}
                        )
                        gname = ann.get(ANNOTATION_GANG_GROUP) or ann.get(
                            "scheduling.k8s.io/group-name"
                        )
                        if gname == key[2] and pkey in self._procs:
                            to_kill.append(pkey)
            self._regrant_locked()
        for pkey in to_kill:
            proc = self._procs.get(pkey)
            if proc is not None and proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        return revoked

    # -- verbs --------------------------------------------------------------

    @staticmethod
    def _tpujob_admission_problem(obj: Dict[str, Any]) -> Optional[str]:
        """Server-side admission, the webhook's seat (CREATE and
        UPDATE verbs, like a real admission webhook): parse + default
        + validate a COPY of the object (the stored JSON stays
        byte-what-the-client-sent); returns the 422 message, or None
        when admissible."""

        try:
            from tf_operator_tpu.api.defaults import set_defaults
            from tf_operator_tpu.api.serde import job_from_dict
            from tf_operator_tpu.api.validation import validate

            job = job_from_dict(obj)
            set_defaults(job)
            validate(job)
        except Exception as e:  # noqa: BLE001 - admission boundary
            return f"TPUJob admission rejected: {type(e).__name__}: {e}"
        return None

    def _create(self, h, kind: str, ns: Optional[str], obj: Dict[str, Any]):
        meta = obj.setdefault("metadata", {})
        namespace = ns or meta.get("namespace", "default")
        meta["namespace"] = namespace
        name = meta.get("name", "")
        if not name:
            return self._reply(
                h, 400, self._status(400, "Invalid", "metadata.name required")
            )
        if kind == "TPUJob" and self.admission:
            problem = self._tpujob_admission_problem(obj)
            if problem is not None:
                return self._reply(
                    h, 422, self._status(422, "Invalid", problem)
                )
        key = (kind, namespace, name)
        with self.store.lock:
            if key in self.store.objects:
                return self._reply(
                    h,
                    409,
                    self._status(409, "AlreadyExists", f"{kind} {name} exists"),
                )
            meta.setdefault("uid", self.store.next_uid(kind.lower()))
            if kind == "Pod":
                obj.setdefault("status", {})["phase"] = "Pending"
            elif kind == "PodGroup":
                granted = self._can_grant(self._group_chips(obj), exclude=None)
                obj.setdefault("status", {})["phase"] = (
                    "Granted" if granted else "Pending"
                )
            self.store.objects[key] = obj
            self.store.bump(kind, "ADDED", obj)
            if kind == "Event":
                # TTL-analogue GC: silently drop the oldest Events past
                # the cap (insertion order; nobody watches Events)
                ev_keys = [k for k in self.store.objects if k[0] == "Event"]
                for old_key in ev_keys[: max(0, len(ev_keys) - MAX_EVENT_OBJECTS)]:
                    self.store.objects.pop(old_key, None)
            return self._reply(h, 201, obj)

    def _get(self, h, kind: str, ns: Optional[str], name: str):
        key = (kind, ns or "default", name)
        with self.store.lock:
            obj = self.store.objects.get(key)
            if obj is None:
                return self._reply(
                    h, 404, self._status(404, "NotFound", f"{kind} {name}")
                )
            return self._reply(h, 200, obj)

    def _list(
        self, h, kind: str, ns: Optional[str], sel: Dict[str, str],
        fsel: Optional[Dict[str, str]] = None,
    ):
        with self.store.lock:
            items = [
                o
                for (k, n, _), o in self.store.objects.items()
                if k == kind
                and (ns is None or n == ns)
                and match_selector(_labels(o), sel)
                and all(
                    str(_field_get(o, fk)) == fv
                    for fk, fv in (fsel or {}).items()
                )
            ]
            out = {
                "apiVersion": "v1",
                "kind": f"{kind}List",
                "metadata": {"resourceVersion": str(self.store.rv)},
                "items": items,
            }
            return self._reply(h, 200, out)

    def _delete_obj(self, h, kind: str, ns: Optional[str], name: str):
        key = (kind, ns or "default", name)
        with self.store.lock:
            obj = self.store.objects.pop(key, None)
            if obj is None:
                return self._reply(
                    h, 404, self._status(404, "NotFound", f"{kind} {name}")
                )
            proc = self._procs.pop(key, None)
            self.store.bump(kind, "DELETED", obj)
            if kind == "PodGroup":
                self._regrant_locked()
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
        return self._reply(h, 200, self._status(200, "Success", "deleted"))

    def _patch(self, h, kind, ns, name, patch: Dict[str, Any]):
        key = (kind, ns or "default", name)
        with self.store.lock:
            obj = self.store.objects.get(key)
            if obj is None:
                return self._reply(
                    h, 404, self._status(404, "NotFound", f"{kind} {name}")
                )
            # optimistic concurrency (the real apiserver's update
            # precondition): a patch carrying metadata.resourceVersion
            # only applies against that exact version — the mechanism
            # Lease-based leader election's compare-and-swap rides
            want_rv = str(patch.get("metadata", {}).get("resourceVersion", ""))
            have_rv = str(obj.get("metadata", {}).get("resourceVersion", ""))
            if want_rv and want_rv != have_rv:
                return self._reply(
                    h,
                    409,
                    self._status(
                        409,
                        "Conflict",
                        f"resourceVersion {want_rv} != {have_rv}",
                    ),
                )
            # admission covers UPDATE like a real webhook — but only
            # when the patch touches spec: status-only patches (the
            # operator marking an out-of-band-invalid job Failed) must
            # land even on inadmissible stored objects
            if kind == "TPUJob" and self.admission and "spec" in patch:
                merged = json.loads(json.dumps(obj))
                _merge_patch(merged, patch)
                problem = self._tpujob_admission_problem(merged)
                if problem is not None:
                    return self._reply(
                        h, 422, self._status(422, "Invalid", problem)
                    )
            _merge_patch(obj, patch)
            self.store.bump(kind, "MODIFIED", obj)
            if kind == "PodGroup":
                # re-evaluate admission with the new size
                chips = self._group_chips(obj)
                granted = self._can_grant(chips, exclude=key)
                obj["status"]["phase"] = "Granted" if granted else "Pending"
                self.store.bump(kind, "MODIFIED", obj)
                self._regrant_locked()
            return self._reply(h, 200, obj)

    def _replace(self, h, kind, ns, name, new_obj: Dict[str, Any]):
        """PUT = whole-object replacement (client-go Update): unlike
        merge-patch, absent keys are DROPPED — the semantics a spec
        update needs to unset a field.  Identity (name/namespace/uid)
        is server-owned and preserved."""

        key = (kind, ns or "default", name)
        with self.store.lock:
            obj = self.store.objects.get(key)
            if obj is None:
                return self._reply(
                    h, 404, self._status(404, "NotFound", f"{kind} {name}")
                )
            want_rv = str(
                new_obj.get("metadata", {}).get("resourceVersion", "")
            )
            have_rv = str(obj.get("metadata", {}).get("resourceVersion", ""))
            if want_rv and want_rv != have_rv:
                return self._reply(
                    h,
                    409,
                    self._status(
                        409,
                        "Conflict",
                        f"resourceVersion {want_rv} != {have_rv}",
                    ),
                )
            meta = new_obj.setdefault("metadata", {})
            meta["name"] = name
            meta["namespace"] = ns or "default"
            meta["uid"] = obj.get("metadata", {}).get("uid", "")
            if kind == "TPUJob" and self.admission:
                # whole-object replacement carries a spec by definition
                problem = self._tpujob_admission_problem(new_obj)
                if problem is not None:
                    return self._reply(
                        h, 422, self._status(422, "Invalid", problem)
                    )
            self.store.objects[key] = new_obj
            self.store.bump(kind, "MODIFIED", new_obj)
            if kind == "PodGroup":
                chips = self._group_chips(new_obj)
                granted = self._can_grant(chips, exclude=key)
                new_obj.setdefault("status", {})["phase"] = (
                    "Granted" if granted else "Pending"
                )
                self.store.bump(kind, "MODIFIED", new_obj)
                self._regrant_locked()
            return self._reply(h, 200, new_obj)

    def _pod_log(self, h, ns: Optional[str], name: str):
        path = self._log_path(ns or "default", name)
        try:
            with open(path, "r", errors="replace") as f:
                return self._reply(h, 200, text=f.read())
        except FileNotFoundError:
            return self._reply(h, 404, self._status(404, "NotFound", "no log"))

    # -- watch --------------------------------------------------------------

    def _watch(self, h, kind: str, rv: int):
        q: Queue = Queue()
        with self.store.lock:
            if rv and rv < self.store.oldest_rv() - 1:
                # the requested window is gone — the client must re-list
                return self._reply(
                    h,
                    410,
                    self._status(
                        410, "Expired", f"resourceVersion {rv} is too old"
                    ),
                )
            backlog = [
                (erv, k, et, o)
                for (erv, k, et, o) in self.store.log
                if k == kind and erv > rv
            ]
            self.store.watchers.append(q)
        try:
            h.send_response(200)
            h.send_header("Content-Type", "application/json")
            h.send_header("Transfer-Encoding", "chunked")
            span = getattr(h, "_trace_span", None)
            if span is not None:
                h.send_header(TRACE_HEADER, span.trace_id)
                # streams outlive any sane span duration: the traced
                # unit is the watch ACCEPT; end it once committed
                span.set_attribute("watch", True)
                span.end()
            h.end_headers()

            def emit(etype: str, obj: Dict[str, Any]) -> None:
                line = (
                    json.dumps({"type": etype, "object": obj}) + "\n"
                ).encode()
                h.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
                h.wfile.flush()

            for _, _, et, o in backlog:
                emit(et, o)
            while not (self._stop.is_set() or self._paused.is_set()):
                try:
                    item = q.get(timeout=0.5)
                except Empty:
                    continue
                if item is None:
                    break
                erv, k, et, o = item
                if k == kind and erv > rv:
                    emit(et, o)
            # terminating chunk (best effort; client may be gone)
            h.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with self.store.lock:
                try:
                    self.store.watchers.remove(q)
                except ValueError:
                    pass

    # -- scheduler sim (gang admission, backend/fake.py semantics) ----------

    @staticmethod
    def _group_chips(obj: Dict[str, Any]) -> int:
        res = obj.get("spec", {}).get("minResources", {})
        try:
            return int(res.get("google.com/tpu", 0))
        except (TypeError, ValueError):
            return 0

    def _can_grant(self, chips: int, exclude) -> bool:
        if self.total_chips is None:
            return True
        in_use = sum(
            self._group_chips(o)
            for key, o in self.store.objects.items()
            if key[0] == "PodGroup"
            and key != exclude
            and o.get("status", {}).get("phase") == "Granted"
        )
        return in_use + chips <= self.total_chips

    def _regrant_locked(self) -> None:
        for key, o in self.store.objects.items():
            if (
                key[0] == "PodGroup"
                and o.get("status", {}).get("phase") == "Pending"
                and self._can_grant(self._group_chips(o), exclude=key)
            ):
                o["status"]["phase"] = "Granted"
                self.store.bump("PodGroup", "MODIFIED", o)

    def _gang_blocked(self, pod: Dict[str, Any]) -> bool:
        ann = pod.get("metadata", {}).get("annotations", {}) or {}
        gname = ann.get(ANNOTATION_GANG_GROUP) or ann.get(
            "scheduling.k8s.io/group-name"
        )
        if not gname:
            return False
        ns = pod["metadata"].get("namespace", "default")
        group = self.store.objects.get(("PodGroup", ns, gname))
        return (
            group is None
            or group.get("status", {}).get("phase") != "Granted"
        )

    # -- kubelet sim --------------------------------------------------------

    def _log_path(self, namespace: str, name: str) -> str:
        d = os.path.join(self.log_dir, namespace)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{name}.log")

    def _spawn_env(self, pod: Dict[str, Any]) -> Dict[str, str]:
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT
        env.pop("JAX_PLATFORMS", None)
        for c in pod.get("spec", {}).get("containers", []):
            for e in c.get("env", []):
                env[e["name"]] = e.get("value", "")
            break
        return env

    def _kubelet_loop(self) -> None:
        """scheduler + kubelet tick: start admissible Pending pods as
        subprocesses; surface exits as pod phase + exit code."""

        while not self._stop.is_set():
            to_spawn = []
            with self.store.lock:
                for key, obj in self.store.objects.items():
                    if key[0] != "Pod":
                        continue
                    if obj.get("status", {}).get("phase") != "Pending":
                        continue
                    if key in self._procs:
                        continue
                    if self._gang_blocked(obj):
                        continue
                    to_spawn.append((key, json.loads(json.dumps(obj))))
            for key, obj in to_spawn:
                self._spawn(key, obj)
            # reap exits
            with self.store.lock:
                items = list(self._procs.items())
            for key, proc in items:
                rc = proc.poll()
                if rc is None:
                    continue
                with self.store.lock:
                    self._procs.pop(key, None)
                    obj = self.store.objects.get(key)
                    if obj is None:
                        continue
                    phase = obj.get("status", {}).get("phase")
                    if phase in ("Succeeded", "Failed"):
                        continue
                    code = rc if rc >= 0 else 128 - rc
                    obj["status"]["phase"] = (
                        "Succeeded" if rc == 0 else "Failed"
                    )
                    cname = "tensorflow"
                    for c in obj.get("spec", {}).get("containers", []):
                        cname = c.get("name", cname)
                        break
                    obj["status"]["containerStatuses"] = [
                        {
                            "name": cname,
                            "restartCount": 0,
                            "state": {"terminated": {"exitCode": code}},
                        }
                    ]
                    self.store.bump("Pod", "MODIFIED", obj)
            self._stop.wait(self.kubelet_interval)

    def _spawn(self, key, obj: Dict[str, Any]) -> None:
        ns, name = key[1], key[2]
        main = None
        for c in obj.get("spec", {}).get("containers", []):
            main = c
            break
        cmd = list((main or {}).get("command", [])) + list(
            (main or {}).get("args", [])
        )
        if not cmd:
            self._fail_pod(key, 127, "no runnable command")
            return
        logf = open(self._log_path(ns, name), "ab")
        try:
            proc = subprocess.Popen(
                cmd,
                env=self._spawn_env(obj),
                stdout=logf,
                stderr=subprocess.STDOUT,
                cwd=(main or {}).get("workingDir") or _REPO_ROOT,
                start_new_session=True,
            )
        except OSError as e:
            logf.write(f"spawn failed: {e}\n".encode())
            logf.close()
            self._fail_pod(key, 127, repr(e))
            return
        logf.close()
        with self.store.lock:
            live = self.store.objects.get(key)
            if live is None:  # deleted while spawning
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                proc.wait()
                return
            self._procs[key] = proc
            live["status"]["phase"] = "Running"
            self.store.bump("Pod", "MODIFIED", live)

    def _fail_pod(self, key, code: int, why: str) -> None:
        with self.store.lock:
            obj = self.store.objects.get(key)
            if obj is None:
                return
            obj["status"]["phase"] = "Failed"
            obj["status"]["containerStatuses"] = [
                {
                    "name": "tensorflow",
                    "restartCount": 0,
                    "state": {"terminated": {"exitCode": code}},
                }
            ]
            self.store.bump("Pod", "MODIFIED", obj)
