"""KubeJobStore: TPUJob objects stored IN the apiserver — the
reference's TFJob-CRD tier, executable.

Parity: in the reference, TFJobs are custom resources in etcd behind
the apiserver; the operator holds only a watch-fed cache, so any
replica that wins leader election sees every job (SURVEY.md §1 L1/L4,
§3.1).  The in-proc ``JobStore`` keeps jobs in operator memory — a
standby that takes over leadership starts blank (docs/TRUST.md's old
HA caveat).  This store closes that gap for the kube backends: jobs
live at ``/apis/tpujob.dist/v1/namespaces/{ns}/tpujobs`` as real
custom-resource JSON (``api/serde.py``'s manifest round-trip), so

- operator restarts and leader failover resume every job from the
  apiserver (the new leader's informer resyncs jobs AND the still-
  running pods, adopting by owner uid exactly like the reference);
- ``tpujob submit`` against any replica could in principle write the
  same substrate (the job API still routes through the leader, which
  is the reference's convention too).

Same surface as ``JobStore`` (create/get/list/update_status/
update_spec/delete/subscribe): admission (defaults + validation) runs
client-side before the POST, exactly where the reference's admission
webhook sits relative to etcd; ``update_status`` PATCHes the status
section last-write-wins — safe because the single elected leader is
the only status writer (the reference relies on the same invariant).

Watch: a ListAndWatch thread on the tpujobs collection feeds
subscribers ``WatchEvent(kind="TPUJob")`` — delivery is asynchronous
(create returns before the controller hears), which is the real
apiserver contract the informer + Expectations machinery is built
for.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
import urllib.parse
from http.client import HTTPConnection
from typing import List, Optional

from tf_operator_tpu.api.defaults import set_defaults
from tf_operator_tpu.api.serde import (
    job_from_dict,
    job_to_dict,
    status_from_dict,
    status_to_dict,
)
from tf_operator_tpu.api.types import ObjectMeta, TPUJob, TPUJobStatus
from tf_operator_tpu.api.validation import validate
from tf_operator_tpu.backend.kube import ApiError, GoneError, http_json
from tf_operator_tpu.backend.objects import (
    WatchEvent,
    WatchEventType,
    WatchHandler,
)
from tf_operator_tpu.backend.retry import (
    NETWORK_ERRORS,
    CircuitBreaker,
    RetryPolicy,
    default_policy,
    watch_recovery,
)
from tf_operator_tpu.utils.metrics import default_metrics

_log = logging.getLogger("tpujob.kubejobs")

COLLECTION = "/apis/tpujob.dist/v1/tpujobs"


def _ns_path(namespace: str) -> str:
    return f"/apis/tpujob.dist/v1/namespaces/{namespace}/tpujobs"


def _decode(obj: dict, metrics=None) -> TPUJob:
    """Stored JSON → TPUJob, NEVER raising: the watch loop and list
    path must survive out-of-band apiserver writes (no admission
    webhook on a real cluster without ours deployed).  An object that
    fails to parse or validate comes back as a skeleton carrying
    ``invalid_reason`` — the informer still caches/keys it, and the
    reconciler marks it Failed/InvalidSpec instead of crashing or
    silently spinning the ListAndWatch recovery path."""

    meta_d = obj.get("metadata", {}) if isinstance(obj, dict) else {}
    try:
        job = job_from_dict(obj)
        validate(job)
    except Exception as e:  # noqa: BLE001 - ingestion admission boundary
        job = TPUJob(
            metadata=ObjectMeta(
                name=str(meta_d.get("name", "")),
                namespace=str(meta_d.get("namespace", "default")),
                uid=str(meta_d.get("uid", "")),
            ),
            invalid_reason=f"{type(e).__name__}: {e}",
        )
        try:
            # keep any status the leader already wrote (e.g. our own
            # Failed/InvalidSpec condition) so is_terminal() holds on
            # re-ingestion and the object is cleaned up, not re-marked
            if isinstance(obj, dict) and "status" in obj:
                job.status = status_from_dict(obj["status"])
        except Exception as status_err:  # noqa: BLE001 - garbage status stays empty
            _log.warning(
                "invalid object %s also has unparseable status: %s",
                job.key, status_err,
            )
        # count on the CALLER's registry when one was injected —
        # the store routes every other fault counter there, and a
        # /metrics missing exactly this family hides garbage ingestion
        (metrics if metrics is not None else default_metrics).inc(
            "informer_invalid_objects_total", kind="TPUJob"
        )
    rv = meta_d.get("resourceVersion", "0")
    job.metadata.resource_version = int(rv) if str(rv).isdigit() else 0
    return job


class KubeJobStore:
    """JobStore surface over the Kubernetes HTTP protocol."""

    def __init__(
        self, base_url: str, timeout: float = 5.0,
        retry: Optional[RetryPolicy] = None, metrics=None, breaker=None,
        tracer=None,
    ):
        from tf_operator_tpu.utils.trace import default_tracer

        u = urllib.parse.urlparse(base_url)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.timeout = timeout
        self.retry = retry if retry is not None else default_policy()
        self.metrics = metrics if metrics is not None else default_metrics
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.tracer = tracer if tracer is not None else default_tracer
        self._handlers: List[WatchHandler] = []
        self._handlers_lock = threading.Lock()
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._watch_conn: Optional[HTTPConnection] = None

    def _request(self, method: str, path: str, body=None) -> dict:
        return http_json(
            self.host, self.port, method, path, body, self.timeout,
            policy=self.retry, metrics=self.metrics, client="kube-jobs",
            breaker=self.breaker, tracer=self.tracer,
        )

    # -- JobStore surface ---------------------------------------------------

    def create(self, job: TPUJob) -> TPUJob:
        """Admission client-side, storage in the apiserver."""

        from tf_operator_tpu.backend.base import AlreadyExistsError

        set_defaults(job)
        validate(job)
        d = job_to_dict(job)
        d.setdefault("metadata", {})["namespace"] = job.metadata.namespace
        path = _ns_path(job.metadata.namespace)
        ambiguous = []

        def attempt():
            try:
                return http_json(
                    self.host, self.port, "POST", path, d, self.timeout,
                    tracer=self.tracer,
                )
            except NETWORK_ERRORS:
                # the send died without a response: the server may or
                # may not have committed it.  Error RESPONSES (429,
                # injected 503) are definitive non-commits and do not
                # mark ambiguity.
                ambiguous.append(True)
                raise

        try:
            out = self.retry.call(
                attempt,
                client="kube-jobs",
                metrics=self.metrics,
                breaker=self.breaker,
            )
        except AlreadyExistsError:
            # a 409 is ambiguous ONLY after a lost-response send:
            # against a real apiserver that first send may have
            # committed, so our own replay lands 409.  If that
            # happened AND the stored object's spec is exactly what we
            # posted, this IS our create — return it.  A 409 with no
            # lost response (genuine duplicate submission, even after
            # a definitive 429/503 retry) and a conflicting
            # pre-existing spec both still raise.
            if ambiguous:
                existing = self.get(
                    job.metadata.namespace, job.metadata.name
                )
                if existing is not None and job_to_dict(existing).get(
                    "spec"
                ) == d.get("spec"):
                    job.metadata.uid = existing.metadata.uid
                    job.metadata.resource_version = (
                        existing.metadata.resource_version
                    )
                    return existing
            raise
        stored = _decode(out, self.metrics)
        # reflect server-assigned identity back into the caller's
        # object, like JobStore.create / client-go Create
        job.metadata.uid = stored.metadata.uid
        job.metadata.resource_version = stored.metadata.resource_version
        return stored

    def get(self, namespace: str, name: str) -> Optional[TPUJob]:
        from tf_operator_tpu.backend.base import NotFoundError

        try:
            out = self._request("GET", f"{_ns_path(namespace)}/{name}")
        except NotFoundError:
            return None
        return _decode(out, self.metrics)

    def list(self, namespace: Optional[str] = None) -> List[TPUJob]:
        path = COLLECTION if namespace is None else _ns_path(namespace)
        out = self._request("GET", path)
        return [_decode(o, self.metrics) for o in out.get("items", [])]

    def update_status(
        self, namespace: str, name: str, status: TPUJobStatus
    ) -> TPUJob:
        """The status-subresource write.  Last-write-wins by design:
        the elected leader is the only status writer."""

        out = self._request(
            "PATCH",
            f"{_ns_path(namespace)}/{name}",
            {"status": status_to_dict(status)},
        )
        return _decode(out, self.metrics)

    def update_spec(self, job: TPUJob) -> TPUJob:
        """Whole-spec REPLACEMENT (JobStore.update_spec parity, via
        PUT): merge-patch would keep keys the new spec omits — e.g.
        enableGangScheduling set back to False serializes to an
        absent key and must still unset the stored True."""

        set_defaults(job)
        validate(job)
        path = f"{_ns_path(job.metadata.namespace)}/{job.metadata.name}"
        current = self._request("GET", path)
        current["spec"] = job_to_dict(job)["spec"]
        out = self._request("PUT", path, current)
        return _decode(out, self.metrics)

    def delete(self, namespace: str, name: str) -> None:
        self._request("DELETE", f"{_ns_path(namespace)}/{name}")

    # -- watch --------------------------------------------------------------

    def subscribe(self, handler: WatchHandler) -> None:
        with self._handlers_lock:
            self._handlers.append(handler)
            if self._watcher is None:
                self._watcher = threading.Thread(
                    target=self._watch_loop, daemon=True,
                    name="kube-watch-tpujob",
                )
                self._watcher.start()

    def _dispatch(self, ev: WatchEvent) -> None:
        with self._handlers_lock:
            handlers = list(self._handlers)
        for h in handlers:
            h(ev)

    def _watch_loop(self) -> None:
        """client-go ListAndWatch on the tpujobs collection (same
        recovery discipline as KubeBackend._watch_loop: resume from
        the last delivered event; 410 or a broken stream re-lists)."""

        rv = 0
        fails = 0  # consecutive broken streams → jittered backoff
        while not self._stop.is_set():
            try:
                if rv == 0:
                    out = self._request("GET", COLLECTION)
                    lrv = out.get("metadata", {}).get("resourceVersion", "0")
                    rv = int(lrv) if str(lrv).isdigit() else 0
                    # feed the listed jobs to subscribers (client-go
                    # ListAndWatch): a job stored before this operator
                    # started must reconcile NOW, not at first resync
                    for o in out.get("items", []):
                        self._dispatch(
                            WatchEvent(
                                type=WatchEventType.ADDED,
                                kind="TPUJob",
                                obj=_decode(o, self.metrics),
                            )
                        )
                rv = self._stream(rv)
                fails = 0
            except GoneError:
                # expired watch window (or injected 410 storm): re-list
                # from scratch, under backoff so a storm can't spin
                fails = watch_recovery(
                    fails, stop=self._stop, policy=self.retry,
                    metrics=self.metrics, kind="TPUJob", gone=True,
                )
                rv = 0
            except Exception as e:  # noqa: BLE001 - ListAndWatch recovery
                fails = watch_recovery(
                    fails, stop=self._stop, policy=self.retry,
                    metrics=self.metrics, kind="TPUJob", log=_log, exc=e,
                )
                rv = 0

    def _stream(self, rv: int) -> int:
        conn = HTTPConnection(self.host, self.port)
        self._watch_conn = conn
        try:
            conn.request(
                "GET", f"{COLLECTION}?watch=true&resourceVersion={rv}"
            )
            resp = conn.getresponse()
            if resp.status == 410:
                raise GoneError(410, "")
            if resp.status != 200:
                raise ApiError(resp.status, "")
            while not self._stop.is_set():
                line = resp.readline()
                if not line:
                    return rv
                line = line.strip()
                if not line:
                    continue
                doc = json.loads(line)
                if doc.get("type") == "ERROR":
                    status = doc.get("object", {})
                    if status.get("code") == 410:
                        raise GoneError(410, "")
                    raise ApiError(int(status.get("code", 500)), str(status))
                job = _decode(doc["object"], self.metrics)
                rv = max(rv, job.metadata.resource_version)
                self._dispatch(
                    WatchEvent(
                        type=WatchEventType(doc["type"]),
                        kind="TPUJob",
                        obj=job,
                    )
                )
            return rv
        finally:
            self._watch_conn = None
            conn.close()

    def close(self) -> None:
        self._stop.set()
        conn = self._watch_conn
        if conn is not None:
            try:
                conn.sock and conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        if self._watcher is not None:
            self._watcher.join(timeout=2.0)


class KubeEventRecorder:
    """EventRecorder surface posting REAL ``v1 Event`` objects to the
    apiserver (``/api/v1/namespaces/{ns}/events``) — the reference's
    audit trail lives in the events API, not operator memory, so
    `kubectl get events`-style tooling and a post-failover leader both
    see the history.  Reads filter server-side with the real
    ``fieldSelector involvedObject.name=...`` shape.

    Same surface as utils.events.EventRecorder (event / for_object /
    all), so the controller, job API, and `tpujob describe` read path
    take it unchanged.  Like client-go's event broadcaster, posting is
    asynchronous AND best-effort: ``event()`` enqueues to a bounded
    buffer drained by a daemon thread (an emission must never block a
    reconcile worker on network I/O), and a full buffer or an
    apiserver error drops the event rather than failing the reconcile
    that emitted it.  Timestamps go out as RFC3339 (what a real
    apiserver validates) and parse back from RFC3339 or epoch floats.
    """

    #: bounded post buffer; overflow drops the OLDEST events
    QUEUE_MAX = 1024

    def __init__(
        self, base_url: str, timeout: float = 2.0,
        retry: Optional[RetryPolicy] = None, metrics=None,
    ):
        import collections

        u = urllib.parse.urlparse(base_url)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.timeout = timeout
        # tighter budget than the control-loop default: event posting
        # is best-effort and must never wedge the drain thread long
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=0.5, deadline=2.0
        )
        self.metrics = metrics if metrics is not None else default_metrics
        self._seq = 0
        self._lock = threading.Lock()
        self._queue = collections.deque(maxlen=self.QUEUE_MAX)
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._poster = threading.Thread(
            target=self._post_loop, daemon=True, name="kube-event-post"
        )
        self._poster.start()

    def _request(self, method: str, path: str, body=None) -> dict:
        return http_json(
            self.host, self.port, method, path, body, self.timeout,
            policy=self.retry, metrics=self.metrics, client="kube-events",
        )

    @staticmethod
    def _rfc3339(ts: float) -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))

    @staticmethod
    def _parse_ts(raw) -> float:
        """Epoch float from either our epoch-float wire value or a real
        apiserver's RFC3339 string; unparseable -> 0.0 (never raises:
        this sits on the describe read path)."""

        if isinstance(raw, (int, float)):
            return float(raw)
        if isinstance(raw, str):
            try:
                return float(raw)
            except ValueError:
                pass
            import calendar

            for fmt in ("%Y-%m-%dT%H:%M:%SZ", "%Y-%m-%dT%H:%M:%S.%fZ"):
                try:
                    return calendar.timegm(time.strptime(raw, fmt))
                except ValueError:
                    continue
        return 0.0

    def event(
        self, object_key: str, etype: str, reason: str, message: str
    ) -> None:
        ns, _, name = object_key.partition("/")
        now = time.time()
        with self._lock:
            self._seq += 1
            seq = self._seq
        obj = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                # unique AND lexicographically time-ordered (zero-padded
                # micros): the same-second tie-break for sorted reads
                "name": f"{name}.{int(now * 1e6):016x}.{seq}",
                "namespace": ns,
            },
            "type": etype,
            "reason": reason,
            "message": message,
            "involvedObject": {
                "apiVersion": "tpujob.dist/v1",
                "kind": "TPUJob",
                "name": name,
                "namespace": ns,
            },
            "firstTimestamp": self._rfc3339(now),
        }
        self._queue.append((ns, obj))  # deque(maxlen): overflow drops oldest
        self._kick.set()

    def _post_loop(self) -> None:
        dropped = 0
        while not self._stop.is_set():
            self._kick.wait(timeout=0.5)
            self._kick.clear()
            while True:
                try:
                    ns, obj = self._queue.popleft()
                except IndexError:
                    break
                try:
                    # bounded retry (self.retry inside _request); still
                    # best-effort like client-go's broadcaster, but a
                    # drop is now COUNTED and periodically logged, not
                    # silently swallowed
                    self._request(
                        "POST", f"/api/v1/namespaces/{ns}/events", obj
                    )
                except Exception as e:  # noqa: BLE001 - best-effort sink
                    dropped += 1
                    self.metrics.inc("api_events_dropped_total")
                    if dropped == 1 or dropped % 100 == 0:
                        _log.warning(
                            "dropped %d event(s); last: %s posting %s (%s)",
                            dropped, type(e).__name__,
                            obj.get("reason", "?"), e,
                        )

    def flush(self, timeout: float = 5.0) -> None:
        """Block until the post buffer drains (tests / clean shutdown)."""

        deadline = time.time() + timeout
        while self._queue and time.time() < deadline:
            time.sleep(0.02)

    def close(self) -> None:
        self.flush(timeout=2.0)
        self._stop.set()
        self._kick.set()
        self._poster.join(timeout=2.0)

    def _decode_events(self, items):
        from tf_operator_tpu.utils.events import Event

        decorated = []
        for o in items:
            inv = o.get("involvedObject", {}) or {}
            decorated.append((
                self._parse_ts(o.get("firstTimestamp")),
                str(o.get("metadata", {}).get("name", "")),
                Event(
                    object_key=(
                        f"{inv.get('namespace', '')}/{inv.get('name', '')}"
                    ),
                    type=o.get("type", "Normal"),
                    reason=o.get("reason", ""),
                    message=o.get("message", ""),
                    timestamp=self._parse_ts(o.get("firstTimestamp")),
                ),
            ))
        decorated.sort(key=lambda t: (t[0], t[1]))
        return [e for _, _, e in decorated]

    def _read_failed(self, what: str, e: Exception) -> list:
        """Describe-path reads degrade to [] (must never raise), but
        the failure is counted and logged — not silently swallowed."""

        self.metrics.inc("api_event_read_failures_total")
        _log.warning(
            "event read %s failed after retries: %s: %s",
            what, type(e).__name__, e,
        )
        return []

    def for_object(self, object_key: str):
        ns, _, name = object_key.partition("/")
        fsel = urllib.parse.quote(
            f"involvedObject.name={name},involvedObject.namespace={ns}"
        )
        try:
            out = self._request(
                "GET",
                f"/api/v1/namespaces/{ns}/events?fieldSelector={fsel}",
            )
        except Exception as e:  # noqa: BLE001 - degrade-to-empty read path
            return self._read_failed(object_key, e)
        return self._decode_events(out.get("items", []))

    def all(self):
        try:
            out = self._request("GET", "/api/v1/events")
        except Exception as e:  # noqa: BLE001 - degrade-to-empty read path
            return self._read_failed("all", e)
        return self._decode_events(out.get("items", []))
