"""In-proc fake cluster — the test substrate (SURVEY.md §4 tier 1, §7 step 2).

Parity: the role played by ``client-go``'s fake clientsets + FakePodControl
in the reference's unit tests: "the cluster is a data structure"
(SURVEY.md §4).  Additions the reference gets from a real cluster and we
must simulate:

- **watch latency**: ``delivery="manual"`` buffers watch events until
  ``pump()`` — the informer-cache lag that the Expectations mechanism
  exists to survive; tests can interleave syncs and deliveries
  adversarially.
- **scheduler + kubelet sim**: pods whose gang group is not yet Granted
  stay Pending; test helpers transition phases and set exit codes.
- **atomic capacity**: ``total_chips`` with all-or-nothing PodGroup
  admission (the TPU-slice generalisation of volcano gang scheduling).
"""

from __future__ import annotations

import copy
import itertools
import threading
from collections import deque
from typing import Deque, Dict, List, Optional

from tf_operator_tpu.api.types import ANNOTATION_GANG_GROUP, ObjectMeta, PodPhase
from tf_operator_tpu.backend.base import (
    AlreadyExistsError,
    ClusterBackend,
    NotFoundError,
    match_selector,
)
from tf_operator_tpu.backend.objects import (
    Pod,
    PodGroup,
    PodGroupPhase,
    Service,
    WatchEvent,
    WatchEventType,
    WatchHandler,
)
from tf_operator_tpu.utils.logging import logger_for_job


class FakeCluster(ClusterBackend):
    def __init__(self, delivery: str = "sync", total_chips: Optional[int] = None):
        assert delivery in ("sync", "manual")
        self.delivery = delivery
        self.total_chips = total_chips  # None = unlimited
        self._lock = threading.RLock()
        self._pods: Dict[str, Pod] = {}
        self._services: Dict[str, Service] = {}
        self._groups: Dict[str, PodGroup] = {}
        self._handlers: List[WatchHandler] = []
        self._pending_events: Deque[WatchEvent] = deque()
        self._uid_counter = itertools.count(1)
        # write-call journal, FakePodControl-style assertion surface
        self.created_pods: List[str] = []
        self.deleted_pods: List[str] = []
        self.created_services: List[str] = []
        self.deleted_services: List[str] = []
        #: fleet-scheduler victim routing (controller/scheduler.py):
        #: when attached, capacity-shrink revocation asks it to order
        #: the victims instead of blind LIFO, and every revocation
        #: emits an attributed Preempted Warning event
        self._sched_chooser = None
        self._sched_recorder = None

    def attach_scheduler(self, chooser, recorder=None) -> None:
        """Route capacity-shrink victim choice through ``chooser``
        (anything with ``choose_victims(candidates) -> [keys]``) and,
        when ``recorder`` is given, emit a ``Preempted`` Warning event
        naming each revoked gang and the capacity change."""

        with self._lock:
            self._sched_chooser = chooser
            self._sched_recorder = recorder

    def detach_scheduler(self, chooser) -> None:
        with self._lock:
            if self._sched_chooser is chooser:
                self._sched_chooser = None
                self._sched_recorder = None

    # -- event plumbing -----------------------------------------------------

    def _emit(self, etype: WatchEventType, kind: str, obj) -> None:
        # snapshot: watchers must never alias live store objects, or the
        # manual-delivery lag simulation (and cache/store isolation)
        # breaks for in-place mutations like phase transitions
        ev = WatchEvent(type=etype, kind=kind, obj=obj.clone())
        if self.delivery == "sync":
            self._dispatch(ev)
        else:
            self._pending_events.append(ev)

    def _dispatch(self, ev: WatchEvent) -> None:
        for h in list(self._handlers):
            h(ev)

    def snapshot(self):
        """Re-list for informer resync: cloned pods/services/groups."""

        with self._lock:
            return (
                [p.clone() for p in self._pods.values()],
                [s.clone() for s in self._services.values()],
                [g.clone() for g in self._groups.values()],
            )

    def pump(self, n: Optional[int] = None) -> int:
        """Deliver up to ``n`` buffered watch events (all if None).

        Only meaningful with delivery="manual"; returns events delivered.
        """

        delivered = 0
        while self._pending_events and (n is None or delivered < n):
            self._dispatch(self._pending_events.popleft())
            delivered += 1
        return delivered

    def subscribe(self, handler: WatchHandler) -> None:
        with self._lock:
            self._handlers.append(handler)

    # -- pods ---------------------------------------------------------------

    def create_pod(self, pod: Pod) -> None:
        with self._lock:
            if pod.key in self._pods:
                raise AlreadyExistsError(pod.key)
            if not pod.metadata.uid:
                pod.metadata.uid = f"pod-uid-{next(self._uid_counter)}"
            pod.phase = PodPhase.PENDING
            self._pods[pod.key] = pod
            self.created_pods.append(pod.key)
            self._emit(WatchEventType.ADDED, "Pod", pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            pod = self._pods.pop(key, None)
            if pod is None:
                raise NotFoundError(key)
            self.deleted_pods.append(key)
            self._emit(WatchEventType.DELETED, "Pod", pod)
            self._regrant_pending_groups()

    def list_pods(self, namespace: str, selector=None) -> List[Pod]:
        with self._lock:
            return [
                p
                for p in self._pods.values()
                if p.metadata.namespace == namespace
                and match_selector(p.metadata.labels, selector)
            ]

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        return self._pods.get(f"{namespace}/{name}")

    def update_pod_owner(self, namespace: str, name: str, owner_uid: Optional[str]) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            pod = self._pods.get(key)
            if pod is None:
                raise NotFoundError(key)
            if pod.metadata.owner_uid == (owner_uid or ""):
                return
            pod.metadata.owner_uid = owner_uid or ""
            self._emit(WatchEventType.MODIFIED, "Pod", pod)

    # -- services -----------------------------------------------------------

    def create_service(self, svc: Service) -> None:
        with self._lock:
            if svc.key in self._services:
                raise AlreadyExistsError(svc.key)
            if not svc.metadata.uid:
                svc.metadata.uid = f"svc-uid-{next(self._uid_counter)}"
            self._services[svc.key] = svc
            self.created_services.append(svc.key)
            self._emit(WatchEventType.ADDED, "Service", svc)

    def delete_service(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            svc = self._services.pop(key, None)
            if svc is None:
                raise NotFoundError(key)
            self.deleted_services.append(key)
            self._emit(WatchEventType.DELETED, "Service", svc)

    def list_services(self, namespace: str, selector=None) -> List[Service]:
        with self._lock:
            return [
                s
                for s in self._services.values()
                if s.metadata.namespace == namespace
                and match_selector(s.metadata.labels, selector)
            ]

    # -- gang groups (the scheduler sim) ------------------------------------

    def create_pod_group(self, group: PodGroup) -> None:
        with self._lock:
            if group.key in self._groups:
                raise AlreadyExistsError(group.key)
            group.phase = (
                PodGroupPhase.GRANTED if self._can_grant(group) else PodGroupPhase.PENDING
            )
            self._groups[group.key] = group
            self._emit(WatchEventType.ADDED, "PodGroup", group)

    def delete_pod_group(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            group = self._groups.pop(key, None)
            if group is None:
                raise NotFoundError(key)
            group.phase = PodGroupPhase.RELEASED
            self._emit(WatchEventType.DELETED, "PodGroup", group)
            self._regrant_pending_groups()

    def get_pod_group(self, namespace: str, name: str) -> Optional[PodGroup]:
        return self._groups.get(f"{namespace}/{name}")

    def update_pod_group(self, namespace: str, name: str, min_member: int, chip_request: int) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            group = self._groups.get(key)
            if group is None:
                raise NotFoundError(key)
            if group.min_member == min_member and group.chip_request == chip_request:
                return
            group.min_member = min_member
            group.chip_request = chip_request
            # re-evaluate admission with the new size (a grown granted
            # gang may no longer fit; a shrunk pending one may now fit)
            group.phase = (
                PodGroupPhase.GRANTED
                if self._can_grant(group, exclude=group)
                else PodGroupPhase.PENDING
            )
            self._emit(WatchEventType.MODIFIED, "PodGroup", group)
            self._regrant_pending_groups()

    def _chips_in_use(self, exclude: Optional[PodGroup] = None) -> int:
        return sum(
            g.chip_request
            for g in self._groups.values()
            if g.phase is PodGroupPhase.GRANTED and g is not exclude
        )

    def _can_grant(self, group: PodGroup, exclude: Optional[PodGroup] = None) -> bool:
        if self.total_chips is None:
            return True
        return self._chips_in_use(exclude) + group.chip_request <= self.total_chips

    def _regrant_pending_groups(self) -> None:
        """Capacity freed — retry pending gangs in creation order."""

        for g in self._groups.values():
            if g.phase is PodGroupPhase.PENDING and self._can_grant(g):
                g.phase = PodGroupPhase.GRANTED
                self._emit(WatchEventType.MODIFIED, "PodGroup", g)

    def set_total_chips(self, total_chips: Optional[int]) -> List[str]:
        """Resize the simulated chip pool (None = unlimited); returns
        names of gangs revoked by a shrink.  Shrinking preempts the
        most-recently granted gangs until the rest fit (LIFO — the
        oldest work keeps its grant) and FAILS their live pods (the
        kubesim twin kills the processes; losing the grant without
        losing the pods would oversubscribe the pool and hide the
        failures the autoscaler's distress signals key on); growing
        regrants pending gangs.  The kubesim /_capacity knob's in-proc
        twin — the capacity add/remove scenario the elastic autoscaler
        acts on."""

        revoked: List[str] = []
        with self._lock:
            self.total_chips = total_chips
            if total_chips is not None:
                granted = [
                    g for g in self._groups.values()
                    if g.phase is PodGroupPhase.GRANTED
                ]
                in_use = sum(g.chip_request for g in granted)
                # victim order: the attached fleet scheduler's policy
                # (lowest priority class first — controller/scheduler
                # .choose_victims) when one is attached, else LIFO
                # (most-recently granted first; the oldest work keeps
                # its grant, the volcano-ish convention)
                victims = list(reversed(granted))
                if self._sched_chooser is not None:
                    by_key = {g.key: g for g in granted}
                    try:
                        order = self._sched_chooser.choose_victims(
                            [
                                {"key": g.key, "chips": g.chip_request}
                                for g in granted
                            ]
                        )
                        victims = [by_key[k] for k in order if k in by_key]
                    except Exception as e:  # noqa: BLE001 - fall back to LIFO
                        logger_for_job("-", "fake-cluster").warning(
                            "victim chooser failed, using LIFO: %s", e
                        )
                for g in victims:
                    if in_use <= total_chips:
                        break
                    g.phase = PodGroupPhase.PENDING
                    in_use -= g.chip_request
                    revoked.append(g.metadata.name)
                    self._emit(WatchEventType.MODIFIED, "PodGroup", g)
                    if self._sched_chooser is not None:
                        # synchronous park: the scheduler must know the
                        # grant is gone BEFORE any sync observes the
                        # killed pods, or the corpse reads as replica
                        # failure instead of preemption
                        try:
                            self._sched_chooser.note_revoked(
                                g.key, by="capacity-shrink"
                            )
                        except Exception as e:  # noqa: BLE001 - advisory
                            logger_for_job("-", "fake-cluster").warning(
                                "note_revoked(%s) failed: %s", g.key, e
                            )
                    if self._sched_recorder is not None:
                        # attribution (no more anonymous exit-137): the
                        # audit trail names the revoked gang AND why
                        self._sched_recorder.event(
                            g.key,
                            "Warning",
                            "Preempted",
                            f"gang {g.metadata.name} revoked: capacity "
                            f"shrunk to {total_chips} chips "
                            f"(gang held {g.chip_request})",
                        )
                gone = set(revoked)
                for pod in self._pods.values():
                    gname = pod.metadata.annotations.get(ANNOTATION_GANG_GROUP)
                    if gname in gone and pod.phase in (
                        PodPhase.PENDING, PodPhase.RUNNING
                    ):
                        pod.phase = PodPhase.FAILED
                        pod.exit_code = 137  # SIGKILL: preempted
                        self._emit(WatchEventType.MODIFIED, "Pod", pod)
            self._regrant_pending_groups()
        return revoked

    # -- kubelet/scheduler simulation helpers (test-facing) -----------------

    def _gang_blocked(self, pod: Pod) -> bool:
        gname = pod.metadata.annotations.get(ANNOTATION_GANG_GROUP)
        if not gname:
            return False
        group = self._groups.get(f"{pod.metadata.namespace}/{gname}")
        return group is None or group.phase is not PodGroupPhase.GRANTED

    def set_pod_phase(
        self, namespace: str, name: str, phase: PodPhase, exit_code: Optional[int] = None
    ) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            pod = self._pods.get(key)
            if pod is None:
                raise NotFoundError(key)
            if phase is PodPhase.RUNNING and self._gang_blocked(pod):
                raise RuntimeError(f"pod {key} is gang-blocked; group not granted")
            pod.phase = phase
            pod.exit_code = exit_code
            self._emit(WatchEventType.MODIFIED, "Pod", pod)

    def run_all(self, namespace: str) -> int:
        """Scheduler tick: move every schedulable Pending pod to Running."""

        moved = 0
        with self._lock:
            for pod in self._pods.values():
                if (
                    pod.metadata.namespace == namespace
                    and pod.phase is PodPhase.PENDING
                    and not self._gang_blocked(pod)
                ):
                    pod.phase = PodPhase.RUNNING
                    self._emit(WatchEventType.MODIFIED, "Pod", pod)
                    moved += 1
        return moved

    def succeed_pod(self, namespace: str, name: str) -> None:
        self.set_pod_phase(namespace, name, PodPhase.SUCCEEDED, exit_code=0)

    def fail_pod(self, namespace: str, name: str, exit_code: int = 1) -> None:
        self.set_pod_phase(namespace, name, PodPhase.FAILED, exit_code=exit_code)


def make_meta(name: str, namespace: str = "default", **labels) -> ObjectMeta:
    return ObjectMeta(name=name, namespace=namespace, labels=dict(labels))
