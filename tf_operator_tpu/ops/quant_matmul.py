"""int8 weights-only matmul for the decode path — output-scale XLA
form by default, pallas kernel opt-in.

Decode at small batch is weight-bandwidth-bound (ops/quant.py): the
per-token step re-reads every projection from HBM while the MXU idles.
The int8 scheme only pays off if the weight crosses HBM as int8.  The
original `materialize_tree`-per-step form did NOT achieve that —
measured on v5e (2026-08-01, window_out/bench.out): 0.55× the bf16
path, because every step materialized the full bf16 weight tree to HBM
(int8 read + bf16 write + bf16 read ≈ 2.5× the bf16-only traffic).

`quant_matmul` is the fix, wired into the model stack by
`QDenseGeneral` (models/transformer.py): the decode loops pass the
quantized tree straight to `apply`, and each projection computes the
algebraic output-scale form

    x @ (q·s)  ==  (x @ q.astype(bf16)) · s        (s per out-channel)

as ONE dot feeding XLA's own fusions — no weight-tree materialization
anywhere in the program.  Measured decode, llama-wide ~700M
(PROFILE.md "int8 decode"): **1.63× bf16 at batch 1, 1.54× at batch
8**; llama-mini at batch 8 is too weight-light for int8 to pay at all
(0.89×, weight reads are only ~60% of its 0.5 ms step).

The hand-written pallas kernel (grid over N tiles, int8 tile HBM→VMEM,
bf16 convert + MXU dot + f32 scale in VMEM, x resident across the
grid) is kept OPT-IN via TPU_OPERATOR_QUANT_KERNEL=1: it wins isolated
microbenches at the lm_head shape but loses end-to-end — 70+ pallas
calls per token step are 70+ fusion barriers with operand staging
copies (trace: 19k sync copies per 64 steps), which the XLA form never
pays.  See `_use_kernel` for the measured table.

Reference parity: SURVEY.md §2a (the reference's compute tier is CUDA
kernels in its example images); no quantized serving exists there —
this is a beyond-reference capability.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tf_operator_tpu.ops.quant import QTensor

#: pallas GEMV path only below this many activation rows — above it the
#: matmul is compute-bound and XLA's GEMM (with a one-shot dequant) wins
_MAX_GEMV_ROWS = 64

#: candidate N tile widths, largest first (lane-multiple of 128); the
#: first that divides N wins.  256 caps the int8 tile at K×256 bytes —
#: 1 MB at K=4096 — comfortably double-bufferable in 16 MB of VMEM.
_BLOCK_N_CANDIDATES = (512, 256, 128)


def _kernel(x_ref, q_ref, s_ref, o_ref):
    w = q_ref[...].astype(jnp.bfloat16)  # int8→bf16 exact for |q|<=127
    acc = jax.lax.dot_general(
        x_ref[...], w,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = (acc * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def _quant_matmul_2d(x, q, s, block_n: int, interpret: bool = False):
    """x [M, K] bf16 · q [K, N] int8 · s [1, N] f32 → [M, N] x.dtype."""

    m, k = x.shape
    n = q.shape[1]
    grid = (n // block_n,)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((m, k), lambda i: (0, 0)),
            pl.BlockSpec((k, block_n), lambda i: (0, i)),
            pl.BlockSpec((1, block_n), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((m, block_n), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(x, q, s)


def _pick_block_n(n: int) -> "int | None":
    for bn in _BLOCK_N_CANDIDATES:
        if n % bn == 0:
            return bn
    return None


def _use_kernel() -> bool:
    """Opt-IN (TPU_OPERATOR_QUANT_KERNEL=1): measured on v5e
    (2026-08-01, PROFILE.md "int8 decode"), the XLA output-scale form
    below beats this kernel end-to-end at every decode shape tried —
    wide(700M) b8: 104.6 ms vs 142.9 ms; b1: 88.7 vs 93.2 — because 70+
    pallas calls per token step are 70+ fusion barriers with operand
    staging copies, while XLA keeps the int8→bf16 convert inside its
    own fusions.  The kernel wins isolated microbenches at the lm_head
    shape (176 GB/s vs 223 GB/s effective for twice the bytes) and is
    kept for shapes/future tiles where a fused-sibling grid could
    amortize the call count."""

    return (
        os.environ.get("TPU_OPERATOR_QUANT_KERNEL", "") == "1"
        and jax.default_backend() == "tpu"
    )


def quant_matmul(x, qt: QTensor, dtype=jnp.bfloat16):
    """`x @ qt` with the weight crossing HBM as int8.

    x: [..., K] (any leading batch dims); qt.q: [K, *features] int8
    with per-output-channel scale over the LAST axis.  Contraction is
    over x's last axis and q's first — the DenseGeneral single-axis
    case; callers contracting several axes reshape first
    (QDenseGeneral does).  Returns [..., *features] in `dtype`.
    """

    q, s = qt.q, qt.scale
    k = x.shape[-1]
    feat = q.shape[1:]
    n = 1
    for f in feat:
        n *= f
    x2 = x.reshape(-1, k).astype(dtype)
    q2 = q.reshape(k, n)
    # scale must be per-output-channel over the flattened feature dim:
    # broadcastable (1, ..., 1, last) with last == feat[-1]
    per_channel = bool(feat) and s.size == feat[-1]
    if not per_channel:
        raise ValueError(
            f"quant_matmul needs a per-output-channel scale over the last "
            f"axis; got scale shape {s.shape} for kernel {q.shape}"
        )
    # scale per FLATTENED output channel: broadcast over the feature
    # dims, then flatten to match q2's N axis
    s2 = jnp.broadcast_to(s.reshape(-1), feat).reshape(1, n).astype(jnp.float32)
    m = x2.shape[0]
    block_n = _pick_block_n(n)
    if (
        _use_kernel()
        and block_n is not None
        and m <= _MAX_GEMV_ROWS
        and k % 32 == 0  # int8 VMEM tile is (32, 128) on the (K, N) block
    ):
        out = _quant_matmul_2d(x2, q2, s2, block_n)
    else:
        out = (
            jax.lax.dot_general(
                x2, q2.astype(dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * s2
        ).astype(dtype)
    return out.reshape(*x.shape[:-1], *feat)
