"""Fused train-mode BatchNorm(+ReLU, +residual epilogue) — ISSUE 19
tentpole.

FLOPS.md's committed trace table indicts the ResNet TRAIN step: BN-stat
reductions (18.9%) + elementwise fusions (55.8%) + dtype converts
(8.7%) carry the wall while convolution is 5.7% — the documented
~0.32-MFU ceiling.  Per BatchNorm layer the stock graph emits a
reduce pass for the moments, a second elementwise pass for
normalize/affine/ReLU, bf16↔f32 converts on both, and the backward
adds two MORE reductions (Σg, Σg·x̂) plus the dx chain.  Eval-mode
BN-fold (PR 14) cannot touch any of this: training needs live batch
statistics.

This module is the training-side answer: one primitive that computes
the whole BN(+ReLU, +residual-add) epilogue — statistics, normalize,
affine, activation, and every dtype convert — as a two-sweep Pallas
pass over VMEM-resident tiles, with a hand-written VJP whose backward
fuses BN-grad's two reductions with dγ/dβ and the elementwise dx
chain (ReLU mask and residual-branch dy split included) into a single
kernel.

Layout contract (the kernel view):

- input is any ``[..., C]`` array; statistics reduce over every axis
  but the last (NHWC feature norm).  Internally the kernel sees the
  collapsed ``[R, C]`` view (R = prod(leading)), zero-padded up to
  tile multiples — zero rows add nothing to Σx/Σx² while the TRUE row
  count divides the moments, so padding never skews statistics, and
  padded outputs are sliced off;
- forward grid ``(C_tiles, 2, R_tiles)``: for each channel tile,
  sweep 0 accumulates Σx/Σx² into f32 VMEM scratch (x read as bf16
  tiles, converted in-register — the convert never exists in HBM),
  sweep 1 turns the moments into mean/rstd once and streams
  normalize → affine → (+residual) → ReLU → store, all in f32
  registers with ONE final cast to the activation dtype;
- backward grid is the same shape: sweep 0 re-derives the ReLU mask
  from the saved output (``y > 0`` — ``jax.nn.relu``'s subgradient
  convention), accumulates Σg and Σg·x̂ (which ARE dβ/dγ), sweep 1
  streams the dx chain ``(γ·rstd)·(g − Σg/R − x̂·Σg·x̂/R)`` and the
  residual-branch cotangent (= g) in one pass.

VJP contract: the primitive returns ``(y, mean, var)``.  ``mean`` /
``var`` are bookkeeping outputs for the running-statistics update
(flax semantics) — their cotangents are dropped by the backward rule,
so they must never appear in a differentiated objective.  The module
wrapper in models/resnet.py uses them only inside the mutable
``batch_stats`` update, which jax.grad never sees.

Impls (the ``impl`` arg — callers resolve "auto" THEMSELVES so an
explicit request can FAIL instead of silently downgrading, the PR 10
rule):

- ``"xla"``              reference composition mirroring
                         ``flax.linen.BatchNorm``'s exact op order
                         (f32 fast-variance stats, f32 normalize, one
                         final cast) + ``jax.nn.relu`` — bit-
                         comparable to the stock graph, differentiated
                         by autodiff, the CPU/fallback path;
- ``"pallas"``           the TPU kernel (custom_vjp, both directions
                         fused);
- ``"pallas-interpret"`` the same kernel through the interpreter —
                         how CI (JAX_PLATFORMS=cpu) exercises the real
                         kernel path end to end.

Sharding caveat (documented, checked by the resnet wrapper): the
kernel reduces over the rows IT SEES.  Under multi-device pjit the
stock composition computes batch-GLOBAL statistics via XLA collectives;
the Pallas kernel cannot, so ``models/resnet.py`` refuses
``impl="pallas"`` when more than one device is visible instead of
silently switching to per-shard statistics.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

FUSEDBN_IMPLS = ("xla", "pallas", "pallas-interpret")

#: lane width — channel tiles are full lanes
_LANES = 128
#: row-tile ceiling; shrunk (at sublane granularity) for small inputs
_BLOCK_R = 256
#: sublane granularity — bf16 tiles pack (16, 128)
_SUBLANES = 16


def fusedbn_available(*, interpret: bool = False) -> Tuple[bool, str]:
    """(ok, why_not) — can the Pallas fused-BN kernel run HERE?

    The honesty contract (ISSUE 10/19): ``norm_impl="pallas"`` callers
    must FAIL on (False, why) rather than silently run the xla
    composition.  ``interpret=True`` waives the backend requirement
    (the interpreter runs the real kernel anywhere — the CI path)."""

    if not interpret and jax.default_backend() != "tpu":
        return (
            False,
            "the fused-BatchNorm kernel needs the TPU backend (got "
            f"{jax.default_backend()!r}); the xla composition serves "
            "CPU, or pass impl='pallas-interpret' for kernel-path tests",
        )
    return True, ""


class _Cfg(NamedTuple):
    """Static kernel config — hashable, rides custom_vjp's
    nondiff_argnums."""

    eps: float
    relu: bool
    has_residual: bool
    interpret: bool
    #: residual dtype NAME (str keeps the tuple hashable; None = no
    #: residual input)
    res_dtype: Optional[str]


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _compiler_params(interpret: bool):
    if interpret:
        return None
    # channel tiles are independent; the two-sweep + row dims carry the
    # scratch accumulators and must stay sequential
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary", "arbitrary")
    )


def _tiles(r: int, c: int) -> Tuple[int, int, int, int]:
    """(block_r, block_c, r_padded, c_padded) for an [r, c] view."""

    block_r = min(_BLOCK_R, _round_up(max(r, 1), _SUBLANES))
    return block_r, _LANES, _round_up(r, block_r), _round_up(c, _LANES)


def _pad2d(a: jax.Array, rp: int, cp: int, value: float = 0.0) -> jax.Array:
    r, c = a.shape
    if (r, c) == (rp, cp):
        return a
    return jnp.pad(a, ((0, rp - r), (0, cp - c)), constant_values=value)


def _pad_param(v: jax.Array, cp: int, value: float) -> jax.Array:
    """[C] f32 param -> [1, cp] (padding value keeps padded channels
    inert: gamma pads with 1 so rstd·γ stays finite, beta with 0)."""

    c = v.shape[0]
    if c != cp:
        v = jnp.pad(v, (0, cp - c), constant_values=value)
    return v.reshape(1, cp)


# ---------------------------------------------------------------------------
# forward kernel


def _fwd_kernel(cfg: _Cfg, n_rows: int, *refs):
    if cfg.has_residual:
        (x_ref, res_ref, gamma_ref, beta_ref,
         y_ref, mean_ref, var_ref,
         s_sum, s_sq, s_mu, s_rs) = refs
    else:
        res_ref = None
        (x_ref, gamma_ref, beta_ref,
         y_ref, mean_ref, var_ref,
         s_sum, s_sq, s_mu, s_rs) = refs

    p = pl.program_id(1)
    r = pl.program_id(2)

    @pl.when((p == 0) & (r == 0))
    def _init():
        s_sum[...] = jnp.zeros_like(s_sum)
        s_sq[...] = jnp.zeros_like(s_sq)

    @pl.when(p == 0)
    def _accumulate():
        xf = x_ref[...].astype(jnp.float32)
        s_sum[...] += jnp.sum(xf, axis=0, keepdims=True)
        s_sq[...] += jnp.sum(xf * xf, axis=0, keepdims=True)

    @pl.when((p == 1) & (r == 0))
    def _finalize():
        inv_n = 1.0 / float(n_rows)  # TRUE row count — padded rows are
        mu = s_sum[...] * inv_n      # zeros, so Σ is already exact
        var = jnp.maximum(s_sq[...] * inv_n - mu * mu, 0.0)
        s_mu[...] = mu
        s_rs[...] = jax.lax.rsqrt(var + cfg.eps)
        mean_ref[...] = mu
        var_ref[...] = var

    @pl.when(p == 1)
    def _normalize():
        xf = x_ref[...].astype(jnp.float32)
        mul = s_rs[...] * gamma_ref[...]
        y = (xf - s_mu[...]) * mul + beta_ref[...]
        if cfg.has_residual:
            y = y + res_ref[...].astype(jnp.float32)
        if cfg.relu:
            y = jnp.maximum(y, 0.0)
        y_ref[...] = y.astype(y_ref.dtype)


def _fwd_pallas(cfg: _Cfg, x2d, gamma32, beta32, residual2d):
    r, c = x2d.shape
    block_r, block_c, rp, cp = _tiles(r, c)
    grid = (cp // block_c, 2, rp // block_r)

    tile = pl.BlockSpec((block_r, block_c), lambda ci, p, ri: (ri, ci))
    chan = pl.BlockSpec((1, block_c), lambda ci, p, ri: (0, ci))

    inputs = [_pad2d(x2d, rp, cp)]
    in_specs = [tile]
    if cfg.has_residual:
        inputs.append(_pad2d(residual2d, rp, cp))
        in_specs.append(tile)
    inputs += [_pad_param(gamma32, cp, 1.0), _pad_param(beta32, cp, 0.0)]
    in_specs += [chan, chan]

    y, mean, var = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg, r),
        grid=grid,
        in_specs=in_specs,
        out_specs=[tile, chan, chan],
        out_shape=[
            jax.ShapeDtypeStruct((rp, cp), x2d.dtype),
            jax.ShapeDtypeStruct((1, cp), jnp.float32),
            jax.ShapeDtypeStruct((1, cp), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, block_c), jnp.float32)] * 4,
        compiler_params=_compiler_params(cfg.interpret),
        interpret=cfg.interpret,
    )(*inputs)
    return y[:r, :c], mean[0, :c], var[0, :c]


# ---------------------------------------------------------------------------
# backward kernel


def _bwd_kernel(cfg: _Cfg, n_rows: int, *refs):
    i = 0

    def nxt():
        nonlocal i
        ref = refs[i]
        i += 1
        return ref

    dy_ref, x_ref = nxt(), nxt()
    y_ref = nxt() if cfg.relu else None
    gamma_ref, mean_ref, rstd_ref = nxt(), nxt(), nxt()
    dx_ref = nxt()
    dres_ref = nxt() if cfg.has_residual else None
    dgamma_ref, dbeta_ref = nxt(), nxt()
    s_sg, s_sgx, s_c1, s_c2 = nxt(), nxt(), nxt(), nxt()

    p = pl.program_id(1)
    r = pl.program_id(2)

    def masked_g():
        g = dy_ref[...].astype(jnp.float32)
        if cfg.relu:
            # jax.nn.relu's subgradient convention: 0 at the kink
            g = jnp.where(y_ref[...] > 0, g, 0.0)
        return g

    @pl.when((p == 0) & (r == 0))
    def _init():
        s_sg[...] = jnp.zeros_like(s_sg)
        s_sgx[...] = jnp.zeros_like(s_sgx)

    @pl.when(p == 0)
    def _reduce():
        g = masked_g()
        xhat = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * rstd_ref[...]
        s_sg[...] += jnp.sum(g, axis=0, keepdims=True)
        s_sgx[...] += jnp.sum(g * xhat, axis=0, keepdims=True)

    @pl.when((p == 1) & (r == 0))
    def _finalize():
        # the two reductions ARE the param grads — no extra pass
        dbeta_ref[...] = s_sg[...]
        dgamma_ref[...] = s_sgx[...]
        inv_n = 1.0 / float(n_rows)
        s_c1[...] = s_sg[...] * inv_n
        s_c2[...] = s_sgx[...] * inv_n

    @pl.when(p == 1)
    def _dx():
        g = masked_g()
        xhat = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * rstd_ref[...]
        k = gamma_ref[...] * rstd_ref[...]
        dx = k * (g - s_c1[...] - xhat * s_c2[...])
        dx_ref[...] = dx.astype(dx_ref.dtype)
        if cfg.has_residual:
            # the residual branch sees dy post-ReLU-mask, pre-BN-chain
            dres_ref[...] = g.astype(dres_ref.dtype)


def _bwd_pallas(cfg: _Cfg, x2d, gamma32, y2d, mean, var, dy2d):
    r, c = x2d.shape
    block_r, block_c, rp, cp = _tiles(r, c)
    grid = (cp // block_c, 2, rp // block_r)

    tile = pl.BlockSpec((block_r, block_c), lambda ci, p, ri: (ri, ci))
    chan = pl.BlockSpec((1, block_c), lambda ci, p, ri: (0, ci))

    # identical to the forward's finalize: rstd = rsqrt(var+eps) on the
    # same f32 var, so x̂ in the backward is bitwise the forward's
    rstd = jax.lax.rsqrt(var + cfg.eps)

    inputs = [_pad2d(dy2d, rp, cp), _pad2d(x2d, rp, cp)]
    in_specs = [tile, tile]
    if cfg.relu:
        inputs.append(_pad2d(y2d, rp, cp))
        in_specs.append(tile)
    inputs += [
        _pad_param(gamma32, cp, 1.0),
        _pad_param(mean, cp, 0.0),
        _pad_param(rstd, cp, 1.0),
    ]
    in_specs += [chan, chan, chan]

    out_specs = [tile]
    out_shape = [jax.ShapeDtypeStruct((rp, cp), x2d.dtype)]
    if cfg.has_residual:
        out_specs.append(tile)
        out_shape.append(jax.ShapeDtypeStruct((rp, cp), jnp.dtype(cfg.res_dtype)))
    out_specs += [chan, chan]
    out_shape += [jax.ShapeDtypeStruct((1, cp), jnp.float32)] * 2

    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, cfg, r),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((1, block_c), jnp.float32)] * 4,
        compiler_params=_compiler_params(cfg.interpret),
        interpret=cfg.interpret,
    )(*inputs)
    if cfg.has_residual:
        dx, dres, dgamma, dbeta = outs
        dres = dres[:r, :c]
    else:
        dx, dgamma, dbeta = outs
        dres = None
    return dx[:r, :c], dgamma[0, :c], dbeta[0, :c], dres


# ---------------------------------------------------------------------------
# custom_vjp plumbing


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fusedbn_kernel(cfg: _Cfg, x2d, gamma32, beta32, residual2d):
    return _fwd_pallas(cfg, x2d, gamma32, beta32, residual2d)


def _fusedbn_fwd(cfg: _Cfg, x2d, gamma32, beta32, residual2d):
    y, mean, var = _fwd_pallas(cfg, x2d, gamma32, beta32, residual2d)
    return (y, mean, var), (x2d, gamma32, y, mean, var)


def _fusedbn_bwd(cfg: _Cfg, saved, cots):
    # mean/var are bookkeeping outputs (running-stats update); their
    # cotangents are dropped by contract — see module docstring
    dy, _dmean, _dvar = cots
    x2d, gamma32, y2d, mean, var = saved
    dx, dgamma, dbeta, dres = _bwd_pallas(cfg, x2d, gamma32, y2d, mean, var, dy)
    return dx, dgamma, dbeta, (dres if cfg.has_residual else None)


_fusedbn_kernel.defvjp(_fusedbn_fwd, _fusedbn_bwd)


# ---------------------------------------------------------------------------
# reference composition (impl="xla")


def _fusedbn_xla(x, gamma, beta, eps, relu, residual):
    """flax.linen.BatchNorm's exact train-mode op order (f32 fast-
    variance stats, f32 normalize, single trailing cast) + the stock
    block epilogue — bit-comparable to ``nn.BatchNorm`` + ``nn.relu``;
    differentiated by autodiff."""

    red = tuple(range(x.ndim - 1))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=red)
    mean2 = jnp.mean(xf * xf, axis=red)
    var = jnp.maximum(mean2 - mean * mean, 0.0)
    y = x - mean
    mul = jax.lax.rsqrt(var + eps)
    mul = mul * gamma
    y = y * mul
    y = y + beta
    # flax casts to the module dtype here; the functional contract is
    # "activation dtype in, activation dtype out"
    y = y.astype(x.dtype)
    if residual is not None:
        y = residual + y
    if relu:
        y = jax.nn.relu(y)
    return y, mean, var


# ---------------------------------------------------------------------------
# public entry point


#: (cfg, shape) classes already registered in the compile ledger —
#: one note per distinct Pallas lowering, however many times the
#: enclosing train step retraces
_noted_classes: set = set()


def _note_compile_class(cfg: _Cfg, shape, c: int) -> None:
    key = (cfg, tuple(int(s) for s in shape))
    if key in _noted_classes:
        return
    _noted_classes.add(key)
    from tf_operator_tpu.utils.costplane import default_costplane

    variant = "bn"
    if cfg.relu:
        variant += "+relu"
    if cfg.has_residual:
        variant += "+res"
    if cfg.interpret:
        variant += ",interpret"
    default_costplane.compiles.note(
        "ops.fused_batchnorm", trigger=variant,
        shapes=[f"x[{','.join(str(int(s)) for s in shape)}]", f"c={c}"],
    )


def fused_batchnorm(
    x: jax.Array,
    gamma: jax.Array,
    beta: jax.Array,
    *,
    eps: float = 1e-5,
    relu: bool = False,
    residual: Optional[jax.Array] = None,
    impl: str = "xla",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Train-mode BatchNorm over the last axis, with the block epilogue
    fused in: ``y = [relu]( [residual +] (x − μ)·rsqrt(σ²+eps)·γ + β )``.

    Returns ``(y, mean, var)`` — ``y`` in ``x.dtype``; ``mean``/``var``
    are the f32 batch moments for the caller's running-stats update
    and must stay OUT of differentiated objectives (their cotangents
    are dropped; see module docstring).

    ``impl`` is resolved by the CALLER (models/resnet.py maps "auto");
    an explicit "pallas"/"pallas-interpret" raises ValueError when the
    kernel cannot serve, never downgrades.
    """

    if impl not in FUSEDBN_IMPLS:
        raise ValueError(
            f"impl must be one of {FUSEDBN_IMPLS}, got {impl!r}"
        )
    if x.ndim < 2:
        raise ValueError(f"fused_batchnorm needs [..., C] input, got {x.shape}")
    c = x.shape[-1]
    if gamma.shape != (c,) or beta.shape != (c,):
        raise ValueError(
            f"gamma/beta must be [{c}] to match x {x.shape}, got "
            f"{gamma.shape}/{beta.shape}"
        )
    if residual is not None and residual.shape != x.shape:
        raise ValueError(
            f"residual shape {residual.shape} != x shape {x.shape}"
        )

    if impl == "xla":
        return _fusedbn_xla(x, gamma, beta, eps, relu, residual)

    interpret = impl == "pallas-interpret"
    ok, why = fusedbn_available(interpret=interpret)
    if not ok:
        raise ValueError(f"fused_batchnorm impl={impl!r} refused: {why}")

    cfg = _Cfg(
        eps=float(eps),
        relu=bool(relu),
        has_residual=residual is not None,
        interpret=interpret,
        res_dtype=None if residual is None else jnp.dtype(residual.dtype).name,
    )
    # ISSUE 20: each distinct (variant, 2D shape) class is one Pallas
    # lowering of the forward/backward pair.  The pallas_call compiles
    # inside whatever jit encloses this, so there is no call boundary
    # to time — register the class once (wall honestly 0.0) instead of
    # double-compiling to measure
    _note_compile_class(cfg, x.shape, c)
    x2d = x.reshape(-1, c)
    res2d = residual.reshape(-1, c) if residual is not None else None
    # params go through the kernel in f32 (stats dtype); the cast is
    # outside custom_vjp so autodiff transposes it back to param dtype
    y2d, mean, var = _fusedbn_kernel(
        cfg, x2d, gamma.astype(jnp.float32), beta.astype(jnp.float32), res2d
    )
    return y2d.reshape(x.shape), mean, var
