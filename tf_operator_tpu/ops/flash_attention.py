"""Pallas flash attention — the hot-op TPU kernel.

The reference's compute tier lives in the CUDA kernels inside the TF/
Horovod images its examples run (SURVEY.md §2a); the TPU-native
equivalent of that tier is a pallas kernel feeding the MXU.  This is
classic flash attention (online softmax, never materialising the
[Sq, Sk] score matrix):

- grid (batch, heads, Sq/block_q, Sk/block_k): pallas streams one
  (block_k, d) k/v block from HBM into VMEM per step (double-buffered
  by the pipeline), so VMEM use is O(block), not O(S);
- the running (max, denominator, accumulator) carry lives in VMEM
  scratch, persisted across the innermost k grid dimension, in fp32;
- causal: k blocks fully above the diagonal skip their compute via
  @pl.when (partially-masked diagonal blocks mask per element);
- bf16-friendly: matmuls run with preferred_element_type=float32.

Forward-only kernel: the VJP recomputes attention with the XLA fallback
(flash-style recompute — O(S) memory in the forward where it matters;
the backward matches ops.attention numerics exactly).

Dispatch: `attention()` picks flash when it applies (TPU backend, no
bias/mask, tile-aligned shapes) and falls back to
ops.attention.dot_product_attention otherwise.  pallas_call has no
GSPMD partitioning rule, so on a multi-device mesh the dispatcher wraps
the kernel in shard_map over (dp/fsdp → batch, tp → heads); meshes that
shard other attention dims fall back.  TPU_OPERATOR_FLASH=0 disables
the kernel globally.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.ops.attention import dot_product_attention

_NEG_INF = float(jnp.finfo(jnp.float32).min)
#: lane width — scratch carries are padded to full lanes
_LANES = 128


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_ref,
    l_ref,
    acc_ref,
    *,
    scale: float,
    causal: bool,
):
    qi = pl.program_id(2)
    ji = pl.program_id(3)
    nk = pl.num_programs(3)
    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]

    @pl.when(ji == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: blocks fully above the diagonal contribute nothing for
    # every row of this q block — skip their compute entirely
    needed = (ji * block_k < (qi + 1) * block_q) if causal else (ji >= 0)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ji * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(qpos >= kpos, logits, _NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ji == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-37)  # fully-masked rows divide safely
        o_ref[0, 0, :, :] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jax.Array:
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / (d**0.5)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(b, h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda bi, hi, qi, ji: (bi, hi, qi, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda bi, hi, qi, ji: (bi, hi, ji, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda bi, hi, qi, ji: (bi, hi, ji, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi, ji: (bi, hi, qi, 0)
        ),
        scratch_shapes=[
            # carries persist across the innermost (k) grid dimension
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v)


def _compiler_params(interpret: bool):
    if interpret:
        return None
    # batch/head/q-block programs are independent; only the k dimension
    # carries state and must stay sequential
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention over [B, H, S, D].  Sq % block_q == Sk % block_k
    == 0 required (dispatch checks this; call `attention` instead)."""

    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g):
    # flash-style recompute: no [Sq, Sk] scores saved from the forward;
    # the backward re-derives them through the XLA reference (numerics
    # identical to ops.attention)
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: dot_product_attention(q, k, v, causal=causal), q, k, v
    )
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def flash_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Flash over a multi-device mesh: shard_map over batch (dp, fsdp)
    and heads (tp) — attention is independent per (batch, head), so the
    per-shard kernel is exact.  Requires sp == ep == 1 (ring attention
    owns sp > 1)."""

    try:
        from jax import shard_map  # jax >= 0.8

        check_kw = {"check_vma": False}
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

        check_kw = {"check_rep": False}

    spec = P(("dp", "fsdp"), "tp", None, None)
    fn = shard_map(
        functools.partial(
            flash_attention,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            interpret=interpret,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **check_kw,
    )
    return fn(q, k, v)


def _mesh_flash_applicable(mesh: Optional[Mesh], q, k) -> Optional[str]:
    """"single" | "sharded" | None (= fall back to the XLA path)."""

    if mesh is None:
        # no mesh in a multi-device program: inputs may carry GSPMD
        # shardings pallas_call has no partitioning rule for — only the
        # XLA fallback is safe there
        return "single" if jax.device_count() == 1 else None
    if all(s == 1 for s in mesh.shape.values()):
        return "single"
    shape = dict(mesh.shape)
    if shape.get("sp", 1) != 1 or shape.get("ep", 1) != 1:
        return None  # seq/expert sharding: not this kernel's job
    batch_shards = shape.get("dp", 1) * shape.get("fsdp", 1)
    head_shards = shape.get("tp", 1)
    if q.shape[0] % batch_shards or q.shape[1] % head_shards:
        return None
    return "sharded"


def _flash_applicable(q, k, bias, mask, block_q, block_k) -> bool:
    if os.environ.get("TPU_OPERATOR_FLASH", "1") == "0":
        return False
    if bias is not None or mask is not None:
        return False
    if q.shape[-2] % block_q or k.shape[-2] % block_k:
        return False
    # the kernel targets the TPU backend; everything else takes the
    # XLA-fused reference path (the interpreter is for tests)
    return jax.default_backend() == "tpu"


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    bias: Optional[jax.Array] = None,
    mask: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Dispatching attention: pallas flash kernel when it applies, the
    XLA-fused reference otherwise.  Drop-in for dot_product_attention;
    pass the mesh so multi-device calls get the shard_map wrapper."""

    if _flash_applicable(q, k, bias, mask, block_q, block_k):
        mode = _mesh_flash_applicable(mesh, q, k)
        if mode == "single":
            return flash_attention(q, k, v, causal, block_q, block_k)
        if mode == "sharded":
            return flash_attention_sharded(
                q, k, v, mesh, causal, block_q, block_k
            )
    return dot_product_attention(q, k, v, causal=causal, bias=bias, mask=mask)
