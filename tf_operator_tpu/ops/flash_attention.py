"""Pallas flash attention — the hot-op TPU kernel.

The reference's compute tier lives in the CUDA kernels inside the TF/
Horovod images its examples run (SURVEY.md §2a); the TPU-native
equivalent of that tier is a pallas kernel feeding the MXU.  This is
classic flash attention (online softmax, never materialising the
[Sq, Sk] score matrix):

- grid (batch, heads, Sq/block_q, Sk/block_k): pallas streams one
  (block_k, d) k/v block from HBM into VMEM per step (double-buffered
  by the pipeline), so VMEM use is O(block), not O(S);
- the running (max, denominator, accumulator) carry lives in VMEM
  scratch, persisted across the innermost k grid dimension, in fp32;
- causal: k blocks fully above the diagonal skip their compute via
  @pl.when (partially-masked diagonal blocks mask per element);
- bf16-friendly: matmuls run with preferred_element_type=float32.

Training-complete: the custom VJP is backed by pallas backward kernels
(_flash_bwd_dq_kernel / _flash_bwd_dkv_kernel) that recompute the
softmax from the forward's saved row-logsumexp block by block — the
[Sq, Sk] score matrix never exists in either direction.  The primal
forward skips the lse write entirely; TPU_OPERATOR_FLASH_BWD=0 falls
back to an XLA-recompute VJP.

Dispatch: `attention()` picks flash when it applies (TPU backend, no
bias/mask, tile-aligned shapes) and falls back to
ops.attention.dot_product_attention otherwise.  pallas_call has no
GSPMD partitioning rule, so on a multi-device mesh the dispatcher wraps
the kernel in shard_map over (dp/fsdp → batch, tp → heads); meshes that
shard other attention dims fall back.

Env knobs — note the three-state semantics of TPU_OPERATOR_FLASH:
  unset / ""  auto: the measured seq crossover decides.  The floor is
              keyed to the kernel blocks in use (r5 block-autotune,
              window_out/wide-xover*.out): the default blocks are
              1024x1024 (the monotone autotune winner AND the VMEM
              ceiling), shrunk per-dim until they tile; 512-class and
              above win from seq 512 on both head dims (1.11-2.3x
              over XLA-fused, growing with seq), so their floor is
              512; shapes whose blocks shrank to 256 keep that class's
              measured floor (256 at head dim >= 128 where it still
              wins, 1024 at D=64 where XLA takes short seqs), and
              128x128 keeps 2048.
              TPU_OPERATOR_FLASH_MIN_SEQ overrides the floor.
  "0"         disable the kernel globally.
  any other   FORCE flash wherever it applies, crossover ignored.
              ** Semantics changed in r4: an explicit "1" used to be
              the documented default value and is now a force — configs
              that pinned TPU_OPERATOR_FLASH=1 get flash below the
              crossover where auto would take XLA. **
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.ops.attention import dot_product_attention, validate_window

_NEG_INF = float(jnp.finfo(jnp.float32).min)
#: lane width — scratch carries are padded to full lanes
_LANES = 128


def _causal_mask(logits, qi, ji, block_q, block_k, window=None):
    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    kpos = ji * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    visible = qpos >= kpos
    if window is not None:
        # sliding window: row i sees [i - window + 1, i]
        visible = jnp.logical_and(visible, qpos - kpos < window)
    return jnp.where(visible, logits, _NEG_INF)


def _block_needed(qi, ji, block_q, block_k, causal, window):
    """Whole-block visibility: skip blocks fully above the diagonal
    (causal) and, with a sliding window, blocks fully below the band.
    With banding the grid itself only spans the band; this predicate
    then just trims the clamped / overshooting edge blocks."""

    if not causal:
        return ji >= 0
    upper = ji * block_k < (qi + 1) * block_q
    if window is None:
        return upper
    lower = (ji + 1) * block_k - 1 >= qi * block_q - (window - 1)
    return jnp.logical_and(upper, lower)


def _kv_band_width(block_q: int, block_k: int, window: int, nk: int) -> int:
    """#k blocks a q block's window band can intersect (q-major grids).
    Tight when block_q % block_k == 0 (band alignment is then fixed);
    +1 slack otherwise."""

    n = (block_q - 1) // block_k + -(-(window - 1) // block_k) + 1
    if block_q % block_k:
        n += 1
    return min(nk, n)


def _q_band_width(block_q: int, block_k: int, window: int, nq: int) -> int:
    """#q blocks that can see a kv block (kv-major grid twin)."""

    n = (block_k + window - 2) // block_q + 1
    if block_k % block_q:
        n += 1
    return min(nq, n)


def _banded_kv_setup(sq: int, sk: int, block_q: int, block_k: int,
                     causal: bool, window, group: int):
    """Shared banding setup for the q-major grids (forward and dq):
    (n_band, banded, kv index map).  Forward and backward MUST use this
    one helper or their banding silently diverges."""

    nk = sk // block_k
    n_band = (
        _kv_band_width(block_q, block_k, window, nk)
        if (window is not None and causal)
        else nk
    )
    banded = n_band < nk
    if window is not None and causal and sq != sk:
        # banding derives k-block indices from q-block positions —
        # only meaningful for self-attention (and windowed
        # cross-attention has no defined semantics here anyway)
        raise ValueError(
            f"window attention requires Sq == Sk, got {sq} vs {sk}"
        )

    def kv_idx(bi, hi, qi, j):
        if banded:
            j = jnp.maximum(_fwd_band_ji(qi, j, n_band, block_q, block_k), 0)
        return (bi, hi // group, j, 0)

    return n_band, banded, kv_idx


def _fwd_band_ji(qi, j, nj, block_q: int, block_k: int):
    """Banded j → absolute k-block index: the band ends at the q
    block's diagonal; early slots may undershoot 0 (caller masks)."""

    hi_blk = ((qi + 1) * block_q - 1) // block_k
    return hi_blk - (nj - 1) + j


def _dkv_band_qi(ji, qb, block_q: int, block_k: int):
    """Banded per-head q slot → absolute q-block index (may overshoot
    nq-1; caller masks)."""

    return (ji * block_k) // block_q + qb


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    *rest,
    scale: float,
    causal: bool,
    with_lse: bool,
    window=None,
    banded: bool = False,
):
    if with_lse:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        m_ref, l_ref, acc_ref = rest
    qi = pl.program_id(2)
    j = pl.program_id(3)
    nj = pl.num_programs(3)
    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]
    # banded window grid: j indexes the band, ending at the diagonal
    # block — may undershoot 0 (masked out below)
    ji = _fwd_band_ji(qi, j, nj, block_q, block_k) if banded else j

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: blocks fully above the diagonal (and, with a window,
    # fully below the band) contribute nothing — skip their compute
    needed = _block_needed(qi, ji, block_q, block_k, causal, window)
    if banded:
        needed = jnp.logical_and(needed, ji >= 0)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            logits = _causal_mask(logits, qi, ji, block_q, block_k, window)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nj - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-37)  # fully-masked rows divide safely
        o_ref[0, 0, :, :] = (acc_ref[:] / l).astype(o_ref.dtype)
        if with_lse:
            # logsumexp per row, broadcast across the lane dim (the
            # public TPU flash kernels use the same 128-lane padding —
            # sublane→lane reshapes are not a TPU-friendly op)
            lse_ref[0, 0, :, :] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-37))


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
    with_lse: bool = False,
    window=None,
):
    """Forward kernel.  with_lse=True additionally returns the row
    logsumexp [B, H, Sq, LANES] (lane-broadcast) for the backward; the
    primal-only variant skips that HBM write entirely."""

    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / (d**0.5)
    # GQA: k/v may carry H/group heads — the BlockSpec index map points
    # every query head at its shared K/V head, so the repeat never
    # materialises anywhere (not even in VMEM: same block, re-fetched)
    if h % k.shape[1]:
        raise ValueError(f"q heads ({h}) must be a multiple of kv heads ({k.shape[1]})")
    group = h // k.shape[1]
    # banded grid: with a window (and causal) only the blocks that can
    # intersect a q block's band get DMA'd — k-dim grid shrinks from
    # S/block_k to O(window/block_k)
    n_band, banded, kv_idx = _banded_kv_setup(
        sq, sk, block_q, block_k, causal, window, group
    )
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, with_lse=with_lse,
        window=window, banded=banded,
    )
    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ji: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d), kv_idx)
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    out_specs = [q_spec]
    if with_lse:
        out_shape.append(jax.ShapeDtypeStruct((b, h, sq, _LANES), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, 1, block_q, _LANES), lambda bi, hi, qi, ji: (bi, hi, qi, 0))
        )
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(b, h, sq // block_q, n_band),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=out_specs,
        scratch_shapes=[
            # carries persist across the innermost (k) grid dimension
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v)
    return tuple(res) if with_lse else res[0]


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale: float, causal: bool, window=None, banded: bool = False,
):
    qi = pl.program_id(2)
    j = pl.program_id(3)
    nj = pl.num_programs(3)
    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]
    ji = _fwd_band_ji(qi, j, nj, block_q, block_k) if banded else j

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    needed = _block_needed(qi, ji, block_q, block_k, causal, window)
    if banded:
        needed = jnp.logical_and(needed, ji >= 0)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            logits = _causal_mask(logits, qi, ji, block_q, block_k, window)
        # p is the exact softmax (lse folds max+denominator): masked
        # entries give exp(-inf - lse) = 0
        p = jnp.exp(logits - lse_ref[0, 0, :, :1])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        ds = p * (dp - delta_ref[0, 0, :, :1])
        dq_acc[:] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nj - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, scale: float, causal: bool, nq: int, window=None,
    banded: bool = False, nq_total: int = 0,
):
    # grid (b, hkv, KV block, T): the innermost T dimension is
    # sequential and flattens (query-head-in-group, q block) — for MHA
    # T == n_q_blocks and this is the plain q loop; for GQA every query
    # head sharing this K/V head streams through before finalize.
    # dk/dv accumulate across all of T in VMEM scratch.  With a banded
    # window, the per-head q index spans only the blocks that can see
    # this kv block, offset from the block's own position.
    ji = pl.program_id(2)
    t = pl.program_id(3)
    nt = pl.num_programs(3)
    qb = t % nq  # banded (or plain) q index within the current head
    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]
    qi = _dkv_band_qi(ji, qb, block_q, block_k) if banded else qb

    @pl.when(t == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # causal: q blocks strictly above the diagonal (and, windowed,
    # fully below the band) see none of this kv block — skip
    needed = _block_needed(qi, ji, block_q, block_k, causal, window) if causal else (t >= 0)
    if banded:
        needed = jnp.logical_and(needed, qi <= nq_total - 1)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            logits = _causal_mask(logits, qi, ji, block_q, block_k, window)
        p = jnp.exp(logits - lse_ref[0, 0, :, :1])  # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # p^T @ do -> [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0, :, :1])
        # dk = scale * ds^T @ q_raw — q was loaded pre-scaled, so the
        # factor is already in the operand
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(t == nt - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, out, lse, g, causal: bool, block_q: int, block_k: int, interpret: bool,
    window=None,
):
    b, h, sq, d = q.shape
    # lane-broadcast the [B,H,Sq] row stats for the kernels (transient —
    # freed when the two pallas calls complete)
    lse = jnp.broadcast_to(lse[..., None], (b, h, sq, _LANES))
    # delta_i = rowsum(dO_i * O_i)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (b, h, sq, _LANES))
    return _flash_backward_blocks(
        q, k, v, g, lse, delta, causal, block_q, block_k, interpret, window=window
    )


def _flash_backward_blocks(
    q, k, v, g, lse, delta, causal: bool, block_q: int, block_k: int, interpret: bool,
    grad_dtype=None,
    window=None,
):
    """dq/dk/dv kernels against precomputed lane-broadcast row stats
    (lse, delta = rowsum(dO*O), both [B,H,Sq,LANES]).  Split out from
    `_flash_backward` so the ring backward can reuse the kernels with
    the GLOBAL row stats while feeding per-hop K/V blocks.

    grad_dtype: output dtype for the partials (default: input dtypes).
    The ring backward passes float32 so per-hop partials aren't
    quantized to bf16 before its cross-hop accumulation.

    GQA: k/v may carry H/group heads.  dq reads the shared K/V head via
    the BlockSpec index map; dk/dv come out at Hkv width natively — the
    kv-major grid's innermost dimension flattens (head-in-group,
    q-block) so every query head sharing a K/V head accumulates into
    the same VMEM scratch before finalize.  No repeat, no group-sum."""

    b, h, sq, d = q.shape
    hkv = k.shape[1]
    group = h // hkv
    sk = k.shape[2]
    scale = 1.0 / (d**0.5)
    dq_dt = grad_dtype or q.dtype
    dk_dt = grad_dtype or k.dtype
    dv_dt = grad_dtype or v.dtype

    n_band, banded, kv_idx = _banded_kv_setup(
        sq, sk, block_q, block_k, causal, window, group
    )
    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ji: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d), kv_idx)
    row_spec = pl.BlockSpec(
        (1, 1, block_q, _LANES), lambda bi, hi, qi, ji: (bi, hi, qi, 0)
    )
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, causal=causal, window=window,
            banded=banded,
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, dq_dt),
        grid=(b, h, sq // block_q, n_band),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    # kv-major grid over the Hkv heads; innermost dimension t flattens
    # (query-head-in-group, q-block): head = hi*group + t//nq, qi = t%nq.
    # With a banded window the per-head span shrinks to the q blocks
    # that can see this kv block.
    nq_total = sq // block_q
    nq_band = (
        _q_band_width(block_q, block_k, window, nq_total)
        if (window is not None and causal)
        else nq_total
    )
    banded_t = nq_band < nq_total
    nq = nq_band

    def q_idx(bi, hi, ji, t):
        head = hi * group + t // nq
        qb = t % nq
        if banded_t:
            qb = jnp.minimum(_dkv_band_qi(ji, qb, block_q, block_k), nq_total - 1)
        return (bi, head, qb, 0)

    q_spec_t = pl.BlockSpec((1, 1, block_q, d), q_idx)
    kv_spec_t = pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ji, t: (bi, hi, ji, 0))
    row_spec_t = pl.BlockSpec((1, 1, block_q, _LANES), q_idx)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, causal=causal, nq=nq, window=window,
            banded=banded_t, nq_total=nq_total,
        ),
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, dk_dt),
            jax.ShapeDtypeStruct(v.shape, dv_dt),
        ],
        grid=(b, hkv, sk // block_k, group * nq),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t, row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _compiler_params(interpret: bool):
    if interpret:
        return None
    # batch/head/q-block programs are independent; only the k dimension
    # carries state and must stay sequential
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_p(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
    window: Optional[int],
) -> jax.Array:
    """custom_vjp primal: concrete blocks only (the public wrapper
    resolves None dims before this point so _fwd/_bwd see the same
    values)."""

    validate_window(window, causal)
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret, window=window)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
    window: Optional[int] = None,
) -> jax.Array:
    """Flash attention over [B, H, S, D].  Sq % block_q == Sk % block_k
    == 0 required (dispatch checks this; call `attention` instead).
    ``block_q``/``block_k``: None (default) takes the measured-winner
    defaults (default_flash_blocks — 1024x1024, env-overridable), shrunk
    per-dim until they tile the sequence; explicit values are used
    exactly as given.
    ``window``: sliding-window local attention (requires causal) —
    the k grid dimension shrinks to the band (O(window/block_k) blocks
    per q block), so both FLOPs AND K/V DMA are O(S * window), not
    O(S^2).  Same banding in the backward kernels."""

    block_q, block_k = resolve_flash_blocks(
        block_q, block_k, q.shape[-2], k.shape[-2], head_dim=q.shape[-1]
    )
    return _flash_attention_p(q, k, v, causal, block_q, block_k, interpret, window)


def resolve_use_flash(use_flash, applicable: bool, why_not: str) -> bool:
    """Shared use_flash knob semantics for the sp attention schedules
    (ring/ulysses): None = auto (on the TPU backend, when the shapes
    tile, unless TPU_OPERATOR_FLASH=0); True validates applicability."""

    if use_flash is None:
        return (
            os.environ.get("TPU_OPERATOR_FLASH", "1") != "0"
            and jax.default_backend() == "tpu"
            and applicable
        )
    if use_flash and not applicable:
        raise ValueError(why_not)
    return use_flash


def _use_pallas_bwd() -> bool:
    # escape hatch back to the XLA-recompute VJP
    return os.environ.get("TPU_OPERATOR_FLASH_BWD", "1") != "0"


def _fwd(q, k, v, causal, block_q, block_k, interpret, window):
    validate_window(window, causal)
    if not _use_pallas_bwd():
        out = _flash_forward(q, k, v, causal, block_q, block_k, interpret, window=window)
        return out, (q, k, v, None, None)
    out, lse = _flash_forward(
        q, k, v, causal, block_q, block_k, interpret, with_lse=True, window=window
    )
    # residuals persist across the whole fwd→bwd window (× n_layers in
    # a stacked model): keep only one lane of the lane-broadcast lse;
    # the backward re-broadcasts transiently
    return out, (q, k, v, out, lse[..., 0])


def _bwd(causal, block_q, block_k, interpret, window, res, g):
    q, k, v, out, lse = res
    if lse is None:
        # XLA-recompute fallback (TPU_OPERATOR_FLASH_BWD=0): re-derives
        # the scores through the reference path — numerics identical to
        # ops.attention
        _, vjp = jax.vjp(
            lambda q, k, v: dot_product_attention(
                q, k, v, causal=causal, window=window
            ), q, k, v
        )
        return vjp(g)
    # pallas backward: dq then dk/dv, each streaming blocks and
    # recomputing p from (q, k, lse) in-kernel — O(block) memory, the
    # [Sq, Sk] score matrix never exists
    return _flash_backward(
        q, k, v, out, lse, g, causal, block_q, block_k, interpret, window=window
    )


_flash_attention_p.defvjp(_fwd, _bwd)


def flash_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
    window: Optional[int] = None,
) -> jax.Array:
    """Flash over a multi-device mesh: shard_map over batch (dp, fsdp)
    and heads (tp) — attention is independent per (batch, head), so the
    per-shard kernel is exact (the per-shard sequence is the full S, so
    None block dims resolve against the global shape).  Requires
    sp == ep == 1 (ring attention owns sp > 1)."""

    block_q, block_k = resolve_flash_blocks(
        block_q, block_k, q.shape[-2], k.shape[-2], head_dim=q.shape[-1]
    )

    from tf_operator_tpu.utils.jax_compat import shard_map_unchecked

    spec = P(("dp", "fsdp"), "tp", None, None)
    fn = shard_map_unchecked(
        functools.partial(
            flash_attention,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            interpret=interpret,
            window=window,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def _mesh_flash_applicable(mesh: Optional[Mesh], q, k) -> Optional[str]:
    """"single" | "sharded" | None (= fall back to the XLA path)."""

    if mesh is None:
        # no mesh in a multi-device program: inputs may carry GSPMD
        # shardings pallas_call has no partitioning rule for — only the
        # XLA fallback is safe there
        return "single" if jax.device_count() == 1 else None
    if all(s == 1 for s in mesh.shape.values()):
        return "single"
    shape = dict(mesh.shape)
    if shape.get("sp", 1) != 1 or shape.get("ep", 1) != 1:
        return None  # seq/expert sharding: not this kernel's job
    batch_shards = shape.get("dp", 1) * shape.get("fsdp", 1)
    head_shards = shape.get("tp", 1)
    if q.shape[0] % batch_shards or q.shape[1] % head_shards or k.shape[1] % head_shards:
        return None
    return "sharded"


def _flash_applicable(q, k, bias, mask, block_q, block_k, window=None) -> bool:
    raw = os.environ.get("TPU_OPERATOR_FLASH")
    if raw == "0":
        return False
    # ANY explicit non-"0" value forces the kernel (bypasses the seq
    # crossover below) — the sweeps set "1" to measure flash AT the
    # crossover shapes; unset means auto-dispatch.  Matches
    # resolve_use_flash's enabled/disabled reading of the same var (the
    # sp schedules have no crossover: their per-shard applicability
    # rules differ).
    forced = bool(raw)  # "" (cleared var) reads as unset/auto
    if bias is not None or mask is not None:
        return False
    if q.shape[-2] % block_q or k.shape[-2] % block_k or q.shape[1] % k.shape[1]:
        return False
    if window is not None and q.shape[-2] != k.shape[-2]:
        # banded grids need Sq == Sk; the XLA reference's position-based
        # window mask handles the cross-length case — route it there
        return False
    # Measured crossover, keyed to the blocks actually in use — each
    # tier's floor is the shortest seq where THOSE blocks were measured
    # to win or tie the XLA-fused reference fwd+bwd
    # (window_out/llama-sweep.out + wide-xover{,2,3,4,5,6}.out, r5):
    #   512-class blocks: win from seq 512 up, both head dims
    #     (mini s512 128.2k vs 115.5k XLA 1.11x, s1024 1.63x, s2048
    #     1.82x; wide s512 1.15x, s1024 1.30x, s4096 2.30x) → floor
    #     512;
    #   256-class blocks (a dim shrank): head-dim split — at D >= 128
    #     they WIN from seq 256 (wide s256 34.7k vs 31.5k XLA 1.10x;
    #     every mixed bk512 wide cell wins) → floor 256; at D < 128
    #     they LOSE short (mini s256 0.78x, s512 0.90x) and only tie
    #     at 1024 / win 1.06x at 2048 → floor 1024;
    #   128x128 (fully shrunk or pinned): lose 1.4x at 1024, win
    #     1.17x at 4096 (r4) → keep the old floor of 2048.
    # TPU_OPERATOR_FLASH_MIN_SEQ overrides the block-derived floor.
    raw_min = os.environ.get("TPU_OPERATOR_FLASH_MIN_SEQ")
    if raw_min:
        min_seq = int(raw_min)
    elif min(block_q, block_k) >= 512:
        min_seq = 512
    elif min(block_q, block_k) >= 256:
        min_seq = 256 if q.shape[-1] >= 128 else 1024
    else:
        min_seq = 2048
    if not forced and max(q.shape[-2], k.shape[-2]) < min_seq:
        return False
    # the kernel targets the TPU backend; everything else takes the
    # XLA-fused reference path (the interpreter is for tests)
    return jax.default_backend() == "tpu"


def default_flash_blocks() -> tuple:
    """Kernel block sizes used when the caller doesn't pick:
    TPU_OPERATOR_FLASH_BLOCK_Q / _BLOCK_K env overrides (the
    benchmarks/llama_sweep.py autotune matrices set these per variant),
    else 1024x1024 — the win is monotone in block size at EVERY
    measured training shape on both head dims, 128→256→512→1024
    (window_out/wide-xover*.out; fwd+bwd tok/s/chip at 1024 blocks vs
    the 512-block pass):
      mini D=64:  s1024 119.6k (+8%), s2048 101.6k (+9%),
                  s4096 82.9k (+19%)
      wide D=128: s1024 30.8k mfu 0.616 (+2%), s2048 29.1k (+3%),
                  s4096 25.4k mfu 0.566 (+7%)
    Bigger blocks = fewer grid steps, longer in-VMEM inner loops,
    fewer K/V re-streams.  1024 is also the VMEM ceiling: 2048-class
    blocks blow the 16 MB scoped-vmem limit (measured: pallas stack
    alloc 30.85M at D=64 s2048 — and that compile-helper OOM surfaces
    as the misleading "unexpected worker hostname" error).  Shapes
    that don't tile 1024 shrink per-dim to 512/256/128 in
    resolve_flash_blocks, keeping each class's measured floor."""

    return (
        int(os.environ.get("TPU_OPERATOR_FLASH_BLOCK_Q", "1024")),
        int(os.environ.get("TPU_OPERATOR_FLASH_BLOCK_K", "1024")),
    )


def resolve_flash_blocks(
    block_q: Optional[int],
    block_k: Optional[int],
    sq: int,
    sk: int,
    head_dim: Optional[int] = None,
) -> tuple:
    """Fill unpinned block dims from default_flash_blocks(), shrinking
    each BUILT-IN default per-dim (1024→512→256→128) until it tiles the
    given q/k sequence lengths.  Caller-pinned dims and BLOCK_Q/_K env
    pins are never adjusted (a sweep must measure exactly what it set).
    Used everywhere blocks default: `attention()` (whose auto-crossover
    then keys on the resolved blocks), the raw kernel entry points, and
    the sp schedules (ring/ulysses), which size blocks against their
    per-shard sequence.

    ``head_dim`` (ADVICE r5 #1): the 1024-class default sits AT the
    16 MB scoped-VMEM ceiling, measured only at D=64/128 — kernel
    block footprint scales with D, so a larger head dim would route an
    UNMEASURED config into a Pallas compile OOM (which this platform
    surfaces as the misleading "unexpected worker hostname" error, see
    default_flash_blocks) instead of the XLA fallback.  When the
    caller passes the head dim and it exceeds 128, the built-in
    default class is capped at 512 before sequence tiling; explicit
    pins (caller args / BLOCK env vars) are still taken exactly as
    given — a sweep probing big-D 1024 blocks measures what it set."""

    dq, dk = default_flash_blocks()
    cap = 512 if head_dim is not None and head_dim > 128 else None
    if block_q is None:
        if not os.environ.get("TPU_OPERATOR_FLASH_BLOCK_Q"):
            if cap is not None:
                while dq > cap:
                    dq //= 2
            while dq > 128 and sq % dq:
                dq //= 2
        block_q = dq
    if block_k is None:
        if not os.environ.get("TPU_OPERATOR_FLASH_BLOCK_K"):
            if cap is not None:
                while dk > cap:
                    dk //= 2
            while dk > 128 and sk % dk:
                dk //= 2
        block_k = dk
    return block_q, block_k


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    bias: Optional[jax.Array] = None,
    mask: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Dispatching attention: pallas flash kernel when it applies, the
    XLA-fused reference otherwise.  Drop-in for dot_product_attention;
    pass the mesh so multi-device calls get the shard_map wrapper."""

    # A BUILT-IN default block that doesn't tile the sequence shrinks
    # per-dim to one that does (floor 128) instead of silently losing
    # the kernel; pinned blocks — caller args AND the BLOCK_Q/_K env
    # knobs — are never adjusted (the sweep must measure exactly what
    # it set; a non-tiling pin falls back to XLA via
    # _flash_applicable).  The auto-crossover inside _flash_applicable
    # is keyed to the RESOLVED blocks, so shapes that shrank (or were
    # pinned) down to smaller blocks keep the higher seq floor those
    # blocks were measured at.
    block_q, block_k = resolve_flash_blocks(
        block_q, block_k, q.shape[-2], k.shape[-2], head_dim=q.shape[-1]
    )
    if _flash_applicable(q, k, bias, mask, block_q, block_k, window):
        mode = _mesh_flash_applicable(mesh, q, k)
        if mode == "single":
            return flash_attention(q, k, v, causal, block_q, block_k, window=window)
        if mode == "sharded":
            return flash_attention_sharded(
                q, k, v, mesh, causal, block_q, block_k, window=window
            )
    return dot_product_attention(
        q, k, v, causal=causal, bias=bias, mask=mask, window=window
    )
