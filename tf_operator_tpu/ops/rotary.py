"""Rotary position embeddings (RoPE), llama-style half-split rotation.

The reference has no model code at all (SURVEY.md §0 — it is a control
plane); this belongs to the framework's model zoo, where the modern
decoder families (llama-style) encode position by rotating q/k in the
complex plane instead of adding learned vectors.

Composition with sequence parallelism is free: RoPE is applied to the
GLOBAL [B, H, S, D] q/k right after projection, before attention
dispatches to ring/ulysses — positions are absolute indices, so XLA
simply shards the elementwise rotation along with the seq axis.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rope_sin_cos(
    positions: jax.Array,  # [S] (or any shape) absolute positions
    head_dim: int,
    theta: float = 10000.0,
) -> Tuple[jax.Array, jax.Array]:
    """sin/cos tables [*positions.shape, head_dim // 2], float32."""

    half = head_dim // 2
    freq = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * freq  # [..., half]
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,
    positions: Optional[jax.Array] = None,  # [S] absolute; default arange
    theta: float = 10000.0,
) -> Tuple[jax.Array, jax.Array]:
    """Rotate q and k by their positions (half-split convention: the
    vector is viewed as D/2 complex pairs (x[:D/2], x[D/2:]))."""

    d = q.shape[-1]
    if d % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {d}")
    if positions is None:
        positions = jnp.arange(q.shape[-2])
    sin, cos = rope_sin_cos(positions, d, theta)  # [S, D/2]

    def rot(x):
        x1, x2 = x[..., : d // 2], x[..., d // 2 :]
        xr = jnp.concatenate(
            (x1 * cos - x2 * sin, x2 * cos + x1 * sin), axis=-1
        )
        return xr.astype(x.dtype)

    return rot(q), rot(k)
