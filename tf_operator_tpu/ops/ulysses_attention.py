"""Ulysses attention: all-to-all sequence parallelism over the `sp` axis.

The second of the two standard long-context schedules (the reference has
no sequence-length story at all — SURVEY.md §2b calls it absent; ring
attention in ops/ring_attention.py is the first).  Where the ring keeps
the sequence sharded and rotates K/V blocks around the ICI ring, the
Ulysses schedule (DeepSpeed-Ulysses-style, re-derived here) re-shards
*heads* instead:

- Each sp shard holds Q/K/V for its contiguous sequence chunk, all
  (local) heads: ``[B, H, S/n, D]``.
- One ``lax.all_to_all`` per tensor switches the sharded dim from
  sequence to heads: every device ends up with the FULL sequence for
  ``H/n`` of the heads — attention is then embarrassingly parallel per
  head and runs locally (pallas flash kernel when shapes tile), with
  exact causal masking for free since the whole sequence is resident.
- One all-to-all on the output switches back to sequence sharding.

Trade-off vs the ring (why the framework ships both): Ulysses moves
4 fixed all-to-alls of O(B·H·S·D/n) per device regardless of the ring
size, while the ring pays n-1 neighbour hops of the K/V shard; Ulysses
wins when the interconnect does fast all-to-all (ICI within a slice)
and H ≥ n, but caps the sp degree at the head count and holds full-S
score rows per head, whereas the ring scales S without bound at O(S/n)
memory.  Gradients flow through plain autodiff: all_to_all is linear
(its transpose is the reverse all-to-all) and the local attention is
either the XLA reference or the pallas kernel with its custom VJP.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.ops.attention import (
    dot_product_attention,
    repeat_kv_heads as _rep_kv,
    validate_window,
)
from tf_operator_tpu.ops.flash_attention import (
    flash_attention,
    resolve_flash_blocks,
    resolve_use_flash,
)


def _ulysses_local(
    q: jax.Array,  # [B, Hl, Sl, D] — local heads, local seq chunk
    k: jax.Array,  # [B, Hkvl, Sl, D] (GQA: Hkvl may be Hl/group)
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
    use_flash: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
    group: int = 1,
    kv_native_a2a: bool = True,
    window=None,
) -> jax.Array:
    """Runs inside shard_map.  heads→seq re-shard, local attention,
    seq→heads re-shard back.  Window attention is free here: after the
    all-to-all every device holds the FULL sequence for its heads, so
    the banded kernels/mask apply unchanged.

    GQA: when the kv head count splits across the axis
    (kv_native_a2a), K/V ride the all-to-all at Hkv width — the
    h/hkv bandwidth saving — and feed the GQA-native local attention
    directly (no expansion anywhere); otherwise they expand before the
    re-shard (correct, no saving).  Autodiff handles both."""

    a2a = functools.partial(lax.all_to_all, axis_name=axis_name, tiled=True)
    # [B, Hl, Sl, D] -> [B, Hl/n, S, D]: give away head groups, collect
    # the full sequence for the heads we keep
    q = a2a(q, split_axis=1, concat_axis=2)
    if not kv_native_a2a:
        # kv heads don't split the axis: expand before the re-shard
        k, v = _rep_kv(k, group), _rep_kv(v, group)
    k, v = (a2a(t, split_axis=1, concat_axis=2) for t in (k, v))
    # both local attentions are GQA-native (grouped einsum / kernel
    # index maps), so native-width K/V go straight in
    if use_flash:
        o = flash_attention(q, k, v, causal, block_q, block_k, interpret, window=window)
    else:
        o = dot_product_attention(q, k, v, causal=causal, window=window)
    # [B, Hl/n, S, D] -> [B, Hl, Sl, D]
    return a2a(o, split_axis=2, concat_axis=1)


def _ulysses_applicable(heads_local: int, axis_size: int) -> bool:
    """The head dim per shard must split across the sp axis (at least
    one head per device — heads_local 0 means tp already over-shards)."""

    return heads_local >= axis_size and heads_local % axis_size == 0


def _flash_local_applicable(q: jax.Array, block_q: int, block_k: int) -> bool:
    # post-all-to-all the local view is the FULL sequence
    s, d = q.shape[-2], q.shape[-1]
    return s % block_q == 0 and s % block_k == 0 and d % 8 == 0


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    axis_name: str = "sp",
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    heads_axis: Optional[str] = "tp",
    use_flash: Optional[bool] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
    window: Optional[int] = None,
) -> jax.Array:
    """Exact attention with sequence sharded over `axis_name`, computed
    by the all-to-all (Ulysses) schedule.  Drop-in for `ring_attention`
    — same signature, same global [B, H, S, D] contract, same result.

    Constraint the ring does not have: the per-device head count
    (H / mesh[heads_axis]) must be divisible by mesh[axis_name].

    ``use_flash``: compute the local full-sequence attention with the
    pallas flash kernel.  None = auto: on the TPU backend when the
    full-sequence shapes tile the kernel blocks (TPU_OPERATOR_FLASH=0
    disables).
    """

    h, hkv = q.shape[1], k.shape[1]
    if h % hkv:
        raise ValueError(f"q heads ({h}) must be a multiple of kv heads ({hkv})")
    group = h // hkv
    validate_window(window, causal)

    if mesh.shape[axis_name] <= 1:
        return dot_product_attention(q, k, v, causal=causal, window=window)

    n = mesh.shape[axis_name]
    tp_size = mesh.shape.get(heads_axis, 1) if heads_axis else 1
    heads_local = h // tp_size
    if not _ulysses_applicable(heads_local, n):
        raise ValueError(
            f"ulysses_attention needs heads-per-shard divisible by the sp "
            f"axis: {heads_local} local heads over sp={n}; use "
            f"ring_attention for head counts that don't split"
        )
    if group > 1 and hkv % tp_size:
        # kv heads don't divide the tp axis: fall back to full width
        k, v = _rep_kv(k, group), _rep_kv(v, group)
        group, hkv = 1, h
    # K/V can ride the all-to-all at Hkv width only if their local
    # head count splits across the axis too
    kv_native_a2a = group == 1 or (hkv // tp_size) % n == 0

    # the local attention sees the FULL sequence (heads are what's
    # sharded here): size unpinned block dims against S, tuned defaults
    # shrunk until they tile
    block_q, block_k = resolve_flash_blocks(
        block_q, block_k, q.shape[-2], k.shape[-2], head_dim=q.shape[-1]
    )
    use_flash = resolve_use_flash(
        use_flash,
        _flash_local_applicable(q, block_q, block_k),
        f"use_flash=True but the full sequence {q.shape[-2]} / head dim "
        f"{q.shape[-1]} don't tile the kernel blocks ({block_q},{block_k})",
    )

    spec = P(batch_axes, heads_axis, axis_name, None)
    local = functools.partial(
        _ulysses_local,
        axis_name=axis_name,
        causal=causal,
        use_flash=use_flash,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        group=group,
        kv_native_a2a=kv_native_a2a,
        window=window,
    )
    from tf_operator_tpu.utils.jax_compat import shard_map_unchecked

    return shard_map_unchecked(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
